"""Model save/load + inference-model export
(reference ``python/paddle/fluid/io.py``: ``save_vars:66``,
``save_persistables:145``, ``load_persistables:234``,
``save_inference_model:298``, ``load_inference_model:383``).

Persistence runs THROUGH PROGRAMS, like the reference: ``save_vars`` /
``load_vars`` build a program of ``save``/``load`` IR ops (one per
variable, or a single ``save_combine``/``load_combine`` when ``filename``
is given) and execute it — so a startup-style program containing load ops
boots a scope, and exported models are runnable by ``native/capi.cpp``.
The on-disk tensor format is the versioned container of
``ops/persist_ops.py`` (replacing the reference's LoDTensor proto files
of ``save_op.cc``).  Sharded / multi-host checkpointing lives below
(orbax-style).
"""

from __future__ import annotations

import json
import os

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import Program, Parameter, Variable, default_main_program
from paddle_tpu.scope import global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "save_checkpoint", "load_checkpoint",
    "get_inference_program", "infer_feed_specs",
]


def is_persistable(var):
    return var.persistable


def is_parameter(var):
    return isinstance(var, Parameter)


def _var_path(dirname, name):
    return os.path.join(dirname, name.replace("/", "%2F"))


def _persist_program(vars, for_load):
    """A fresh program whose global block mirrors ``vars`` (persistable),
    ready to host save/load ops over them."""
    prog = Program()
    # persistence programs are host-op programs BY DESIGN (file IO);
    # the host-op-cliff warning is for unexpected training-path cliffs
    prog.expect_host_ops = True
    block = prog.global_block()
    for var in vars:
        v = block.create_var(name=var.name, shape=var.shape,
                             dtype=var.dtype)
        v.persistable = True
        if for_load:
            v.stop_gradient = True
    return prog, block


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference ``io.py:66``: build a program of ``save`` ops (or one
    ``save_combine``) over the selected variables and run it."""
    scope = global_scope()
    if vars is None:
        main_program = main_program or default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    vars = [v for v in vars if scope.find_var(v.name) is not None]
    os.makedirs(dirname, exist_ok=True)
    if filename is not None:
        # combined records carry no names — order is the contract, so
        # both ends sort by name (load_vars below does the same)
        vars = sorted(vars, key=lambda v: v.name)
    prog, block = _persist_program(vars, for_load=False)
    if filename is not None:
        if vars:
            block.append_op(
                type="save_combine",
                inputs={"X": [v.name for v in vars]}, outputs={},
                attrs={"file_path": os.path.join(dirname, filename)})
    else:
        for var in vars:
            block.append_op(
                type="save", inputs={"X": [var.name]}, outputs={},
                attrs={"file_path": _var_path(dirname, var.name)})
    if block.ops:
        executor.run(prog, feed={}, fetch_list=[])


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, None, is_persistable,
              filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """reference ``io.py`` load_vars: build a program of ``load`` ops (or
    one ``load_combine``) and run it to boot the scope."""
    if vars is None:
        main_program = main_program or default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    if filename is not None:
        path = os.path.join(dirname, filename)
        # the record order in the file is the contract; match the
        # program's vars BY RECORDED NAME so a var that was skipped at
        # save time (uninitialized) cannot shift every later assignment
        from paddle_tpu.ops.persist_ops import read_record_names
        recorded = read_record_names(path)
        by_name = {v.name: v for v in vars}
        if any(n is None for n in recorded):
            vars = sorted(vars, key=lambda v: v.name)  # legacy files
        else:
            missing = [n for n in recorded if n not in by_name]
            if missing:
                raise ValueError(
                    f"load_vars: {path!r} holds records for "
                    f"{missing[:3]}... not present in the program")
            vars = [by_name[n] for n in recorded]
        prog, block = _persist_program(vars, for_load=True)
        if vars:
            block.append_op(
                type="load_combine", inputs={},
                outputs={"Out": [v.name for v in vars]},
                attrs={"file_path": path})
            executor.run(prog, feed={}, fetch_list=[])
        return
    vars = [v for v in vars
            if os.path.exists(_var_path(dirname, v.name))]
    prog, block = _persist_program(vars, for_load=True)
    for var in vars:
        block.append_op(
            type="load", inputs={}, outputs={"Out": [var.name]},
            attrs={"file_path": _var_path(dirname, var.name)})
    if block.ops:
        executor.run(prog, feed={}, fetch_list=[])


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, None, is_parameter, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, None, is_persistable,
              filename)


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """reference ``io.py:298``: prune to targets, record feed/fetch, save
    params."""
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    main_program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)

    pruned = main_program.prune(target_vars)
    inference_program = pruned.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]

    model = {
        "program": inference_program.to_dict(),
        "feed_var_names": feeded_var_names,
        "fetch_var_names": fetch_var_names,
    }
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename), "w") as f:
        json.dump(model, f)
    # combined params by default: __model__ + __params__ is the whole
    # deployable artifact (runnable by serving.Predictor / native/capi.cpp)
    save_persistables(executor, dirname, inference_program,
                      params_filename or "__params__")
    return fetch_var_names


def synth_feed_value(shape, dtype):
    """Zero-filled feed array for a declared signature — the ONE
    materialization AOT warmup (``Executor.warmup``) and the synthetic
    profile/selfcheck feeds (``models.synth_feed``) share: bfloat16
    synthesizes as a jax array (numpy has no such dtype), everything
    else as numpy zeros."""
    shape = tuple(int(d) for d in shape)
    if str(dtype) == "bfloat16":
        import jax.numpy as jnp
        return jnp.zeros(shape, jnp.bfloat16)
    return np.zeros(shape, np.dtype(str(dtype)))


def infer_feed_specs(program, feed_names):
    """Declared feed signatures of an inference program: a dict
    ``name -> {"shape": tuple (None for dynamic dims), "dtype": str,
    "lod_level": int}`` — what a server needs to synthesize AOT-warmup
    batches (``Executor.warmup`` / ``serving.Predictor.warmup``) for the
    model's declared shapes without ever seeing a real request."""
    block = program.global_block()
    specs = {}
    for name in feed_names:
        var = block.var(name) if block.has_var(name) else None
        if var is None:
            specs[name] = {"shape": None, "dtype": "float32",
                           "lod_level": 0}
            continue
        shape = None
        if var.shape is not None:
            shape = tuple(None if d is None or int(d) < 0 else int(d)
                          for d in var.shape)
        specs[name] = {"shape": shape,
                       "dtype": var.dtype or "float32",
                       "lod_level": getattr(var, "lod_level", 0) or 0}
    return specs


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """reference ``io.py:383``. Returns (program, feed_names, fetch_vars)."""
    model_filename = model_filename or "__model__"
    with open(os.path.join(dirname, model_filename)) as f:
        model = json.load(f)
    program = Program.from_dict(model["program"])
    program._is_inference = True
    if params_filename is None and \
            os.path.exists(os.path.join(dirname, "__params__")):
        params_filename = "__params__"
    load_persistables(executor, dirname, program, params_filename)
    fetch_vars = [program.global_block().var(n)
                  for n in model["fetch_var_names"]]
    return program, model["feed_var_names"], fetch_vars


# ---------------------------------------------------------------------------
# checkpoint/resume: sharded (TP-aware) training-state checkpoints
# (SURVEY.md §5.4).  The reference checkpoints via save_op/load_op files
# + the Go pserver's CRC'd state (go/pserver/service.go:346); on TPU the
# state is a pytree of (possibly mesh-sharded) arrays, saved through orbax
# — each host writes only its addressable shards, so checkpoints scale to
# multi-host meshes without gathering.
# ---------------------------------------------------------------------------

def atomic_write(path, data):
    """Crash-safe small-file write (tmp + fsync + rename): a crash
    mid-write keeps the old file.  ``data`` may be str or bytes.  The
    shared idiom behind every pointer/bundle file the runtime commits
    (``latest``, ``last_good``, sentinel quarantine bundles).  The temp
    name is deterministic (single-writer protocol), so a crashed
    write's orphan is overwritten by the next attempt instead of
    accumulating per-pid litter."""
    tmp = f"{path}.tmp"
    with open(tmp, "wb" if isinstance(data, bytes) else "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _write_latest(dirname, step):
    atomic_write(os.path.join(dirname, "latest"), str(int(step)))


def snapshot_state(main_program=None, scope=None):
    """The persistable state a checkpoint captures, as a name -> array
    dict.  Values are the scope's live arrays (jax arrays are
    immutable; the executor REPLACES scope entries rather than mutating
    them), so the snapshot is a consistent point-in-time view that an
    async writer can serialize off the step path."""
    from paddle_tpu.framework import default_main_program
    from paddle_tpu.scope import global_scope

    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    state = {}
    for var in main_program.global_block().vars.values():
        if not is_persistable(var):
            continue
        v = scope.find_var(var.name)
        if v is None or not hasattr(v, "dtype"):
            continue
        state[var.name] = v
    return state


def save_checkpoint(executor, dirname, main_program=None, step=0,
                    scope=None, extras=None, mesh=None, shard_specs=None,
                    state=None):
    """Save ALL persistable state (params + optimizer accumulators) plus
    metadata; sharded arrays are written shard-by-shard (orbax).

    Crash-consistent: the state is written to a ``.tmp-`` dir, a
    checksummed ``MANIFEST.json`` is added, and only then is the dir
    atomically renamed to ``ckpt-<step>`` and the ``latest`` pointer
    swung — an interruption at any point leaves no partial ``ckpt-*``
    dir behind (``fault.checkpoint.commit_checkpoint``).

    ``extras``: optional ``filename -> bytes`` sidecar files (e.g. the
    serialized datapipe iterator state) written into the checkpoint dir
    BEFORE the commit, so they ride the same manifest/rename atomicity
    as the tensors.  EVERY host writes its own extras (names must be
    per-host unique in multi-host runs — each trainer's input-shard
    position is host-local state); a barrier then orders those writes
    before the coordinator's manifest walk.

    ``mesh``: switches to the ELASTIC per-shard format
    (``fault.shard_ckpt``): each var becomes one file per mesh shard
    (written concurrently, each host its owned shards), and the
    manifest gains a topology record so restore can re-map the
    checkpoint onto a *different* mesh.  ``shard_specs`` (name ->
    placement tuple, e.g. ``ZeroPlan.checkpoint_specs()``) names the
    vars partitioned over the mesh; everything else writes replicated.
    ``state``: a pre-snapshotted :func:`snapshot_state` dict — the
    async-save path captures it on the step path and writes later."""
    import shutil

    import jax

    from paddle_tpu.fault import chaos
    from paddle_tpu.fault.checkpoint import commit_checkpoint

    if state is None:
        state = snapshot_state(main_program, scope)
    os.makedirs(dirname, exist_ok=True)
    path = os.path.abspath(os.path.join(dirname, f"ckpt-{int(step)}"))
    # the temp path must be IDENTICAL on every host: orbax coordinates a
    # multi-host save over one shared directory, each host writing its
    # addressable shards into it.  Only the coordinator host commits
    # (manifest + rename + latest pointer), after orbax reports the
    # write finished on all hosts.
    tmp = os.path.abspath(os.path.join(dirname, f".tmp-ckpt-{int(step)}"))
    if jax.process_index() == 0 and os.path.exists(tmp):
        shutil.rmtree(tmp)
    chaos.fire("ckpt.save", step=step)
    from paddle_tpu.obs.trace import span as _span
    commit_extra = None
    with _span("ckpt.write", step=int(step), vars=len(state)):
        if mesh is not None:
            from paddle_tpu.fault import shard_ckpt
            os.makedirs(tmp, exist_ok=True)
            topology = shard_ckpt.build_topology(mesh, state,
                                                 shard_specs)
            shard_ckpt.write_state(tmp, state, topology, step=int(step))
            commit_extra = {"topology": topology}
        else:
            import orbax.checkpoint as ocp
            ckptr = ocp.StandardCheckpointer()
            ckptr.save(tmp, state, force=True)
            ckptr.wait_until_finished()
        for name, blob in (extras or {}).items():
            with open(os.path.join(tmp, name), "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
    if jax.process_count() > 1:
        # all hosts' extras must land before the coordinator manifests
        # the tmp dir — without this barrier a late host's sidecar file
        # would be missing from (or invalidate) the manifest
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(
            f"paddle_tpu.ckpt.extras.{int(step)}")
    commit_error = None
    if jax.process_index() == 0:
        try:
            commit_checkpoint(tmp, path, step=int(step),
                              extra=commit_extra)
            _write_latest(dirname, step)
        except BaseException as e:
            commit_error = e
    if jax.process_count() > 1:
        # barrier + commit-status broadcast: no host may observe
        # save_checkpoint() returning until the coordinator's commit
        # (manifest + rename + latest) is done — and a commit FAILURE
        # must raise on every host, not deadlock the others at a
        # barrier the coordinator never reaches
        from jax.experimental import multihost_utils
        ok = multihost_utils.broadcast_one_to_all(
            np.int32(0 if commit_error is not None else 1))
        if int(ok) != 1 and commit_error is None:
            raise RuntimeError(
                f"checkpoint commit for step {int(step)} failed on the "
                f"coordinator host")
    if commit_error is not None:
        raise commit_error
    return path


def load_checkpoint(executor, dirname, main_program=None, step=None,
                    scope=None, shardings=None, mesh=None):
    """Restore a checkpoint into the scope.  ``shardings``: optional map
    name -> jax.sharding.Sharding to restore arrays SHARDED onto a mesh
    (TP-aware resume); unlisted arrays load replicated/host-local.

    ``mesh``: the mesh the RESTORING run trains on.  For a shard-format
    checkpoint (manifest topology record) this is the elastic-resume
    path: ``fault.shard_ckpt.plan_restore`` maps the saved topology
    onto ``mesh`` — and statically verifies the plan — before any shard
    is read or device allocated, saved shards are re-sliced onto the
    new degree (a dp4 checkpoint restores on dp2, or dp8), and every
    array is placed with its planned ``NamedSharding``.  The scope is
    only mutated after EVERY var loaded cleanly — a failed restore
    leaves no half-restored state behind."""
    import orbax.checkpoint as ocp
    import jax

    from paddle_tpu.framework import default_main_program
    from paddle_tpu.scope import global_scope

    from paddle_tpu.fault import chaos

    main_program = main_program or default_main_program()
    scope = scope or global_scope()
    if step is None:
        with open(os.path.join(dirname, "latest")) as f:
            step = int(f.read().strip())
    path = os.path.abspath(os.path.join(dirname, f"ckpt-{int(step)}"))
    from paddle_tpu.fault import shard_ckpt
    manifest = shard_ckpt.read_manifest(path)
    topology = (manifest or {}).get("topology")
    if topology is not None:
        # elastic shard format: plan (and prove) BEFORE touching data
        plan = shard_ckpt.plan_restore(
            topology, mesh) if mesh is not None else None
        chaos.fire("ckpt.restore", step=int(step))
        state = shard_ckpt.read_state(path, topology)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            placed = {}
            for name, arr in state.items():
                spec = plan.get(name) or ()
                placed[name] = jax.device_put(
                    arr, NamedSharding(mesh, P(*spec)))
            state = placed
        elif shardings:
            state = {name: (jax.device_put(arr, shardings[name])
                            if name in shardings else arr)
                     for name, arr in state.items()}
        for name, value in state.items():
            scope.set_var(name, value)
        return int(step)
    # the restore boundary: a kill here (crash mid-rollback) must leave
    # the directory restorable by the next boot — restores never mutate
    # committed checkpoints, so the drill validates exactly that
    chaos.fire("ckpt.restore", step=int(step))
    ckptr = ocp.StandardCheckpointer()
    if shardings:
        meta = ckptr.metadata(path)
        # orbax returns a StepMetadata for dirs it renamed itself and a
        # raw name->ArrayMetadata tree for ours (committed via
        # fault.checkpoint.commit_checkpoint)
        meta = dict(meta) if isinstance(meta, dict) else \
            dict(meta.item_metadata.tree)
        targets = {}
        for name, m in meta.items():
            sh = shardings.get(name)
            if sh is not None:
                targets[name] = jax.ShapeDtypeStruct(m.shape, m.dtype,
                                                     sharding=sh)
            else:
                targets[name] = jax.ShapeDtypeStruct(m.shape, m.dtype)
        state = ckptr.restore(path, targets)
    else:
        state = ckptr.restore(path)
    for name, value in state.items():
        scope.set_var(name, value)
    return int(step)
