"""``python -m paddle_tpu`` — see paddle_tpu/cli.py."""

import sys

from paddle_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
