"""MNIST (reference ``python/paddle/dataset/mnist.py``): 28x28 grayscale
digits, normalized to [-1, 1], labels 0-9.  Reads the IDX files from the
local cache when present; otherwise yields deterministic synthetic digits
(class-dependent blob patterns so simple models actually converge)."""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test"]

TRAIN_IMAGE = "train-images-idx3-ubyte.gz"
TRAIN_LABEL = "train-labels-idx1-ubyte.gz"
TEST_IMAGE = "t10k-images-idx3-ubyte.gz"
TEST_LABEL = "t10k-labels-idx1-ubyte.gz"


def _read_idx(image_path, label_path):
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    with gzip.open(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    images = images.astype(np.float32) / 255.0 * 2.0 - 1.0
    return images, labels.astype(np.int64)


def _cached(image_name, label_name):
    d = os.path.join(common.DATA_HOME, "mnist")
    ip, lp = os.path.join(d, image_name), os.path.join(d, label_name)
    if os.path.exists(ip) and os.path.exists(lp):
        return _read_idx(ip, lp)
    return None


def _synthetic(split, n):
    rng = common.synthetic_rng("mnist", split)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    # class-dependent gaussian blob at a per-class location + noise
    xs = np.zeros((n, 784), dtype=np.float32)
    grid = np.stack(np.meshgrid(np.arange(28), np.arange(28),
                                indexing="ij"), -1).reshape(-1, 2)
    centers = np.stack([(7 + 4 * (k % 5), 7 + 9 * (k // 5))
                        for k in range(10)])
    for k in range(10):
        mask = labels == k
        cnt = int(mask.sum())
        if cnt == 0:
            continue
        d2 = np.sum((grid - centers[k]) ** 2, axis=1)
        blob = np.exp(-d2 / 20.0).astype(np.float32)
        xs[mask] = blob[None, :] + \
            rng.normal(0, 0.15, size=(cnt, 784)).astype(np.float32)
    xs = np.clip(xs, 0, 1) * 2.0 - 1.0
    return xs, labels


def _reader_creator(split, image_name, label_name, n_synth):
    def reader():
        data = _cached(image_name, label_name)
        if data is None:
            data = _synthetic(split, n_synth)
        images, labels = data
        for img, lbl in zip(images, labels):
            yield img, int(lbl)
    return reader


def train():
    return _reader_creator("train", TRAIN_IMAGE, TRAIN_LABEL, 8192)


def test():
    return _reader_creator("test", TEST_IMAGE, TEST_LABEL, 2048)


def fetch():
    pass
