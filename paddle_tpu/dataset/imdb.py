"""IMDB sentiment (reference ``python/paddle/dataset/imdb.py``): word-id
sequences + binary label.  Synthetic fallback: two vocab regions with
class-dependent frequencies."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "word_dict"]

_VOCAB = 5147  # close to the reference's cutoff vocab


def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic(split, n):
    rng = common.synthetic_rng("imdb", split)
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(20, 120))
        center = _VOCAB // 4 if label == 0 else 3 * _VOCAB // 4
        ids = np.clip(rng.normal(center, _VOCAB // 6, length).astype(int),
                      0, _VOCAB - 1)
        yield list(ids), label


def train(word_idx=None):
    def reader():
        yield from _synthetic("train", 2000)
    return reader


def test(word_idx=None):
    def reader():
        yield from _synthetic("test", 500)
    return reader


def fetch():
    pass
