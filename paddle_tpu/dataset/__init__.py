"""Datasets (reference ``python/paddle/dataset/``: mnist, cifar, imdb,
imikolov, movielens, conll05, uci_housing, wmt14/16, flowers, voc2012,
mq2007, sentiment — each downloads + caches + yields samples).

This environment has zero network egress, so each module first looks for a
local cache under ``$PADDLE_TPU_DATA_HOME`` (default ``~/.cache/paddle_tpu``)
in the reference's format and otherwise falls back to a deterministic
synthetic generator with the same sample shapes/vocab sizes, so models and
tests exercise identical code paths.
"""

from paddle_tpu.dataset import common
from paddle_tpu.dataset import mnist
from paddle_tpu.dataset import cifar
from paddle_tpu.dataset import uci_housing
from paddle_tpu.dataset import imdb
from paddle_tpu.dataset import imikolov
from paddle_tpu.dataset import movielens
from paddle_tpu.dataset import conll05
from paddle_tpu.dataset import wmt14
from paddle_tpu.dataset import wmt16
from paddle_tpu.dataset import flowers
from paddle_tpu.dataset import sentiment
from paddle_tpu.dataset import mq2007
from paddle_tpu.dataset import voc2012

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb", "imikolov",
           "movielens", "conll05", "wmt14", "wmt16", "flowers", "sentiment",
           "mq2007", "voc2012"]
