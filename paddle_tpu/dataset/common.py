"""Dataset plumbing (reference ``python/paddle/dataset/common.py``:
DATA_HOME cache, md5-checked download, ``cluster_files_reader``,
``convert``)."""

from __future__ import annotations

import hashlib
import os
import pickle
import glob
import shutil

import numpy as np

__all__ = ["DATA_HOME", "download", "md5file", "split", "cluster_files_reader",
           "convert", "synthetic_rng"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve a dataset file: local DATA_HOME cache first (md5-checked,
    reference ``dataset/common.py:download``); when the environment
    allows egress (``PADDLE_TPU_DATASET_ONLINE=1``) fetch + verify +
    cache like the reference; otherwise raise so callers fall back to
    their synthetic generators."""
    dirname = os.path.join(DATA_HOME, module_name)
    must_mkdirs(dirname)
    filename = os.path.join(
        dirname, url.split("/")[-1] if save_name is None else save_name)
    if os.path.exists(filename) and (not md5sum or
                                     md5file(filename) == md5sum):
        return filename
    if os.environ.get("PADDLE_TPU_DATASET_ONLINE"):
        import urllib.request
        tmp = filename + ".part"
        try:
            # stream with a connect/read timeout so a stalled connection
            # raises (and the caller falls back to the synthetic
            # generator) instead of hanging the resolver forever
            timeout = float(os.environ.get(
                "PADDLE_TPU_DATASET_TIMEOUT", "60"))
            with urllib.request.urlopen(url, timeout=timeout) as resp, \
                    open(tmp, "wb") as out_f:
                shutil.copyfileobj(resp, out_f)
            # a mid-body connection close returns normally from
            # copyfileobj; catch truncation before publishing (matters
            # when md5sum is falsy and the md5 gate below is skipped)
            want = resp.headers.get("Content-Length")
            if want is not None and os.path.getsize(tmp) != int(want):
                raise IOError(
                    f"truncated download of {url}: got "
                    f"{os.path.getsize(tmp)} of {want} bytes")
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)  # no stale partials in the cache
            raise
        if md5sum and md5file(tmp) != md5sum:
            os.remove(tmp)
            raise RuntimeError(
                f"md5 mismatch downloading {url} (expected {md5sum})")
        os.replace(tmp, filename)  # atomic publish into the cache
        return filename
    raise RuntimeError(
        f"dataset file {filename} not in local cache and downloads are "
        f"disabled (set PADDLE_TPU_DATASET_ONLINE=1 to fetch); synthetic "
        f"fallback will be used")


def synthetic_rng(module_name, split_name="train"):
    """Deterministic per-dataset RNG for synthetic fallbacks."""
    seed = int(hashlib.md5(
        f"{module_name}/{split_name}".encode()).hexdigest()[:8], 16)
    return np.random.RandomState(seed)


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """reference common.py split: chunk a reader into pickle files."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
                lines = []
                indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """reference common.py: each trainer reads its modulo-slice of files."""

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_file_list = [fn for i, fn in enumerate(file_list)
                        if i % trainer_count == trainer_id]
        for fn in my_file_list:
            with open(fn, "rb") as f:
                lines = loader(f)
                for line in lines:
                    yield line
    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Convert a reader to recordio files (reference common.py convert)."""
    from paddle_tpu.recordio_writer import RecordIOWriter
    indx_f = 0
    lines = []

    def write_data(indx_f, lines):
        filename = "%s/%s-%05d" % (output_path, name_prefix, indx_f)
        with RecordIOWriter(filename) as writer:
            for l in lines:
                writer.write(pickle.dumps(l))

    for i, d in enumerate(reader()):
        lines.append(d)
        if i % line_count == 0 and i >= line_count:
            write_data(indx_f, lines)
            lines = []
            indx_f += 1
    if lines:
        write_data(indx_f, lines)
