"""Oxford-102 flowers (reference ``python/paddle/dataset/flowers.py``):
3x224x224 images, 102 classes.  Synthetic fallback."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "valid"]


def _synthetic(split, n, use_xmap):
    rng = common.synthetic_rng("flowers", split)
    base = rng.normal(0, 1, size=(102, 12)).astype(np.float32)
    for _ in range(n):
        label = int(rng.randint(0, 102))
        # low-rank image: class signature outer product + noise
        u = base[label].reshape(12, 1, 1)
        img = (np.broadcast_to(u, (12, 224, 224)).reshape(
            3, 4, 224, 224).mean(axis=1) * 0.25 + 0.5)
        img = img + rng.normal(0, 0.1, size=(3, 224, 224))
        yield np.clip(img, 0, 1).astype(np.float32).flatten(), label


def train(mapper=None, buffered_size=1024, use_xmap=True):
    def reader():
        yield from _synthetic("train", 512, use_xmap)
    return reader


def test(mapper=None, buffered_size=1024, use_xmap=True):
    def reader():
        yield from _synthetic("test", 128, use_xmap)
    return reader


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    def reader():
        yield from _synthetic("valid", 128, use_xmap)
    return reader


def fetch():
    pass
