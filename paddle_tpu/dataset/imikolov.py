"""PTB-style n-gram LM data (reference ``python/paddle/dataset/imikolov.py``
builds n-grams for word2vec).  Synthetic fallback: Markov-chain text."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "build_dict"]

N_WORDS = 2073  # reference vocab ~2073 after min-freq cut


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(N_WORDS)}


def _synthetic_sentences(split, n_sent):
    rng = common.synthetic_rng("imikolov", split)
    # sparse Markov transitions give learnable structure
    next_words = rng.randint(0, N_WORDS, size=(N_WORDS, 4))
    for _ in range(n_sent):
        length = int(rng.randint(6, 25))
        w = int(rng.randint(0, N_WORDS))
        sent = [w]
        for _ in range(length - 1):
            w = int(next_words[w, rng.randint(0, 4)])
            sent.append(w)
        yield sent


def train(word_idx=None, n=5, data_type=1):
    def reader():
        for sent in _synthetic_sentences("train", 2000):
            if len(sent) >= n:
                sent_arr = np.asarray(sent)
                for i in range(n - 1, len(sent)):
                    yield tuple(sent_arr[i - n + 1:i + 1])
    return reader


def test(word_idx=None, n=5, data_type=1):
    def reader():
        for sent in _synthetic_sentences("test", 400):
            if len(sent) >= n:
                sent_arr = np.asarray(sent)
                for i in range(n - 1, len(sent)):
                    yield tuple(sent_arr[i - n + 1:i + 1])
    return reader


def fetch():
    pass
