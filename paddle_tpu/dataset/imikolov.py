"""PTB-style n-gram LM data (reference ``python/paddle/dataset/imikolov.py``
builds n-grams for word2vec).  Synthetic fallback: Markov-chain text."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "build_dict"]

N_WORDS = 2073  # reference vocab ~2073 after min-freq cut


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(N_WORDS)}


def _synthetic_sentences(split, n_sent):
    rng = common.synthetic_rng("imikolov", split)
    # Zipfian unigrams (like real text) + skewed sparse Markov transitions:
    # the unigram prior alone is worth ~2 nats over uniform, and the
    # dominant successor carries most of the conditional mass, so both are
    # learnable at book-test scale.
    zipf_p = 1.0 / (np.arange(N_WORDS) + 10.0)
    zipf_p /= zipf_p.sum()
    next_words = rng.choice(N_WORDS, size=(N_WORDS, 4), p=zipf_p)
    probs = np.asarray([0.7, 0.15, 0.1, 0.05])
    for _ in range(n_sent):
        length = int(rng.randint(6, 25))
        w = int(rng.choice(N_WORDS, p=zipf_p))
        sent = [w]
        for _ in range(length - 1):
            w = int(next_words[w, rng.choice(4, p=probs)])
            sent.append(w)
        yield sent


def train(word_idx=None, n=5, data_type=1):
    def reader():
        for sent in _synthetic_sentences("train", 2000):
            if len(sent) >= n:
                sent_arr = np.asarray(sent)
                for i in range(n - 1, len(sent)):
                    yield tuple(sent_arr[i - n + 1:i + 1])
    return reader


def test(word_idx=None, n=5, data_type=1):
    def reader():
        for sent in _synthetic_sentences("test", 400):
            if len(sent) >= n:
                sent_arr = np.asarray(sent)
                for i in range(n - 1, len(sent)):
                    yield tuple(sent_arr[i - n + 1:i + 1])
    return reader


def fetch():
    pass
