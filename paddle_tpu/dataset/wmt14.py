"""WMT-14 fr->en (reference ``python/paddle/dataset/wmt14.py``):
(src_ids, trg_ids, trg_next_ids) with <s>/<e>/<unk>.  Synthetic fallback:
invertible toy translation (target = f(source tokens))."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "get_dict"]

dict_size = 30000
START = 0  # <s>
END = 1    # <e>
UNK = 2    # <unk>


def get_dict(dict_size=dict_size, reverse=False):
    src_dict = {f"s{i}": i for i in range(dict_size)}
    trg_dict = {f"t{i}": i for i in range(dict_size)}
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict


def _synthetic(split, n, dict_size):
    rng = common.synthetic_rng("wmt14", split)
    for _ in range(n):
        length = int(rng.randint(4, 20))
        src = rng.randint(3, dict_size, length).tolist()
        # deterministic "translation": shifted tokens, reversed order
        trg = [3 + ((t + 7) % (dict_size - 3)) for t in reversed(src)]
        trg_in = [START] + trg
        trg_next = trg + [END]
        yield src, trg_in, trg_next


def train(dict_size=dict_size):
    def reader():
        yield from _synthetic("train", 2000, dict_size)
    return reader


def test(dict_size=dict_size):
    def reader():
        yield from _synthetic("test", 400, dict_size)
    return reader


def fetch():
    pass
