"""Pascal VOC2012 segmentation (reference
``python/paddle/dataset/voc2012.py``): (image, segmentation-label) pairs.
Synthetic fallback: colored rectangles with matching masks."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "val"]

_N_CLASSES = 21
_H = _W = 128


def _synthetic(split, n):
    rng = common.synthetic_rng("voc2012", split)
    for _ in range(n):
        img = rng.normal(0.5, 0.1, size=(3, _H, _W)).astype(np.float32)
        label = np.zeros((_H, _W), dtype=np.int32)
        for _ in range(int(rng.randint(1, 4))):
            cls = int(rng.randint(1, _N_CLASSES))
            x0, y0 = rng.randint(0, _H // 2), rng.randint(0, _W // 2)
            h, w = rng.randint(16, _H // 2), rng.randint(16, _W // 2)
            label[x0:x0 + h, y0:y0 + w] = cls
            img[:, x0:x0 + h, y0:y0 + w] += cls / _N_CLASSES - 0.5
        yield np.clip(img, 0, 1), label


def train():
    def reader():
        yield from _synthetic("train", 256)
    return reader


def test():
    def reader():
        yield from _synthetic("test", 64)
    return reader


def val():
    def reader():
        yield from _synthetic("val", 64)
    return reader


def fetch():
    pass
