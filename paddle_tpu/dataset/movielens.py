"""MovieLens-1M (reference ``python/paddle/dataset/movielens.py``):
(user, gender, age, job, movie, category, title) -> rating.  Synthetic
fallback with latent-factor structure."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "movie_categories"]

_N_USERS = 6040
_N_MOVIES = 3952
_N_JOBS = 21
age_table = [1, 18, 25, 35, 45, 50, 56]
_CATEGORIES = ["Action", "Adventure", "Animation", "Children's", "Comedy",
               "Crime", "Documentary", "Drama", "Fantasy", "Film-Noir",
               "Horror", "Musical", "Mystery", "Romance", "Sci-Fi",
               "Thriller", "War", "Western"]


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {c: i for i, c in enumerate(_CATEGORIES)}


def _synthetic(split, n):
    rng = common.synthetic_rng("movielens", split)
    u_fac = rng.normal(0, 1, size=(_N_USERS + 1, 8))
    m_fac = rng.normal(0, 1, size=(_N_MOVIES + 1, 8))
    for _ in range(n):
        u = int(rng.randint(1, _N_USERS + 1))
        m = int(rng.randint(1, _N_MOVIES + 1))
        gender = int(rng.randint(0, 2))
        age = int(rng.randint(0, len(age_table)))
        job = int(rng.randint(0, _N_JOBS))
        cats = list(rng.choice(len(_CATEGORIES),
                               size=int(rng.randint(1, 4)), replace=False))
        title = list(rng.randint(0, 5175, size=int(rng.randint(1, 6))))
        score = float(np.clip(
            3.0 + u_fac[u] @ m_fac[m] / 4.0 + rng.normal(0, 0.3), 1, 5))
        yield [u, gender, age, job, m, cats, title, score]


def train():
    def reader():
        yield from _synthetic("train", 4000)
    return reader


def test():
    def reader():
        yield from _synthetic("test", 800)
    return reader


def fetch():
    pass
