"""UCI housing (reference ``python/paddle/dataset/uci_housing.py``):
13 normalized features -> price.  Synthetic fallback: linear model +
noise, so fit-a-line converges to a known solution."""

from __future__ import annotations

import os

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test"]

feature_names = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS",
                 "RAD", "TAX", "PTRATIO", "B", "LSTAT"]


def _load():
    path = os.path.join(common.DATA_HOME, "uci_housing", "housing.data")
    if os.path.exists(path):
        data = np.fromfile(path, sep=" ").reshape(-1, 14)
        maximums = data.max(axis=0)
        minimums = data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(13):
            data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
        return data.astype(np.float32)
    rng = common.synthetic_rng("uci_housing")
    n = 506
    x = rng.normal(0, 0.3, size=(n, 13)).astype(np.float32)
    w = np.linspace(-2, 2, 13).astype(np.float32)
    y = x @ w + 3.0 + rng.normal(0, 0.1, n).astype(np.float32)
    return np.concatenate([x, y[:, None]], axis=1)


def train():
    def reader():
        data = _load()
        split = int(data.shape[0] * 0.8)
        for row in data[:split]:
            yield row[:-1], row[-1:]
    return reader


def test():
    def reader():
        data = _load()
        split = int(data.shape[0] * 0.8)
        for row in data[split:]:
            yield row[:-1], row[-1:]
    return reader


def fetch():
    pass
