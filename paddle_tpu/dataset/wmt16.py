"""WMT-16 en<->de with BPE (reference ``python/paddle/dataset/wmt16.py``)."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "validation", "get_dict"]


def get_dict(lang, dict_size, reverse=False):
    d = {f"{lang}{i}": i for i in range(dict_size)}
    if reverse:
        d = {v: k for k, v in d.items()}
    return d


def _synthetic(split, n, src_dict_size, trg_dict_size):
    rng = common.synthetic_rng("wmt16", split)
    for _ in range(n):
        length = int(rng.randint(4, 24))
        src = rng.randint(3, src_dict_size, length).tolist()
        trg = [3 + ((t * 3 + 11) % (trg_dict_size - 3)) for t in src]
        yield src, [0] + trg, trg + [1]


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    def reader():
        yield from _synthetic("train", 2000, src_dict_size, trg_dict_size)
    return reader


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    def reader():
        yield from _synthetic("test", 400, src_dict_size, trg_dict_size)
    return reader


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    def reader():
        yield from _synthetic("val", 400, src_dict_size, trg_dict_size)
    return reader


def fetch():
    pass
