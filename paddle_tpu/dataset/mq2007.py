"""MQ2007 learning-to-rank (reference ``python/paddle/dataset/mq2007.py``):
query-grouped 46-dim feature vectors with relevance labels; pairwise /
listwise / pointwise readers."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test"]

_N_FEATURES = 46


def _synthetic_queries(split, n_queries):
    rng = common.synthetic_rng("mq2007", split)
    w = rng.normal(0, 1, _N_FEATURES)
    for _ in range(n_queries):
        n_docs = int(rng.randint(5, 20))
        feats = rng.normal(0, 1, size=(n_docs, _N_FEATURES)).astype(
            np.float32)
        scores = feats @ w + rng.normal(0, 0.5, n_docs)
        rel = np.digitize(scores, np.percentile(scores, [50, 80]))
        yield feats, rel.astype(np.int64)


def _pairwise(split, n_queries):
    for feats, rel in _synthetic_queries(split, n_queries):
        order = np.argsort(-rel)
        for i in range(len(order)):
            for j in range(i + 1, len(order)):
                if rel[order[i]] > rel[order[j]]:
                    yield 1.0, feats[order[i]], feats[order[j]]


def _listwise(split, n_queries):
    for feats, rel in _synthetic_queries(split, n_queries):
        yield feats, rel


def _pointwise(split, n_queries):
    for feats, rel in _synthetic_queries(split, n_queries):
        for f, r in zip(feats, rel):
            yield f, float(r)


def train(format="pairwise"):
    def reader():
        if format == "pairwise":
            yield from _pairwise("train", 120)
        elif format == "listwise":
            yield from _listwise("train", 120)
        else:
            yield from _pointwise("train", 120)
    return reader


def test(format="pairwise"):
    def reader():
        if format == "pairwise":
            yield from _pairwise("test", 30)
        elif format == "listwise":
            yield from _listwise("test", 30)
        else:
            yield from _pointwise("test", 30)
    return reader


def fetch():
    pass
