"""CoNLL-2005 SRL (reference ``python/paddle/dataset/conll05.py``):
(word, ctx_n2..ctx_p2, verb, mark) sequences -> IOB label sequence.
Synthetic fallback with verb-anchored label structure."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["test", "get_dict", "get_embedding", "word_dict_len",
           "label_dict_len", "pred_dict_len"]

word_dict_len = 44068
label_dict_len = 59
pred_dict_len = 3162


def get_dict():
    word_dict = {f"w{i}": i for i in range(word_dict_len)}
    verb_dict = {f"v{i}": i for i in range(pred_dict_len)}
    label_dict = {f"l{i}": i for i in range(label_dict_len)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = common.synthetic_rng("conll05", "emb")
    return rng.normal(0, 0.1, size=(word_dict_len, 32)).astype(np.float32)


def _synthetic(split, n):
    rng = common.synthetic_rng("conll05", split)
    for _ in range(n):
        length = int(rng.randint(5, 40))
        words = rng.randint(0, word_dict_len, length).tolist()
        verb_pos = int(rng.randint(0, length))
        verb = int(rng.randint(0, pred_dict_len))
        mark = [1 if i == verb_pos else 0 for i in range(length)]

        def ctx(offset):
            idx = min(max(verb_pos + offset, 0), length - 1)
            return [words[idx]] * length

        labels = []
        for i in range(length):
            d = abs(i - verb_pos)
            labels.append(int(min(d, 2) * 19 + rng.randint(0, 19)) %
                          label_dict_len)
        yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
               [verb] * length, mark, labels)


def test():
    def reader():
        yield from _synthetic("test", 400)
    return reader


def train():
    def reader():
        yield from _synthetic("train", 1600)
    return reader


def fetch():
    pass
