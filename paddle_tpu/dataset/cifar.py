"""CIFAR-10/100 (reference ``python/paddle/dataset/cifar.py``): 3x32x32
images scaled to [0,1].  Synthetic fallback keyed by class."""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train10", "test10", "train100", "test100"]


def _tar_reader(path, sub_name):
    def reader():
        with tarfile.open(path, mode="r") as f:
            names = [n for n in f.getnames() if sub_name in n]
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch[b"data"]
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                for s, l in zip(data, labels):
                    yield s.astype(np.float32) / 255.0, int(l)
    return reader


def _synthetic_reader(split, num_classes, n):
    def reader():
        rng = common.synthetic_rng(f"cifar{num_classes}", split)
        labels = rng.randint(0, num_classes, size=n)
        base = rng.normal(0, 1, size=(num_classes, 3072)).astype(np.float32)
        for i in range(n):
            img = base[labels[i]] * 0.3 + \
                rng.normal(0, 0.2, 3072).astype(np.float32) + 0.5
            yield np.clip(img, 0, 1), int(labels[i])
    return reader


def _creator(fname, sub_name, split, num_classes, n_synth):
    path = os.path.join(common.DATA_HOME, "cifar", fname)
    if os.path.exists(path):
        return _tar_reader(path, sub_name)
    return _synthetic_reader(split, num_classes, n_synth)


def train10():
    return _creator("cifar-10-python.tar.gz", "data_batch", "train", 10, 4096)


def test10():
    return _creator("cifar-10-python.tar.gz", "test_batch", "test", 10, 1024)


def train100():
    return _creator("cifar-100-python.tar.gz", "train", "train", 100, 4096)


def test100():
    return _creator("cifar-100-python.tar.gz", "test", "test", 100, 1024)


def fetch():
    pass
