"""Movie-review sentiment (reference ``python/paddle/dataset/sentiment.py``
over NLTK movie_reviews).  Synthetic fallback mirrors imdb."""

from __future__ import annotations

import numpy as np

from paddle_tpu.dataset import common

__all__ = ["train", "test", "get_word_dict"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 3000


def get_word_dict():
    return [(f"w{i}", i) for i in range(_VOCAB)]


def _synthetic(split, n):
    rng = common.synthetic_rng("sentiment", split)
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(10, 60))
        center = _VOCAB // 4 if label == 0 else 3 * _VOCAB // 4
        ids = np.clip(rng.normal(center, _VOCAB // 5, length).astype(int),
                      0, _VOCAB - 1)
        yield list(ids), label


def train():
    def reader():
        yield from _synthetic("train", NUM_TRAINING_INSTANCES)
    return reader


def test():
    def reader():
        yield from _synthetic("test",
                              NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES)
    return reader


def fetch():
    pass
