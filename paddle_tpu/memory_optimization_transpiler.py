"""Memory-optimization pass: liveness analysis over the Program IR.

Reference: ``python/paddle/fluid/memory_optimization_transpiler.py``
(``ControlFlowGraph:40`` liveness, ``memory_optimize:332`` in-place var
reuse, ``release_memory:340`` early frees via delete_var ops).

TPU re-design.  Inside a compiled block, XLA's buffer assignment already
performs liveness-based reuse — re-pointing VarDescs at shared buffers (the
reference's rewrite) would be redundant there.  What the pass contributes
on TPU:

* a **reuse plan + report** (`MemoryPlan`): per-op live-set byte curve,
  the peak with and without reuse, and the var→var reuse pairs XLA is
  entitled to make — the observability artifact the reference prints;
* **early release** in the executor's op-by-op interpret mode (host ops /
  CSP blocks): env entries whose last use has passed are dropped after
  each op, cutting real peak memory exactly like the reference's
  ``delete_var`` ops (`release_memory`);
* **donation hints**: feed names whose buffers die inside the step are
  recorded so callers can donate them;
* the **donation/aliasing planner** (:func:`plan_donation`, the
  ``donation_plan`` pass of ``analysis/opt``): the ``stateful_outputs``
  in-place-update facts and dead-feed donation candidates emitted as a
  :class:`DonationPlan`, with every fact PROVEN safe by the analyzer's
  PTA009 donation-hazard lint before it enters the plan — a var some
  later op still reads after its in-place update is dropped (recorded
  in ``plan.dropped``), never planned.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import framework
from paddle_tpu.framework import default_main_program
from paddle_tpu.ops.registry import GRAD_SUFFIX

__all__ = ["ControlFlowGraph", "memory_optimize", "release_memory",
           "MemoryPlan", "DonationPlan", "plan_donation"]

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "float32": 4, "int32": 4, "float16": 2,
    "bfloat16": 2, "int16": 2, "int8": 1, "uint8": 1, "bool": 1,
}


def _var_bytes(var):
    if var is None or var.shape is None:
        return 0
    n = 1
    for d in var.shape:
        if d is None or d < 0:
            d = 1  # batch dim unknown at plan time; relative report only
        n *= d
    return n * _DTYPE_BYTES.get(str(var.dtype), 4)


class ControlFlowGraph:
    """Liveness over one block's op list (reference ``ControlFlowGraph:40``;
    the op list is a straight line — sub-blocks are separate graphs, like
    the reference's ``_get_cfgs``)."""

    def __init__(self, block, skip_vars=()):
        self.block = block
        self.ops = list(block.ops)
        self.skip = set(skip_vars)
        self.uses = []   # per op: set of read var names
        self.defs = []   # per op: set of written var names
        self.live_in = []
        self.live_out = []
        for op in self.ops:
            self.uses.append({n for n in op.input_arg_names if n})
            self.defs.append({n for n in op.output_arg_names if n})
        self._dataflow()

    def _dataflow(self):
        n = len(self.ops)
        self.live_in = [set() for _ in range(n)]
        self.live_out = [set() for _ in range(n)]
        # single backward sweep suffices on a straight-line block
        succ_live_in = set()
        for i in range(n - 1, -1, -1):
            self.live_out[i] = set(succ_live_in)
            self.live_in[i] = self.uses[i] | (self.live_out[i] -
                                              self.defs[i])
            succ_live_in = self.live_in[i]

    def last_use_index(self):
        """var name -> index of the op after which it is dead."""
        last = {}
        for i, (u, d) in enumerate(zip(self.uses, self.defs)):
            for name in u | d:
                last[name] = i
        return last

    def _optimizable(self, name):
        if name in self.skip:
            return False
        try:
            var = self.block.var(name)
        except KeyError:
            return False
        if getattr(var, "persistable", False) or \
                getattr(var, "is_data", False):
            return False
        return var.shape is not None

    def reuse_pairs(self):
        """Greedy first-fit reuse: when var B is defined at op i and some
        dead var A has identical shape+dtype, B may take A's buffer
        (reference memory_optimize pool logic)."""
        pairs = []
        pool = []  # (name, shape, dtype) of dead vars
        last = self.last_use_index()
        reused = set()
        for i, op in enumerate(self.ops):
            for name in sorted(self.defs[i]):
                if not self._optimizable(name) or name in reused:
                    continue
                var = self.block.var(name)
                for j, (cand, shape, dtype) in enumerate(pool):
                    if shape == tuple(var.shape or ()) and \
                            dtype == str(var.dtype):
                        pairs.append((name, cand))
                        reused.add(name)
                        pool.pop(j)
                        break
            # vars that die at this op join the pool
            for name in sorted((self.uses[i] | self.defs[i])):
                if last.get(name) == i and self._optimizable(name) \
                        and name not in self.live_out[i]:
                    var = self.block.var(name)
                    pool.append((name, tuple(var.shape or ()),
                                 str(var.dtype)))
        return pairs

    def byte_curve(self):
        """Per-op live bytes (at op exit), without reuse."""
        curve = []
        for i in range(len(self.ops)):
            live = self.live_out[i] | self.defs[i]
            curve.append(sum(_var_bytes(self._safe_var(n))
                             for n in live if self._optimizable(n)))
        return curve

    def _safe_var(self, name):
        try:
            return self.block.var(name)
        except KeyError:
            return None


class MemoryPlan:
    def __init__(self, program):
        self.program = program
        self.reuse_pairs = []          # (new_var, reused_buffer_of)
        self.last_use = {}             # block idx -> {var: op idx}
        self.peak_bytes = 0
        self.peak_bytes_with_reuse = 0
        self.donatable_feeds = []

    def savings_bytes(self):
        return self.peak_bytes - self.peak_bytes_with_reuse

    def report(self):
        lines = [
            "memory plan for program:",
            f"  estimated peak (no reuse):   {self.peak_bytes:,} bytes",
            f"  estimated peak (with reuse): "
            f"{self.peak_bytes_with_reuse:,} bytes",
            f"  savings: {self.savings_bytes():,} bytes "
            f"({100.0 * self.savings_bytes() / max(self.peak_bytes, 1):.1f}%)",
            f"  reuse pairs: {len(self.reuse_pairs)}",
        ]
        for new, old in self.reuse_pairs[:32]:
            lines.append(f"    {new} <- buffer of {old}")
        if len(self.reuse_pairs) > 32:
            lines.append(f"    ... {len(self.reuse_pairs) - 32} more")
        if self.donatable_feeds:
            lines.append(f"  donatable feeds: "
                         f"{', '.join(sorted(self.donatable_feeds))}")
        return "\n".join(lines)


def memory_optimize(input_program=None, print_log=False, level=0):
    """Analyze and attach a MemoryPlan (reference ``memory_optimize:332``).

    Grad vars (``@GRAD``) are always candidates; ``level`` kept for API
    parity (the reference's level 0/1 = exact/compatible shape match; only
    exact matching is planned here since XLA does the byte-level packing).
    """
    program = input_program or default_main_program()
    plan = MemoryPlan(program)
    peak = 0
    peak_reuse = 0
    for blk in program.blocks:
        cfg = ControlFlowGraph(blk)
        pairs = cfg.reuse_pairs()
        plan.reuse_pairs.extend(pairs)
        plan.last_use[blk.idx] = cfg.last_use_index()
        curve = cfg.byte_curve()
        if curve:
            peak += max(curve)
            # with-reuse curve: a var that claims a dead buffer costs no
            # new allocation WHILE LIVE, so subtract its bytes from every
            # live set containing it (its donor is dead there, and donor
            # chains are never co-live), then take the new peak
            reused = {new for new, _ in pairs}
            curve_reuse = []
            for i in range(len(cfg.ops)):
                live = cfg.live_out[i] | cfg.defs[i]
                saved = sum(_var_bytes(cfg._safe_var(nm))
                            for nm in live
                            if nm in reused and cfg._optimizable(nm))
                curve_reuse.append(max(curve[i] - saved, 0))
            peak_reuse += max(curve_reuse)
    plan.peak_bytes = peak
    plan.peak_bytes_with_reuse = peak_reuse

    # feeds whose value dies inside the step can be donated by the caller
    gb = program.global_block()
    last = plan.last_use.get(gb.idx, {})
    n_ops = len(gb.ops)
    for v in gb.vars.values():
        if getattr(v, "is_data", False) and v.name in last \
                and last[v.name] < n_ops - 1:
            plan.donatable_feeds.append(v.name)

    program._memory_plan = plan
    # the plan drives interpret-mode early release (executor drops env
    # entries per last_use) — guard it with the structural verifier so a
    # liveness plan is never attached to an ill-formed program
    from paddle_tpu.analysis import verify_transpiled
    verify_transpiled(program, where="memory_optimize")
    if print_log:
        print(plan.report())
    return plan


def release_memory(input_program=None):
    """Enable interpret-mode early release: the executor drops dead env
    entries after each op per the plan (reference ``release_memory:340``
    inserts delete_var ops)."""
    program = input_program or default_main_program()
    if getattr(program, "_memory_plan", None) is None:
        memory_optimize(program)
    program._release_memory = True
    return program._memory_plan


# ---------------------------------------------------------------------------
# donation/aliasing planner (the analysis/opt ``donation_plan`` pass)
# ---------------------------------------------------------------------------

class DonationPlan:
    """Statically proven donation facts for one program.

    * ``donatable_feeds`` — feed vars whose value dies inside the step
      (their device buffer may be donated to the executable);
    * ``inplace_updates`` — ``{var: (op_index, op_type, slot)}`` for
      every declared ``stateful_outputs`` write whose post-update
      buffer is provably never read again in the step: exactly the
      aliasing the executor's donated in-out state path performs, now
      proven hazard-free instead of assumed;
    * ``dropped`` — facts the PTA009 donation-hazard lint REFUSED: the
      var is read after its in-place update, so donating it would hand
      the reader a poisoned buffer (and break the sentinel's skip-step
      discard).  These stay observable, never planned.
    """

    def __init__(self):
        self.donatable_feeds = []
        self.inplace_updates = {}
        self.dropped = []      # (var, reason) facts the lint rejected

    def to_dict(self):
        return {"donatable_feeds": sorted(self.donatable_feeds),
                "inplace_updates": {
                    n: {"op_index": i, "op_type": t, "slot": s}
                    for n, (i, t, s) in
                    sorted(self.inplace_updates.items())},
                "dropped": [{"var": v, "reason": r}
                            for v, r in self.dropped]}

    def report(self):
        lines = [f"donation plan: {len(self.donatable_feeds)} "
                 f"donatable feed(s), {len(self.inplace_updates)} "
                 f"proven in-place update(s), {len(self.dropped)} "
                 f"hazard(s) dropped"]
        for n in sorted(self.donatable_feeds):
            lines.append(f"  feed {n}: dies inside the step — donatable")
        for n, (i, t, slot) in sorted(self.inplace_updates.items()):
            lines.append(f"  state {n}: in-place update by op #{i} "
                         f"`{t}` ({slot}) — hazard-free")
        for v, r in self.dropped:
            lines.append(f"  DROPPED {v}: {r}")
        return "\n".join(lines)

    def __repr__(self):
        return (f"DonationPlan(feeds={len(self.donatable_feeds)}, "
                f"inplace={len(self.inplace_updates)}, "
                f"dropped={len(self.dropped)})")


def plan_donation(program, feed_names=None, fetch_names=None):
    """Build (and attach as ``program._donation_plan``) the donation/
    aliasing plan.  Every candidate fact is checked against the
    analyzer's PTA009 donation-hazard lint — a hazard on a var removes
    it from the plan rather than shipping an unsafe aliasing claim."""
    from paddle_tpu.analysis import lints
    from paddle_tpu.analysis.opmeta import stateful_output_names
    from paddle_tpu.ops import registry

    program = program or default_main_program()
    block = program.global_block()
    plan = DonationPlan()

    # the existing PTA009 lint IS the proof obligation: collect the
    # vars it flags as read-after-in-place-update
    hazardous = {}
    for d in lints.check_graph(program, feed_names=feed_names,
                               fetch_names=fetch_names):
        if d.code == "PTA009" and d.var:
            hazardous.setdefault(d.var, d.message)

    # in-place update facts (slot declared stateful in the opdef)
    for i, op in enumerate(block.ops):
        opdef = registry.lookup(op.type)
        if opdef is None or not opdef.stateful_outputs:
            continue
        for slot in opdef.stateful_outputs:
            for n in op.output(slot):
                if not n:
                    continue
                if n in hazardous:
                    plan.dropped.append((n, hazardous[n]))
                elif n not in plan.inplace_updates:
                    plan.inplace_updates[n] = (i, op.type, slot)

    # feeds whose buffer dies inside the step: liveness says their last
    # use precedes the end of the block AND they are never fetched
    if feed_names is None:
        feed_names = [v.name for v in block.vars.values()
                      if getattr(v, "is_data", False)]
    fetch_set = set(fetch_names or ())
    cfg = ControlFlowGraph(block)
    last = cfg.last_use_index()
    n_ops = len(block.ops)
    for name in feed_names:
        if name in fetch_set or name in hazardous:
            continue
        if name in last and last[name] < n_ops - 1:
            plan.donatable_feeds.append(name)

    program._donation_plan = plan
    return plan
