"""Scope: hierarchical name -> value store (reference ``scope.h:39``).

Values are jax arrays (committed to device) or host numpy arrays; the
Executor moves values to/from device as needed.  Unlike the reference —
where every op reads and writes Variables in the Scope — only block
*boundaries* touch the scope here: feeds, fetches, and persistable state.
Everything intermediate lives inside the compiled XLA computation.
"""

from __future__ import annotations

__all__ = ["Scope", "global_scope", "scope_guard"]

import contextlib


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self.kids = []
        # LoD metadata (row-splits per level) carried next to ragged tensors
        self._lod = {}

    def new_scope(self):
        kid = Scope(parent=self)
        self.kids.append(kid)
        return kid

    def var(self, name):
        """Find-or-create (reference Scope::Var)."""
        s = self.find_scope(name)
        if s is not None:
            return s._vars[name]
        self._vars[name] = None
        return None

    def find_scope(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s
            s = s.parent
        return None

    def find_var(self, name):
        s = self.find_scope(name)
        return None if s is None else s._vars[name]

    def has_var(self, name):
        return self.find_scope(name) is not None

    def set_var(self, name, value):
        s = self.find_scope(name)
        (s or self)._vars[name] = value

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)
            self._lod.pop(n, None)

    def local_var_names(self):
        return list(self._vars)

    def items(self):
        return list(self._vars.items())

    # -- LoD metadata ------------------------------------------------------
    def set_lod(self, name, lod):
        if lod is None:
            s = self
            while s is not None:
                s._lod.pop(name, None)
                s = s.parent
        else:
            self._lod[name] = lod

    def find_lod(self, name):
        s = self
        while s is not None:
            if name in s._lod:
                return s._lod[name]
            s = s.parent
        return None

    def drop_kids(self):
        self.kids = []


_global_scope = Scope()
_current_scope = _global_scope


def global_scope():
    return _current_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _current_scope
    prev, _current_scope = _current_scope, scope
    try:
        yield
    finally:
        _current_scope = prev
