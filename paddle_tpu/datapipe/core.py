"""Composable input-pipeline stages with checkpointable iterators.

The 2018-era surface (``reader/decorator.py``) is a chain of nullary
generator factories: fast to write, but impossible to checkpoint (a
generator's position cannot be saved), blind (no per-stage metrics), and
leaky (threads owned by abandoned generators).  ``datapipe`` replaces it
with a chain of :class:`Stage` objects — the tf.data lineage (Murray et
al., VLDB 2021) realized over this repo's runtime:

* every stage IS the iterator state: ``state_dict()`` /
  ``load_state_dict()`` capture (shard position, epoch, RNG state,
  buffered samples) so a killed trainer resumes mid-epoch with the
  EXACT sample sequence an uninterrupted run would have seen
  (``fault.CheckpointManager(datapipe=...)`` wires this into the
  crash-consistent checkpoint commit);
* threaded stages (:class:`~paddle_tpu.datapipe.stages.ParallelMap`,
  :class:`~paddle_tpu.datapipe.prefetch.DevicePrefetch`) quiesce on
  ``state_dict()``: in-flight samples drain into a ``pending`` buffer
  that is part of the state — nothing is lost, nothing replays;
* every stage reports throughput / stall-time / queue-depth into
  ``profiler.runtime_metrics`` (``datapipe.<stage>.*``), visible through
  the serving ``/stats`` endpoint and ``paddle_tpu stats --local``.

Iteration protocol: ``iter(stage)`` yields the REMAINDER of the current
epoch (a fresh pipeline starts at epoch 0, offset 0); exhausting it
advances the epoch, so ``for _ in range(passes): for batch in pipe:``
is the multi-epoch loop.  Abandoning an iterator mid-epoch keeps the
position — the next ``iter()`` continues where it stopped; ``reset()``
rewinds to epoch 0.
"""

from __future__ import annotations

import time

from paddle_tpu.obs.trace import record_span
from paddle_tpu.profiler import runtime_metrics

__all__ = ["Stage", "PipelineStateError", "stats"]


class PipelineStateError(ValueError):
    """A ``load_state_dict`` payload does not match the pipeline shape."""


class _Raised:
    """An exception captured in a worker/buffer, re-raised at the
    consumer in sequence position (shared with the threaded stages)."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


class Stage:
    """One pipeline node.  Subclasses implement ``_iterate`` (a generator
    over the rest of the current epoch), ``_shutdown`` (quiesce any
    worker threads, draining in-flight items into stage state), and the
    ``_state``/``_load_state`` pair for their local position."""

    kind = "stage"

    def __init__(self, upstream=None, name=None):
        self._upstream = upstream
        self.name = name or self.kind
        self._metrics = "datapipe." + self.name

    # -- iteration ------------------------------------------------------
    def __iter__(self):
        try:
            yield from self._iterate()
        finally:
            # runs on exhaustion AND on abandonment (generator close/GC):
            # threads stop, in-flight items drain into stage state
            self._shutdown()

    def _iterate(self):
        raise NotImplementedError

    def _shutdown(self):
        """Quiesce: stop worker threads, fold in-flight items into state.
        Must be idempotent and callable at any time."""

    def close(self):
        """Quiesce this stage and everything upstream."""
        self._shutdown()
        if self._upstream is not None:
            self._upstream.close()

    # -- state ----------------------------------------------------------
    def _state(self):
        return {}

    def _load_state(self, state):
        pass

    def state_dict(self):
        """Picklable snapshot of the whole chain's position.  Call it
        between ``next()`` calls (the per-step checkpoint pattern);
        threaded stages quiesce first so in-flight samples are captured,
        not lost."""
        self._shutdown()
        d = {"kind": self.kind, "state": self._state()}
        if self._upstream is not None:
            d["upstream"] = self._upstream.state_dict()
        return d

    def load_state_dict(self, d):
        """Restore the whole chain's position from a ``state_dict``
        snapshot.  Safe to call MID-EPOCH with an abandoned iterator
        still open (the sentinel's rollback path does exactly this):
        every stage quiesces first, so the stale iterator's eventual
        ``close()`` hits an idempotent ``_shutdown`` and cannot drain
        pre-rollback in-flight samples over the restored state — reopen
        with ``iter(pipe)`` to resume from the loaded position."""
        if not isinstance(d, dict) or d.get("kind") != self.kind:
            raise PipelineStateError(
                f"stage {self.name!r} (kind {self.kind!r}) cannot load "
                f"state of kind {d.get('kind') if isinstance(d, dict) else d!r}"
                f" — pipeline shape changed since the checkpoint")
        self._shutdown()
        self._load_state(d.get("state") or {})
        if self._upstream is not None:
            if "upstream" not in d:
                raise PipelineStateError(
                    f"stage {self.name!r}: state has no upstream entry")
            self._upstream.load_state_dict(d["upstream"])

    def reset(self):
        """Rewind the whole chain to epoch 0, discarding buffers."""
        self._shutdown()
        self._reset_local()
        if self._upstream is not None:
            self._upstream.reset()

    def _reset_local(self):
        pass

    # -- metrics --------------------------------------------------------
    def _count(self, n=1):
        runtime_metrics.inc(self._metrics + ".items", n)

    def _pull(self, iterator):
        """``next(iterator)`` with the upstream wait observed as this
        stage's stall time (and, under tracing, one
        ``datapipe.<stage>.pull`` span per sample — the per-stage
        timeline every pipeline stage contributes through this choke
        point).  Raises StopIteration through."""
        t0 = time.perf_counter()
        item = next(iterator)
        dt = time.perf_counter() - t0
        runtime_metrics.observe(self._metrics + ".wait_seconds", dt)
        record_span(self._metrics + ".pull", t0, dt)
        return item

    # -- fluent builders ------------------------------------------------
    def shuffle(self, buffer_size, seed=0, name=None):
        from paddle_tpu.datapipe.stages import Shuffle
        return Shuffle(self, buffer_size, seed=seed, name=name)

    def map(self, fn, workers=0, window=None, name=None):
        from paddle_tpu.datapipe.stages import ParallelMap
        return ParallelMap(self, fn, workers=workers, window=window,
                           name=name)

    def batch(self, batch_size, drop_last=False, collate=None,
              pad_to_bucket=False, bucket_edges=None, name=None):
        from paddle_tpu.datapipe.stages import Batch
        return Batch(self, batch_size, drop_last=drop_last,
                     collate=collate, pad_to_bucket=pad_to_bucket,
                     bucket_edges=bucket_edges, name=name)

    def shard_ids(self, field, vocab_size, num_shards, shard_index=None,
                  owner_field=None, name=None):
        from paddle_tpu.datapipe.stages import ShardIds
        return ShardIds(self, field, vocab_size, num_shards,
                        shard_index=shard_index, owner_field=owner_field,
                        name=name)

    def prefetch(self, depth=2, device=None, name=None):
        from paddle_tpu.datapipe.prefetch import DevicePrefetch
        return DevicePrefetch(self, depth=depth, device=device, name=name)


def stats():
    """The ``datapipe.*`` slice of the process-wide runtime metrics —
    per-stage item counts, stall-time series, and queue-depth gauges
    (the same numbers ``/stats`` and ``paddle_tpu stats --local`` show)."""
    snap = runtime_metrics.snapshot()
    out = {}
    for section, body in snap.items():
        if not isinstance(body, dict):
            continue
        picked = {k: v for k, v in body.items()
                  if k.startswith("datapipe.")}
        if picked:
            out[section] = picked
    return out
