"""Sharded, seekable source stages.

A source owns the deterministic full sample stream of an epoch
(``_stream(epoch)``) and layers two things on top:

* **sharding** — sample-stride partitioning (``shard_index::num_shards``,
  the ``tf.data.Dataset.shard`` discipline): every trainer constructs
  the same source with its own ``shard_index`` and sees a disjoint,
  deterministic slice.  File-granular sharding is the degenerate case of
  handing each trainer its own glob;
* **position** — ``(epoch, offset)`` where ``offset`` counts samples
  already emitted to this shard's consumer this epoch.  Resume seeks by
  skipping ``offset`` samples of the deterministic stream (in-memory
  sources index directly), which is what makes the WHOLE pipeline's
  ``state_dict`` replayable.

The ``datapipe.source`` failpoint fires per emitted sample, so
``PADDLE_TPU_CHAOS`` can break the input stream exactly where a flaky
filesystem or decoder would.
"""

from __future__ import annotations

import glob as _glob
import itertools
import pickle

from paddle_tpu.datapipe.core import Stage
from paddle_tpu.fault import chaos as _chaos

__all__ = ["Source", "InMemorySource", "FileSource", "RecordIOSource"]


class Source(Stage):
    kind = "source"

    def __init__(self, num_shards=1, shard_index=0, name=None):
        super().__init__(None, name or "source")
        num_shards, shard_index = int(num_shards), int(shard_index)
        if num_shards < 1 or not 0 <= shard_index < num_shards:
            raise ValueError(
                f"bad sharding: shard_index={shard_index} of "
                f"num_shards={num_shards}")
        self.num_shards = num_shards
        self.shard_index = shard_index
        self._epoch = 0
        self._offset = 0
        # live stream cache: [iterator, (epoch, offset) it is positioned
        # at].  A downstream quiesce (state_dict per checkpoint) closes
        # the generator chain above the source; without this cache every
        # re-entry would rebuild the stream and re-skip O(offset)
        # samples — quadratic re-reads for file/recordio corpora.
        self._live = None

    @property
    def epoch(self):
        return self._epoch

    def close(self):
        """Release the cached live stream (open file handles for
        file-backed sources).  NOT done in ``_shutdown``: state_dict
        quiesces via _shutdown every checkpoint, and dropping the
        stream there would re-pay the O(offset) seek per save."""
        live, self._live = self._live, None
        if live is not None:
            closer = getattr(live[0], "close", None)
            if closer is not None:
                closer()
        super().close()

    def _stream(self, epoch):
        """The full (unsharded) deterministic sample stream of ``epoch``."""
        raise NotImplementedError

    def _shard_stream(self, epoch, skip):
        """This shard's stream with ``skip`` already-emitted samples
        dropped; subclasses with random access override for O(1) seeks."""
        it = itertools.islice(self._stream(epoch), self.shard_index, None,
                              self.num_shards)
        return itertools.islice(it, skip, None)

    def _iterate(self):
        while True:
            if self._live is None or \
                    self._live[1] != (self._epoch, self._offset):
                self._live = [
                    self._shard_stream(self._epoch, self._offset),
                    (self._epoch, self._offset)]
            live = self._live
            # fire BEFORE pulling: an armed error failpoint must leave
            # the cached stream positioned so a retry re-reads the same
            # sample instead of silently skipping it
            _chaos.fire("datapipe.source", epoch=self._epoch,
                        offset=self._offset)
            try:
                sample = next(live[0])
            except StopIteration:
                self._live = None
                self._epoch += 1
                self._offset = 0
                return
            self._offset += 1
            live[1] = (self._epoch, self._offset)
            self._count()
            yield sample

    def _state(self):
        # sharding geometry rides the state so a restore onto a
        # DIFFERENT dp degree (elastic shrink/grow) can reposition
        # instead of silently replaying/skipping the wrong stride
        return {"epoch": self._epoch, "offset": self._offset,
                "num_shards": self.num_shards,
                "shard_index": self.shard_index}

    def _load_state(self, state):
        self._epoch = int(state["epoch"])
        offset = int(state["offset"])
        saved_shards = int(state.get("num_shards", self.num_shards))
        if saved_shards != self.num_shards:
            # elastic resume: the stream was consumed with a different
            # stride.  All shards advance in lockstep (one batch per
            # step, checkpoints at step boundaries), so the saved
            # per-shard offset means ``saved_shards * offset`` samples
            # of the epoch are consumed globally; this shard resumes at
            # its slice of the remainder.  Exactly-once requires the
            # global position to land on a whole new-stride row — a
            # ragged cut would force replays (duplicates) or skips
            # (gaps), so it fails loudly instead.
            global_consumed = saved_shards * offset
            if global_consumed % self.num_shards:
                from paddle_tpu.datapipe.core import PipelineStateError
                raise PipelineStateError(
                    f"source {self.name!r}: cannot reposition a "
                    f"checkpoint of {saved_shards} shard(s) at offset "
                    f"{offset} onto {self.num_shards} shard(s) — "
                    f"global position {global_consumed} does not align "
                    f"with the new stride (checkpoint at an aligned "
                    f"step, or restore onto the saved degree)")
            offset = global_consumed // self.num_shards
        self._offset = offset

    def _reset_local(self):
        self._epoch = 0
        self._offset = 0
        self._live = None


class InMemorySource(Source):
    """Samples from an in-memory sequence (list/tuple/array rows)."""

    def __init__(self, data, num_shards=1, shard_index=0, name=None):
        super().__init__(num_shards, shard_index, name)
        self._data = data

    def __len__(self):
        n, k = len(self._data), self.num_shards
        return (n - self.shard_index + k - 1) // k

    def _stream(self, epoch):
        return iter(self._data)

    def _shard_stream(self, epoch, skip):
        data = self._data
        start = self.shard_index + skip * self.num_shards
        return (data[i]                 # true O(1) seek: index directly
                for i in range(start, len(data), self.num_shards))


class FileSource(Source):
    """Lines of the files matching ``pattern`` (sorted; newline
    stripped), optionally parsed per line."""

    def __init__(self, pattern, parse=None, num_shards=1, shard_index=0,
                 name=None):
        super().__init__(num_shards, shard_index, name)
        self.pattern = pattern
        self.parse = parse

    def files(self):
        files = sorted(_glob.glob(self.pattern))
        if not files:
            raise FileNotFoundError(
                f"FileSource: no files match {self.pattern!r}")
        return files

    def _stream(self, epoch):
        for path in self.files():
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    yield self.parse(line) if self.parse else line


class RecordIOSource(Source):
    """Records of ``recordio_writer``-format files (glob pattern or
    explicit path list); each record decoded by ``load`` (default:
    ``pickle.loads``, the ``convert_reader_to_recordio_file`` inverse)."""

    def __init__(self, paths, load=None, num_shards=1, shard_index=0,
                 name=None):
        super().__init__(num_shards, shard_index, name)
        self.paths = paths
        self.load = load if load is not None else pickle.loads

    def files(self):
        if isinstance(self.paths, str):
            files = sorted(_glob.glob(self.paths))
            if not files:
                raise FileNotFoundError(
                    f"RecordIOSource: no files match {self.paths!r}")
            return files
        return list(self.paths)

    def _stream(self, epoch):
        from paddle_tpu.recordio_writer import RecordIOScanner
        for path in self.files():
            for rec in RecordIOScanner(path):
                yield self.load(rec)
