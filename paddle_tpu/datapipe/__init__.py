"""paddle_tpu.datapipe — async sharded input pipeline with device
prefetch and checkpointable iterators (docs/data_pipeline.md).

Compose stages fluently from a sharded source::

    import paddle_tpu.datapipe as dp

    pipe = (dp.InMemorySource(samples, num_shards=4, shard_index=rank)
              .shuffle(buffer_size=1024, seed=7)
              .map(decode, workers=4)
              .batch(32, pad_to_bucket=True)
              .prefetch(depth=2))

    for batch in pipe:            # one epoch; iterate again for the next
        exe.run(main, feed=batch, fetch_list=[loss])

``pipe.state_dict()`` / ``pipe.load_state_dict()`` capture the exact
mid-epoch position (shard offsets, shuffle RNG + buffer, in-flight
samples); hand the pipeline to ``fault.CheckpointManager(datapipe=pipe)``
and a killed trainer resumes with the identical sample sequence.  Every
stage reports ``datapipe.*`` throughput/stall/queue-depth metrics into
``profiler.runtime_metrics``.
"""

from paddle_tpu.datapipe.core import Stage, PipelineStateError, stats
from paddle_tpu.datapipe.sources import (Source, InMemorySource, FileSource,
                                         RecordIOSource)
from paddle_tpu.datapipe.stages import (Shuffle, ParallelMap, Batch,
                                        ShardIds, default_collate)
from paddle_tpu.datapipe.prefetch import DevicePrefetch

__all__ = [
    "Stage", "PipelineStateError", "stats",
    "Source", "InMemorySource", "FileSource", "RecordIOSource",
    "Shuffle", "ParallelMap", "Batch", "ShardIds", "default_collate",
    "DevicePrefetch",
]
