"""Transform stages: seeded shuffle, bounded parallel map, batch/pad.

All three are exactly resumable: their ``state_dict`` includes every
sample that has been pulled from upstream but not yet emitted (shuffle
buffer, in-flight map results, partial batch), so a restore continues
the sample sequence with no loss and no replay.
"""

from __future__ import annotations

import collections
import concurrent.futures
import numpy as np

from paddle_tpu.datapipe.core import Stage, _Raised

__all__ = ["Shuffle", "ParallelMap", "Batch", "ShardIds",
           "default_collate"]


class Shuffle(Stage):
    """Deterministic buffered shuffle: a ``buffer_size`` reservoir is
    kept full; each incoming sample evicts a seeded-RNG-chosen resident
    (then the tail drains in random order at epoch end).  The RNG runs
    continuously from ``seed`` across epochs — two pipelines built with
    the same seed emit identical permutations, and the captured RNG
    state + buffer make mid-epoch resume exact."""

    kind = "shuffle"

    def __init__(self, upstream, buffer_size, seed=0, name=None):
        super().__init__(upstream, name or "shuffle")
        if buffer_size < 1:
            raise ValueError("shuffle buffer_size must be >= 1")
        self.buffer_size = int(buffer_size)
        self.seed = seed
        self._rng = None
        self._buffer = []
        self._draining = False

    def _ensure_rng(self):
        if self._rng is None:
            self._rng = np.random.default_rng(self.seed)
        return self._rng

    def _iterate(self):
        rng = self._ensure_rng()
        buf = self._buffer
        if not self._draining:
            up = iter(self._upstream)
            try:
                while True:
                    try:
                        sample = self._pull(up)
                    except StopIteration:
                        break
                    if len(buf) < self.buffer_size:
                        buf.append(sample)
                        continue
                    j = int(rng.integers(len(buf)))
                    out = buf[j]
                    buf[j] = sample
                    self._count()
                    yield out
            finally:
                up.close()
            self._draining = True
        while buf:
            j = int(rng.integers(len(buf)))
            buf[j], buf[-1] = buf[-1], buf[j]
            self._count()
            yield buf.pop()
        self._draining = False

    def _state(self):
        return {"buffer": list(self._buffer),
                "rng": self._ensure_rng().bit_generator.state,
                "draining": self._draining}

    def _load_state(self, state):
        self._buffer = list(state["buffer"])
        self._rng = np.random.default_rng(self.seed)
        self._rng.bit_generator.state = state["rng"]
        self._draining = bool(state["draining"])

    def _reset_local(self):
        self._buffer = []
        self._rng = None
        self._draining = False


class ParallelMap(Stage):
    """``fn`` over the stream on a bounded worker pool, order-preserving.

    Up to ``window`` samples (default ``2 * workers``) are in flight; the
    consumer side re-joins results in submission order, so the output
    sequence is deterministic regardless of worker scheduling — the
    property the resume guarantee rides on.  ``workers=0`` degrades to a
    synchronous map (no threads).  ``state_dict()`` quiesces the pool:
    in-flight results are drained (in order) into a pending buffer that
    ships with the state; worker exceptions re-raise consumer-side at
    their sequence position.
    """

    kind = "map"

    def __init__(self, upstream, fn, workers=0, window=None, name=None):
        super().__init__(upstream, name or "map")
        self.fn = fn
        self.workers = int(workers)
        self.window = int(window) if window is not None \
            else max(2 * self.workers, 1)
        if self.window < 1:
            raise ValueError("map window must be >= 1")
        self._pool = None
        self._futs = collections.deque()
        self._pending = collections.deque()
        self._up_iter = None
        self._up_eof = False

    def _ensure_pool(self):
        if self._pool is None and self.workers > 0:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.workers,
                thread_name_prefix=f"datapipe-{self.name}")
        return self._pool

    def _fill(self):
        """Top the in-flight window up from upstream."""
        from paddle_tpu.profiler import runtime_metrics
        if self._up_iter is None and not self._up_eof:
            self._up_iter = iter(self._upstream)
        while len(self._futs) < self.window and not self._up_eof:
            try:
                sample = self._pull(self._up_iter)
            except StopIteration:
                self._up_eof = True
                self._up_iter = None
                break
            if self.workers > 0:
                self._futs.append(self._ensure_pool().submit(
                    self.fn, sample))
            else:
                # synchronous: apply now, park the result
                try:
                    self._pending.append(self.fn(sample))
                except BaseException as e:
                    self._pending.append(_Raised(e))
                break
        runtime_metrics.set_gauge(self._metrics + ".queue_depth",
                                  len(self._futs) + len(self._pending))

    def _iterate(self):
        while True:
            while self._pending:
                item = self._pending.popleft()
                if isinstance(item, _Raised):
                    raise item.exc
                self._count()
                yield item
            self._fill()
            if self._futs:
                fut = self._futs.popleft()
                self._count()
                yield fut.result()  # re-raises worker exceptions in order
                continue
            if self._pending:
                continue
            if self._up_eof:
                self._up_eof = False
                self._close_pool()
                return

    def _close_pool(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def _shutdown(self):
        while self._futs:
            fut = self._futs.popleft()
            try:
                self._pending.append(fut.result())
            except BaseException as e:
                self._pending.append(_Raised(e))
        self._close_pool()
        if self._up_iter is not None:
            self._up_iter.close()
            self._up_iter = None

    def _state(self):
        pending = list(self._pending)
        if any(isinstance(p, _Raised) for p in pending):
            raise RuntimeError(
                f"map stage {self.name!r} holds a pending worker "
                f"exception; consume (and handle) it before "
                f"checkpointing")
        return {"pending": pending, "up_eof": self._up_eof}

    def _load_state(self, state):
        self._pending = collections.deque(state["pending"])
        self._up_eof = bool(state["up_eof"])

    def _reset_local(self):
        self._pending.clear()
        self._up_eof = False


def default_collate(samples):
    """Stack a list of samples along a new batch axis.  Dict samples
    become a dict of stacked arrays (the executor feed-dict shape),
    tuple/list samples a tuple of stacked slots, scalars/arrays one
    stacked array."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(s[i]) for s in samples])
                     for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


def _pad_rows(arr, target):
    if arr.shape[0] >= target:
        return arr
    pad = np.zeros((target - arr.shape[0],) + arr.shape[1:],
                   dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


class Batch(Stage):
    """Group samples into batches of ``batch_size`` and collate.

    ``pad_to_bucket=True`` pads a short final batch's leading axis up to
    ``lod.row_bucket`` (capped at ``batch_size``), so the tail batch of
    every epoch reuses a warm jit-cache entry instead of compiling a
    one-off shape — the zero rows are the caller's to mask.  The partial
    batch under construction is part of ``state_dict``, so resume never
    drops tail samples."""

    kind = "batch"

    def __init__(self, upstream, batch_size, drop_last=False, collate=None,
                 pad_to_bucket=False, bucket_edges=None, name=None):
        super().__init__(upstream, name or "batch")
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.collate = collate or default_collate
        self.pad_to_bucket = pad_to_bucket
        self.bucket_edges = bucket_edges
        self._partial = []

    def _emit(self, samples):
        batch = self.collate(samples)
        if self.pad_to_bucket and len(samples) < self.batch_size:
            from paddle_tpu.lod import row_bucket
            target = min(row_bucket(len(samples), self.bucket_edges),
                         self.batch_size)
            if isinstance(batch, dict):
                batch = {k: _pad_rows(np.asarray(v), target)
                         for k, v in batch.items()}
            elif isinstance(batch, tuple):
                batch = tuple(_pad_rows(np.asarray(v), target)
                              for v in batch)
            else:
                batch = _pad_rows(np.asarray(batch), target)
        self._count()
        return batch

    def _iterate(self):
        up = iter(self._upstream)
        try:
            while True:
                try:
                    sample = self._pull(up)
                except StopIteration:
                    break
                self._partial.append(sample)
                if len(self._partial) == self.batch_size:
                    samples, self._partial = self._partial, []
                    yield self._emit(samples)
        finally:
            up.close()
        if self._partial and not self.drop_last:
            samples, self._partial = self._partial, []
            yield self._emit(samples)
        self._partial = []

    def _state(self):
        return {"partial": list(self._partial)}

    def _load_state(self, state):
        self._partial = list(state["partial"])

    def _reset_local(self):
        self._partial = []


class ShardIds(Stage):
    """Route embedding ids to their owning table shard.

    The sharded-table contract (``paddle_tpu.embedding.tables``): a
    table ``P(axis, None)``-sharded over ``num_shards`` devices holds
    contiguous vocab *blocks*, so shard ``k`` owns ids
    ``[k*V/N, (k+1)*V/N)``.  This stage stamps each sample with the
    owner of every id in ``field`` (an ``int32`` array of the same
    shape, stored under ``owner_field``, default ``<field>_owner``) —
    the datapipe-side half of the reference's pserver prefetch routing,
    computed where it is cheap (host, per-sample) instead of in the
    step.  With ``shard_index`` given, the stage also tracks the
    fraction of ids NOT owned locally
    (``datapipe.<stage>.remote_frac`` gauge) — the cross-shard gather
    traffic an operator watches when re-bucketing ids.

    Stateless (a pure per-sample map), so resume is exact for free.
    Dict samples get a new key; tuple/list samples get the owner array
    appended.
    """

    kind = "shard_ids"

    def __init__(self, upstream, field, vocab_size, num_shards,
                 shard_index=None, owner_field=None, name=None):
        super().__init__(upstream, name or "shard_ids")
        from paddle_tpu.embedding import rows_per_shard
        self.field = field
        self.vocab_size = int(vocab_size)
        self.num_shards = int(num_shards)
        # validates divisibility eagerly — the same constraint PTA016
        # enforces on the table's PartitionSpec
        self._rows_per_shard = rows_per_shard(self.vocab_size,
                                              self.num_shards)
        self.shard_index = shard_index
        self.owner_field = owner_field or f"{field}_owner"

    def _route(self, sample):
        from paddle_tpu.profiler import runtime_metrics
        ids = np.asarray(sample[self.field])
        if (ids < 0).any() or (ids >= self.vocab_size).any():
            raise ValueError(
                f"{self.name}: ids in {self.field!r} fall outside "
                f"[0, {self.vocab_size}) — a sharded gather would "
                f"silently drop them")
        owner = (ids // self._rows_per_shard).astype(np.int32)
        if self.shard_index is not None and owner.size:
            remote = float(np.mean(owner != self.shard_index))
            runtime_metrics.set_gauge(
                self._metrics + ".remote_frac", remote)
        if isinstance(sample, dict):
            out = dict(sample)
            out[self.owner_field] = owner
        else:
            out = type(sample)(list(sample) + [owner])
        self._count()
        return out

    def _iterate(self):
        up = iter(self._upstream)
        try:
            while True:
                try:
                    sample = self._pull(up)
                except StopIteration:
                    break
                yield self._route(sample)
        finally:
            up.close()
