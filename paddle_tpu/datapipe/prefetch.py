"""Async device prefetch: the stage that actually hides host time.

A background thread pulls batches from upstream, converts them to device
arrays (``jax.device_put``), and parks them in a bounded queue — so
host-side decode/collate/transfer of batch N+1 overlaps device compute
of batch N (the double-buffer discipline of the reference's
``create_double_buffer_reader_op.cc``, generalized to a depth-``depth``
queue).  The consumer-side ``datapipe.prefetch.stall_seconds`` series is
THE input-starvation signal: near zero means the pipeline keeps the
accelerator fed; large means add map workers or prefetch depth.

Quiesce semantics match the other threaded stages: ``state_dict()``
stops the thread and drains queued batches into a pending buffer
(device arrays are pulled back to host numpy for pickling), so a
checkpoint taken between steps loses nothing.  A batch already in the
worker's hands when the stop lands is stashed into an overflow slot and
folded in AFTER the queued (older) batches — order is preserved, no
sample is dropped or replayed.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

import jax
import numpy as np

from paddle_tpu.datapipe.core import Stage, _Raised
from paddle_tpu.obs.trace import record_span
from paddle_tpu.profiler import runtime_metrics

__all__ = ["DevicePrefetch"]

_EOF = object()


def _to_device(batch, device):
    # device_put/jnp.asarray accept host AND device inputs (the latter
    # pass through without a copy), so this is safe both for fresh host
    # batches and for re-placing restored/pending ones
    put = (lambda a: jax.device_put(a, device)) if device is not None \
        else jax.numpy.asarray
    if isinstance(batch, dict):
        return {k: put(v) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return tuple(put(v) for v in batch)
    return put(batch)


def _to_host(batch):
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, (tuple, list)):
        return tuple(np.asarray(v) for v in batch)
    return np.asarray(batch)


class DevicePrefetch(Stage):
    kind = "prefetch"

    def __init__(self, upstream, depth=2, device=None, name=None):
        super().__init__(upstream, name or "prefetch")
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        self.depth = int(depth)
        self.device = device
        self._q = None
        self._thread = None
        self._stop = None
        self._up_iter = None
        self._pending = collections.deque()
        self._overflow = []      # worker's in-hand items at quiesce time
        self._eof_pending = False
        # HBM census: staged (queued + pending) device batches are the
        # `prefetch` collection — weakref'd so a dropped stage releases
        import weakref
        from paddle_tpu.obs import perf as _perf
        ref = weakref.ref(self)
        self._hbm_token = _perf.register_hbm_provider(
            "prefetch", lambda: (ref().device_buffers()
                                 if ref() is not None else ()))
        # a per-epoch rebuilt pipeline must not leak dead providers
        weakref.finalize(self, _perf.unregister_hbm_provider,
                         self._hbm_token)

    def device_buffers(self):
        """Flat snapshot of the DEVICE arrays currently staged in this
        stage (queued + pending batches) — the census's `prefetch`
        collection.  Pending batches restored by ``load_state_dict``
        are host numpy until the next iterate re-places them; those are
        host RAM, not HBM, so the ``devices`` attribute (jax arrays
        only) gates what counts."""
        batches = list(self._pending)
        q = self._q
        if q is not None:
            with q.mutex:
                batches.extend(q.queue)
        out = []
        for b in batches:
            if isinstance(b, dict):
                vals = b.values()
            elif isinstance(b, (tuple, list)):
                vals = b
            else:
                vals = (b,)
            out.extend(v for v in vals
                       if hasattr(v, "nbytes") and hasattr(v, "devices"))
        return out

    # -- producer -------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is not None:
            return
        self._q = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._up_iter = iter(self._upstream)
        up_iter = self._up_iter

        def deliver(q, stop, overflow, item):
            """Queue ``item``; once stopped, stash it in the overflow
            slot instead (never drop — the item was already pulled from
            upstream, so upstream's position has moved past it)."""
            while True:
                if stop.is_set():
                    overflow.append(item)
                    return False
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue

        def worker(q, stop, overflow):
            try:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    try:
                        batch = self._pull(up_iter)
                    except StopIteration:
                        deliver(q, stop, overflow, _EOF)
                        return
                    dev = _to_device(batch, self.device)
                    dt = time.perf_counter() - t0
                    runtime_metrics.observe(
                        self._metrics + ".fill_seconds", dt)
                    record_span(self._metrics + ".fill", t0, dt)
                    if not deliver(q, stop, overflow, dev):
                        return
                    runtime_metrics.set_gauge(
                        self._metrics + ".queue_depth", q.qsize())
            except BaseException as e:
                deliver(q, stop, overflow, _Raised(e))

        self._thread = threading.Thread(
            target=worker, args=(self._q, self._stop, self._overflow),
            daemon=True, name=f"datapipe-{self.name}")
        self._thread.start()

    # -- consumer -------------------------------------------------------
    def _iterate(self):
        while True:
            while self._pending:
                item = self._pending.popleft()
                if isinstance(item, _Raised):
                    raise item.exc
                self._count()
                # pending batches restored by load_state_dict are host
                # numpy — place them so the device-array contract holds
                # on post-restore steps too (no-op for quiesced device
                # batches)
                yield _to_device(item, self.device)
            if self._eof_pending:
                self._eof_pending = False
                return
            self._ensure_thread()
            t0 = time.perf_counter()
            item = self._q.get()
            dt = time.perf_counter() - t0
            runtime_metrics.observe(self._metrics + ".stall_seconds", dt)
            record_span(self._metrics + ".stall", t0, dt)
            runtime_metrics.set_gauge(self._metrics + ".queue_depth",
                                      self._q.qsize())
            if item is _EOF:
                self._shutdown()      # joins the (exiting) thread
                self._eof_pending = False
                return
            if isinstance(item, _Raised):
                self._shutdown()
                raise item.exc
            self._count()
            yield item

    # -- quiesce --------------------------------------------------------
    def _shutdown(self):
        if self._thread is None:
            return
        self._stop.set()
        # drain while the thread winds down so a put blocked on a full
        # queue completes; queued items are OLDER than the worker's
        # in-hand overflow item, so the queue folds into pending first
        while self._thread.is_alive():
            self._drain_into_pending()
            self._thread.join(timeout=0.05)
        self._drain_into_pending()
        self._thread = None
        for item in self._overflow:
            if item is _EOF:
                self._eof_pending = True
            else:
                self._pending.append(item)
        del self._overflow[:]
        if self._up_iter is not None:
            self._up_iter.close()
            self._up_iter = None

    def _drain_into_pending(self):
        try:
            while True:
                item = self._q.get_nowait()
                if item is _EOF:
                    self._eof_pending = True
                else:
                    self._pending.append(item)
        except queue.Empty:
            pass

    def _state(self):
        pending = []
        for item in self._pending:
            if isinstance(item, _Raised):
                raise RuntimeError(
                    f"prefetch stage {self.name!r} holds a pending "
                    f"worker exception; consume (and handle) it before "
                    f"checkpointing")
            pending.append(_to_host(item))
        return {"pending": pending, "eof_pending": self._eof_pending}

    def _load_state(self, state):
        self._pending = collections.deque(state["pending"])
        self._eof_pending = bool(state["eof_pending"])

    def _reset_local(self):
        self._pending.clear()
        self._eof_pending = False
