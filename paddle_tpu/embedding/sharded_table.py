"""Row-sharded embedding tables over the mesh.

The reference scales CTR embedding tables past one host by splitting
them into pserver blocks and rewriting lookups into ``prefetch_op``
RPCs (``distribute_transpiler.py`` sparse branch).  The TPU-native
form keeps the program untouched and expresses the split as a
PartitionSpec on the vocab dim — ``P(axis, None)`` — which GSPMD turns
into the same owner-side gather exchange, and which the PTA016/PTA017
pass can *prove* against the program before anything compiles.

:func:`plan_sharded_tables` is the planning front door: it finds every
``is_distributed`` lookup table in a program, shards the table AND its
row-shaped optimizer accumulators (the sparse Adam moments must live
with their rows or the sparse update would combine differently-sharded
tensors), verifies the whole plan through
``analysis.distributed.check_distributed_spec``, and hands back rules
for ``ParallelExecutor`` plus placement tuples for the elastic
per-shard checkpoint writer (``fault/shard_ckpt.py``) — so a sharded
table rides the same dp4->dp2 shrink/grow machinery as ZeRO state.

:func:`sharded_gather` / :func:`sharded_scatter_add` are the explicit
shard_map-form of the exchange (built on ``parallel/collective.py``),
for code that holds per-shard blocks by hand rather than riding GSPMD.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.embedding import tables as _tables
from paddle_tpu.parallel.mesh import MODEL_AXIS
from paddle_tpu.parallel.zero import OPTIMIZER_STATE_SLOTS

__all__ = ["ShardedTablePlan", "plan_sharded_tables",
           "sharded_gather", "sharded_scatter_add"]


class ShardedTablePlan:
    """The sharding facts of one program's distributed tables:
    ``tables`` maps table param name -> placement tuple
    (``(axis, None)``), ``states`` the row-shaped optimizer
    accumulators riding along, ``diagnostics`` the PTA016/PTA017
    verdict the plan was proven with."""

    def __init__(self, program, axis):
        self.program = program
        self.axis = axis
        self.tables = {}       # table name -> (axis, None)
        self.states = {}       # accumulator name -> (axis, None, ...)
        self.diagnostics = []

    def __bool__(self):
        return bool(self.tables)

    def all_placements(self):
        merged = dict(self.tables)
        merged.update(self.states)
        return merged

    def rules(self):
        """``(regex, PartitionSpec)`` rules for
        ``ParallelExecutor(param_shardings=...)``.  Covering the
        accumulators here also excludes them from the executor's ZeRO
        plan (first match wins), keeping one owner per tensor."""
        return [(f"^{re.escape(name)}$", P(*spec))
                for name, spec in sorted(self.all_placements().items())]

    def checkpoint_specs(self):
        """name -> placement tuple for
        ``CheckpointManager(shard_specs=...)`` /
        ``shard_ckpt.build_topology`` — the elastic per-shard writer
        then saves each table (and its moments) one vocab-block per
        shard, and ``plan_restore`` can re-cut the blocks for a
        different mesh."""
        return dict(self.all_placements())


def plan_sharded_tables(program, mesh_axis=MODEL_AXIS, mesh=None,
                        mesh_axes=None, raise_on_error=True):
    """Build and *prove* the row-sharding plan for every
    ``is_distributed`` lookup table in ``program``.

    The table parameter is placed ``P(mesh_axis, None)`` (vocab dim
    blocked over the axis), and every row-shaped optimizer state slot
    of that parameter (Moment1/Moment2/...) is placed identically —
    scalar slots (Beta1Pow) stay replicated.  The plan is then run
    through ``check_distributed_spec``: PTA016 facts (unknown axis,
    indivisible vocab, param/state disagreement) raise
    ``ProgramVerificationError`` before any compile unless
    ``raise_on_error=False``.

    ``mesh`` (or a ``mesh_axes`` name->size dict) adds the axis-size
    divisibility proof; without either, the plan is only proven
    structurally.
    """
    from paddle_tpu import profiler as _profiler
    from paddle_tpu.analysis import AnalysisResult, check_distributed_spec
    from paddle_tpu.parallel.distribute_transpiler import DistributedSpec

    block = program.global_block()
    plan = ShardedTablePlan(program, mesh_axis)

    for op in block.ops:
        if op.type != "lookup_table" or not op.attr("is_distributed",
                                                    False):
            continue
        w = op.input("W")[0]
        var = block.var(w)
        if not var.shape or len(var.shape) < 2:
            continue
        plan.tables[w] = (mesh_axis, None)
        _tables.register_table(w, vocab=var.shape[0], dim=var.shape[1])

    # the tables' optimizer accumulators: row-shaped slots shard with
    # their rows, scalar slots (Beta1Pow/Beta2Pow) stay replicated
    for op in block.ops:
        slots = OPTIMIZER_STATE_SLOTS.get(op.type)
        if not slots or "Param" not in op.inputs:
            continue
        param = op.input("Param")[0]
        if param not in plan.tables:
            continue
        pshape = block.var(param).shape
        for slot in slots:
            for name in op.inputs.get(slot, ()):
                sshape = block.var(name).shape
                if sshape and tuple(sshape) == tuple(pshape):
                    plan.states[name] = (mesh_axis,) + (None,) * (
                        len(sshape) - 1)

    if mesh is not None and mesh_axes is None:
        mesh_axes = dict(zip(mesh.axis_names, mesh.devices.shape))

    spec = DistributedSpec()
    spec.param_specs = {name: P(*placement)
                        for name, placement
                        in plan.all_placements().items()}
    plan.diagnostics = check_distributed_spec(program, spec,
                                              mesh_axes=mesh_axes)
    if raise_on_error:
        AnalysisResult(plan.diagnostics).raise_on_errors(
            where="embedding.plan_sharded_tables")
    _profiler.runtime_metrics.inc("embedding.plans")
    return plan


# -- shard_map-form gather/scatter (parallel/collective.py) -----------------

def sharded_gather(w_block, ids, axis_name):
    """Gather rows by *global* id from a block-sharded table inside a
    ``shard_map``: each rank resolves the ids it owns (block layout —
    ``tables.owner_of``), contributes zeros elsewhere, and one
    ``all_reduce`` assembles the result (exactly one owner per id, so
    the sum IS the gather — the prefetch RPC of the reference as a
    collective)."""
    from paddle_tpu.parallel import collective
    rows = w_block.shape[0]
    rank = jax.lax.axis_index(axis_name)
    local = ids.astype(jnp.int32) - rank * rows
    owned = (local >= 0) & (local < rows)
    safe = jnp.clip(local, 0, rows - 1)
    vals = jnp.where(owned[..., None],
                     jnp.take(w_block, safe, axis=0), 0)
    return collective.all_reduce(vals, axis_name)


def sharded_scatter_add(w_block, row_ids, vals, axis_name):
    """Scatter-add SelectedRows-style ``(row_ids, vals)`` updates into
    a block-sharded table inside a ``shard_map``: each rank keeps only
    the rows it owns and drops the rest (index == block height ->
    XLA's out-of-bounds drop), so no collective is needed — the rows
    were already routed by ownership."""
    rows = w_block.shape[0]
    rank = jax.lax.axis_index(axis_name)
    local = row_ids.astype(jnp.int32) - rank * rows
    owned = (local >= 0) & (local < rows)
    dropped = jnp.where(owned, local, rows)
    return w_block.at[dropped].add(vals, mode="drop")
