"""Mesh-sharded embedding tables — the CTR/recommendation workload.

PaddlePaddle's defining production scenario is recommendation models
whose embedding tables exceed one host's memory; the reference serves
it with SelectedRows grads + pserver-distributed tables (PAPER.md
§runtime-objects, §distributed).  This package is the TPU-native
replacement: row-sharded tables proven by the PTA016/PTA017 pass
(``sharded_table``), one shared row-ownership geometry for the
datapipe router / collectives / checkpoint reshard (``tables``), and
HBM census attribution of table bytes (``obs/perf.py``'s
``embedding`` collection).
"""

from paddle_tpu.embedding.tables import (
    register_table, registered_tables, is_table, table_meta,
    rows_per_shard, owner_of, local_row)
from paddle_tpu.embedding.sharded_table import (
    ShardedTablePlan, plan_sharded_tables, sharded_gather,
    sharded_scatter_add)

__all__ = ["register_table", "registered_tables", "is_table",
           "table_meta", "rows_per_shard", "owner_of", "local_row",
           "ShardedTablePlan", "plan_sharded_tables", "sharded_gather",
           "sharded_scatter_add"]
