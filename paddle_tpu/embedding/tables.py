"""Embedding-table registry + row-ownership geometry.

One definition of two facts every layer of the sharded-embedding stack
must agree on:

* **which parameters are embedding tables** — ``layers.embedding``
  registers every table it creates here, so the HBM census
  (``obs/perf.py``) can attribute table bytes to the ``embedding``
  collection (``hbm.embedding_bytes``) without guessing from names;
* **which shard owns a row** — PartitionSpec sharding on the vocab dim
  is *block* sharding (shard ``k`` holds the contiguous rows
  ``[k*V/N, (k+1)*V/N)``), so the datapipe id router, the shard-local
  gather/scatter in ``sharded_table.py``, and the checkpoint reshard
  plan must all use the same block arithmetic.  The reference's
  pserver path hashed ids round-robin (``distributed_splitter.py``);
  under GSPMD the table is tiled contiguously, so ownership is
  ``id // rows_per_shard`` — a divide, not a hash.
"""

from __future__ import annotations

import numpy as np

__all__ = ["register_table", "registered_tables", "is_table",
           "table_meta", "rows_per_shard", "owner_of", "local_row"]

# name -> {"vocab": int|None, "dim": int|None}; process-wide like the
# op registry — table identity is a property of the program family, not
# of one Program instance
_TABLES = {}


def register_table(name, vocab=None, dim=None):
    """Record ``name`` as an embedding-table parameter (idempotent;
    later registrations may fill in geometry the first one lacked)."""
    from paddle_tpu import profiler as _profiler
    meta = _TABLES.setdefault(str(name), {"vocab": None, "dim": None})
    if vocab is not None:
        meta["vocab"] = int(vocab)
    if dim is not None:
        meta["dim"] = int(dim)
    _profiler.runtime_metrics.set_gauge("embedding.tables",
                                        len(_TABLES))
    return meta


def registered_tables():
    return {k: dict(v) for k, v in _TABLES.items()}


def is_table(name):
    return str(name) in _TABLES


def table_meta(name):
    meta = _TABLES.get(str(name))
    return dict(meta) if meta else None


def rows_per_shard(vocab, num_shards):
    """Rows each shard holds under block sharding; the same divisibility
    the PTA016 pass enforces on the PartitionSpec."""
    vocab, num_shards = int(vocab), int(num_shards)
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if vocab % num_shards != 0:
        raise ValueError(
            f"vocab {vocab} is not divisible by {num_shards} shards — "
            f"the PartitionSpec block layout (and PTA016) require it")
    return vocab // num_shards


def owner_of(ids, vocab, num_shards):
    """Owning shard of each id under the block layout (array in, array
    out; scalars work too)."""
    per = rows_per_shard(vocab, num_shards)
    return np.asarray(ids) // per


def local_row(ids, vocab, num_shards):
    """Row index of each id *within its owning shard's block*."""
    per = rows_per_shard(vocab, num_shards)
    return np.asarray(ids) % per
