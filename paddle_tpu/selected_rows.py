"""SelectedRows: sparse row-subset gradient value.

TPU-native equivalent of the reference's SelectedRows type
(``paddle/fluid/framework/selected_rows.h``, functors
``operators/math/selected_rows_functor.cc``): a (rows, value) pair standing
for a ``[height, ...]`` tensor that is zero outside ``rows``.  Produced by
``lookup_table_grad`` when the embedding was built with ``is_sparse=True``
and consumed directly by the sparse branches of the optimizer ops — the
full-vocab dense gradient is never materialized, so the update step is
O(batch·dim) instead of O(vocab·dim).

Registered as a jax pytree, so it flows through ``jax.jit``/``vjp``
boundaries inside the compiled block.  ``rows`` may contain duplicates
(one per occurrence in the batch); linear consumers (sgd, sum) scatter-add
directly, while non-linear consumers (adagrad/adam moment updates) call
``merge_duplicates()`` first — the analog of the reference's
``scatter::MergeAdd``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows", "is_selected_rows", "to_dense"]


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    def __init__(self, rows, value, height):
        self.rows = rows          # [N] int array
        self.value = value        # [N, ...] array
        self.height = int(height)  # static logical dim-0 extent

    def tree_flatten(self):
        return (self.rows, self.value), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, value = children
        return cls(rows, value, height)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    def astype(self, dtype):
        return SelectedRows(self.rows, self.value.astype(dtype), self.height)

    def to_dense(self):
        """Densify (duplicate rows accumulate)."""
        out = jnp.zeros((self.height,) + tuple(self.value.shape[1:]),
                        self.value.dtype)
        return out.at[self.rows].add(self.value)

    def merge_duplicates(self):
        """Combine duplicate row indices by summation, statically shaped
        (reference ``scatter::MergeAdd``): the result has the same slot
        count; slot g < #unique holds (unique row id, summed value), and
        unused tail slots get row index ``height`` — OUT OF BOUNDS, so
        jax's default scatter drop-semantics make them no-ops for both
        ``.at[].add`` and ``.at[].set`` consumers (safe for the lazy
        adagrad/adam row updates)."""
        order = jnp.argsort(self.rows)
        sorted_rows = self.rows[order]
        sorted_vals = self.value[order]
        is_head = jnp.concatenate([
            jnp.ones((1,), bool), sorted_rows[1:] != sorted_rows[:-1]])
        seg = jnp.cumsum(is_head) - 1                  # group id per slot
        n = self.rows.shape[0]
        merged_vals = jnp.zeros_like(sorted_vals).at[seg].add(sorted_vals)
        group_rows = jnp.full_like(sorted_rows, -1).at[seg].max(sorted_rows)
        valid = jnp.arange(n) <= seg[-1]               # slot < #unique rows
        rows = jnp.where(valid, group_rows,
                         jnp.asarray(self.height, group_rows.dtype))
        return SelectedRows(rows, merged_vals, self.height)


def is_selected_rows(v):
    return isinstance(v, SelectedRows)


def to_dense(v):
    return v.to_dense() if isinstance(v, SelectedRows) else v
