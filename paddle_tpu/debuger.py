"""Deprecated alias for :mod:`paddle_tpu.analysis.visualize`.

The reference repo shipped its visualizers under this (typo'd) path;
the real implementation now lives in ``paddle_tpu.analysis.visualize``
alongside the other static-analysis passes.  Importing this module
keeps working but warns once — update imports to::

    from paddle_tpu.analysis.visualize import draw_block_graphviz
"""

from __future__ import annotations

import warnings

from paddle_tpu.analysis.visualize import (  # noqa: F401
    draw_block_graphviz, pprint_block_codes, pprint_program_codes,
    program_dot)

__all__ = ["draw_block_graphviz", "pprint_program_codes",
           "pprint_block_codes", "program_dot"]

warnings.warn(
    "paddle_tpu.debuger is deprecated; use paddle_tpu.analysis.visualize",
    DeprecationWarning, stacklevel=2)
