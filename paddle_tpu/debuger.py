"""Program debugging / visualization (reference
``python/paddle/fluid/debuger.py`` + ``graphviz.py`` + ``net_drawer.py``).

``draw_block_graphviz`` emits GraphViz .dot text (ops as boxes, vars as
ellipses, grads highlighted) — render with any dot tool; no binary needed
to produce the file.  ``pprint_program_codes`` renders the program as
pseudo-code like the reference's protobuf pretty printer.
"""

from __future__ import annotations

__all__ = ["draw_block_graphviz", "pprint_program_codes",
           "pprint_block_codes"]

from paddle_tpu.ops.registry import GRAD_SUFFIX


def _var_label(block, name):
    try:
        v = block.var(name)
        shape = "x".join(str(d) for d in (v.shape or ())) or "?"
        return f"{name}\\n{v.dtype}[{shape}]"
    except KeyError:
        return name


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write a .dot graph of one block (reference ``debuger.py``
    draw_block_graphviz).  Returns the dot source text."""
    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars = set()

    def var_node(name):
        nid = f"var_{name}".replace(".", "_").replace("@", "_AT_")
        if name not in seen_vars:
            seen_vars.add(name)
            color = "orange" if name.endswith(GRAD_SUFFIX) else \
                ("red" if name in highlights else "lightblue")
            lines.append(
                f'  "{nid}" [label="{_var_label(block, name)}", '
                f'shape=ellipse, style=filled, fillcolor={color}];')
        return nid

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}_{op.type}"
        lines.append(f'  "{op_id}" [label="{op.type}", shape=box, '
                     f'style=filled, fillcolor=palegreen];')
        for n in op.input_arg_names:
            if n:
                lines.append(f'  "{var_node(n)}" -> "{op_id}";')
        for n in op.output_arg_names:
            if n:
                lines.append(f'  "{op_id}" -> "{var_node(n)}";')
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def pprint_block_codes(block, show_backward=True):
    """Pseudo-code rendering of one block (reference ``debuger.py``
    pprint_block_codes)."""
    out = []
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        outs = ", ".join(n for ns in op.outputs.values() for n in ns if n)
        ins = ", ".join(n for ns in op.inputs.values() for n in ns if n)
        attrs = ", ".join(
            f"{k}={v!r}" for k, v in sorted(op.attrs.items())
            if not hasattr(v, "ops"))  # skip sub-blocks
        call = f"{op.type}({ins}"
        if attrs:
            call += f", {attrs}"
        call += ")"
        out.append(f"{outs or '_'} = {call}" if outs else call)
    return "\n".join(out)


def pprint_program_codes(program, show_backward=True):
    chunks = []
    for blk in program.blocks:
        chunks.append(f"# block {blk.idx}")
        chunks.append(pprint_block_codes(blk, show_backward))
    return "\n".join(chunks)
