"""Executor: lowers a Program block to ONE compiled XLA computation.

This replaces the reference's per-op interpreter hot loop
(``paddle/fluid/framework/executor.cc:334-352`` — CreateOp / InferShape /
kernel dispatch per op per step) with trace-once/compile-once semantics:

  1. Partition block variables into feeds, read-only state, in-out state
     (persistables written by ops, e.g. parameters under SGD), and scratch.
  2. Trace every op's registered lowering into a single jaxpr.
  3. ``jax.jit`` the whole step with in-out state donated, cache by
     (program version, feed shapes/dtypes, fetch names).

Each subsequent ``run`` with the same signature is one XLA executable
launch — no Python per-op work at all.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import framework
from paddle_tpu.analysis import opmeta as _opmeta
from paddle_tpu.framework import Program, default_main_program
from paddle_tpu.obs.trace import span as _span, record_span as _record_span
from paddle_tpu.place import CPUPlace, TPUPlace
from paddle_tpu.scope import Scope, global_scope
from paddle_tpu.ops import registry

__all__ = ["Executor", "fetch_var", "enable_compile_cache",
           "disable_compile_cache", "jit_cache_capacity"]

logger = logging.getLogger(__name__)

# op types that exist for API parity but are no-ops inside a lowered block
from paddle_tpu.ops.reader_ops import (READER_CREATE_OPS, READER_OPS,
                                       EOFException, build_reader)

# feed/fetch are rewritten by the executor; reader ops run in the host-side
# pre-pass (_run_reader_ops) so the compiled step never sees them
_SKIP_OPS = frozenset({"feed", "fetch"}) | READER_OPS


def _run_reader_ops(block, scope, feed_arrays, device, steps=None):
    """Host-side reader pre-pass: construct reader objects (idempotent) and
    pop one batch per ``read`` op into ``feed_arrays`` (or ``steps`` stacked
    batches for the device-side loop).  Runs BEFORE compile/dispatch each
    step — the TPU placement of the reference's per-op reader dispatch
    (``operators/reader/reader_op_registry.h``)."""
    for op in block.ops:
        if op.type in READER_CREATE_OPS:
            out = op.output("Out")[0]
            if scope.find_var(out) is None:
                reader = build_reader(op, scope, device=device)
                scope.set_var(out, reader)
                # back-pointer for Variable.reset() so the user-facing
                # handle works with explicit (non-global) scopes too
                try:
                    block.var(out)._reader_runtime = reader
                except KeyError:
                    pass
        elif op.type == "read":
            reader = scope.find_var(op.input("Reader")[0])
            if reader is None:
                raise RuntimeError(
                    f"reader {op.input('Reader')[0]!r} is not created — "
                    f"run the startup program first")
            try:
                if steps is None:
                    batch = reader.next()
                else:
                    pulled = []
                    try:
                        for _ in range(steps):
                            pulled.append(reader.next())
                    except StopIteration:
                        # mid-pull EOF: return the consumed batches so a
                        # later pull serves them (in order) instead of
                        # dropping them
                        for p in reversed(pulled):
                            reader.unget(p)
                        raise
                    # keep the stack on-device when the reader (double
                    # buffer) already staged the batches there
                    stack = jnp.stack if hasattr(pulled[0][0], "devices") \
                        else np.stack
                    batch = tuple(stack([p[i] for p in pulled])
                                  for i in range(len(pulled[0])))
            except StopIteration:
                raise EOFException(
                    "reader exhausted — call reader.reset() to rewind")
            for name, arr in zip(op.output("Out"), batch):
                feed_arrays[name] = _as_device_array(arr, None, device) \
                    if not hasattr(arr, "devices") else arr


def _as_device_array(value, dtype=None, device=None):
    if isinstance(value, (int, float, bool)):
        value = np.asarray(value, dtype=dtype or None)
    if isinstance(value, np.ndarray) and dtype is not None:
        want = jnp.dtype(dtype) if dtype != "bfloat16" else jnp.bfloat16
        if value.dtype != want and dtype not in (None,):
            value = value.astype(want)
    arr = jnp.asarray(value)
    if device is not None:
        arr = jax.device_put(arr, device)
    return arr


# ---------------------------------------------------------------------------
# persistent XLA compilation cache (PADDLE_TPU_COMPILE_CACHE): a restart
# no longer recompiles every program from scratch — XLA executables are
# stored under the cache dir keyed by the lowered module, and a second
# process (or a second Executor re-tracing an identical program) loads
# them instead of invoking the backend compiler.  Hit/miss counters land
# in profiler.runtime_metrics (compile_cache.hits / .misses).
# ---------------------------------------------------------------------------

_compile_cache_dir = None


def enable_compile_cache(cache_dir):
    """Point jax's persistent compilation cache at ``cache_dir`` and relax
    its size/compile-time admission floors so every executable is cached
    (the floors exist to keep trivial kernels out of shared caches; a
    serving replica wants ALL of its programs warm).  Idempotent."""
    global _compile_cache_dir
    if not cache_dir or _compile_cache_dir == cache_dir:
        return _compile_cache_dir is not None
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_jax_cache_memo()  # see below — without this, enabling after
    # the process has already compiled something is silently a no-op
    _compile_cache_dir = str(cache_dir)
    from paddle_tpu import profiler as _profiler
    _profiler.install_jax_compile_listeners()
    return True


def disable_compile_cache():
    """Turn the persistent cache back off (tests; config symmetry)."""
    global _compile_cache_dir
    if _compile_cache_dir is None:
        return
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_cache_memo()
    _compile_cache_dir = None


def _reset_jax_cache_memo():
    """jax memoizes cache-enabled/disabled at the FIRST compile of the
    process (compilation_cache._cache_checked); reset it so a dir set
    mid-process (serving replica enabling the cache at load time) takes
    effect."""
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover - internal API moved
        logger.warning("could not reset jax compilation-cache state; "
                       "a cache dir set after the first compile may be "
                       "ignored", exc_info=True)


def _maybe_enable_compile_cache_from_env():
    import os
    d = os.environ.get("PADDLE_TPU_COMPILE_CACHE", "").strip()
    if d:
        enable_compile_cache(d)


def jit_cache_capacity():
    """Executor-level jit LRU capacity: PADDLE_TPU_JIT_CACHE_SIZE
    (default 64; values < 1 clamp to 1)."""
    import os
    raw = os.environ.get("PADDLE_TPU_JIT_CACHE_SIZE", "").strip()
    try:
        return max(1, int(raw)) if raw else 64
    except ValueError:
        logger.warning("bad PADDLE_TPU_JIT_CACHE_SIZE=%r; using 64", raw)
        return 64


class _CompiledBlock:
    """A traced+jitted block for one feed/fetch signature."""

    def __init__(self, fn, feed_names, ro_names, inout_names, fetch_names,
                 uses_rng):
        self.fn = fn
        self.feed_names = feed_names
        self.ro_names = ro_names
        self.inout_names = inout_names
        self.fetch_names = fetch_names
        self.uses_rng = uses_rng


class ScopeEnv(dict):
    """Interpret-mode env with write-through/read-through of PERSISTABLE
    vars to the scope — the reference's semantics, where every thread's op
    reads and writes one shared Scope (scope.h).  Needed so CSP go-routine
    threads and the main block observe each other's persistable writes."""

    def __init__(self, scope, persistable_names, init=None):
        super().__init__()
        self.scope = scope
        self.persistable_names = persistable_names
        if init:
            dict.update(self, init)

    def __getitem__(self, k):
        if k in self.persistable_names:
            v = self.scope.find_var(k)
            if v is not None:
                return v
        return dict.__getitem__(self, k)

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def __setitem__(self, k, v):
        dict.__setitem__(self, k, v)
        if k in self.persistable_names:
            self.scope.set_var(k, v)

    def update(self, other=(), **kw):
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v

    def clone_for_thread(self):
        return ScopeEnv(self.scope, self.persistable_names, init=self)


def _persistable_names(program):
    names = set()
    for blk in program.blocks:
        for v in blk.vars.values():
            if getattr(v, "persistable", False):
                names.add(v.name)
    return names


def lower_block(block, env, rng_key, training, aux):
    """Trace all ops of ``block`` into ``env`` (used for the main block and,
    recursively, by control-flow op lowerings for sub-blocks)."""
    from paddle_tpu import profiler as _profiler
    from paddle_tpu.obs import numerics as _numerics
    profiling = _profiler.op_profiling_enabled() and aux.get("interpret")
    probing = _numerics.probing_enabled() and aux.get("interpret")
    release = aux.get("release", {}).get(block.idx)
    rng_plan = aux.get("rng_plan")
    for i, op in enumerate(block.ops):
        if op.type in _SKIP_OPS:
            continue
        opdef = registry.resolve_lowering(op.type)
        key = None
        if rng_key is not None:
            # one counter slot per op (optimization passes leave
            # __rng_slots__ behind for ops they removed/fused, so
            # surviving RNG consumers keep their exact key positions)
            aux["rng_counter"] += op.attrs.get("__rng_slots__", 1)
            if rng_plan is None or _opmeta.needs_rng_key(op, registry):
                # under an opt-pipeline rng plan, ops statically proven
                # key-free skip the fold_in — a traced threefry
                # computation per op that XLA must carry through
                # trace/lower/DCE for nothing
                key = jax.random.fold_in(rng_key, aux["rng_counter"])
        ctx = registry.LowerContext(op, env, block, rng_key=key,
                                    training=training, aux=aux)
        if profiling:
            with _profiler.record_op(op.type, ctx):
                opdef.lower(ctx)
        else:
            # named_scope is trace-time-only: XLA carries it into every
            # emitted HLO op's metadata, so XProf traces of the COMPILED
            # step attribute device time back to IR ops (reference
            # platform/profiler.h RecordEvent — here the attribution
            # survives jit; see profiler.compiled_op_table)
            with jax.named_scope(_profiler.op_scope_name(op)):
                opdef.lower(ctx)
        env.update(ctx.outputs)
        if probing:
            # per-op numerics probes (obs/numerics.py): stats of every
            # output right after the op ran, first-non-finite capture
            _numerics.record_op(op, ctx.outputs, env)
        _share_lod(op, ctx, env, aux)
        if release is not None:
            # early release (memory_optimization_transpiler.release_memory):
            # in interpret mode every intermediate otherwise lives for the
            # whole step; drop vars past their last use, like the
            # reference's delete_var ops
            stats = release.get("stats")
            for n in release["dead_after"].get(i, ()):
                v = env.pop(n, None)
                if v is not None and hasattr(v, "nbytes") \
                        and stats is not None:
                    stats["bytes"] += int(v.nbytes)
                    stats["vars"] += 1
    return env


def _share_lod(op, ctx, env, aux):
    """Default LoD propagation (reference: OpKernels call ShareLoD(X, Out)
    unless they change the row structure): outputs whose leading dim equals
    a LoD-carrying input's row count inherit that input's lod, unless the
    lowering set an explicit output lod."""
    lod_map = aux.get("lod")
    if not lod_map or not ctx.outputs:
        return
    src = None
    rows = None
    for n in op.input_arg_names:
        if n in lod_map and n in env and hasattr(env[n], "shape") \
                and env[n].ndim > 0:
            src, rows = lod_map[n], env[n].shape[0]
            break
    if src is None:
        return
    for n, v in ctx.outputs.items():
        if n not in lod_map and hasattr(v, "shape") and \
                getattr(v, "ndim", 0) > 0 and v.shape[0] == rows:
            lod_map[n] = src


class Executor:
    """Reference: ``python/paddle/fluid/executor.py:181`` +
    ``paddle/fluid/framework/executor.cc:133``."""

    def __init__(self, place=None):
        self.place = place if place is not None else (
            TPUPlace(0) if any(d.platform != "cpu" for d in jax.devices())
            else CPUPlace())
        self._cache = {}
        self._cache_capacity = jit_cache_capacity()
        self._cache_inserts = 0  # lifetime insert count (eviction-proof)
        self._run_counter = 0
        self._verified = set()  # (id(program), version) PADDLE_TPU_VERIFY memo
        self._opt_cache = {}    # (id, version, feeds, fetches) -> program
        _maybe_enable_compile_cache_from_env()
        from paddle_tpu import profiler as _profiler
        _profiler.install_jax_compile_listeners()
        from paddle_tpu.obs import perf as _perf
        _perf.arm_census_from_env()

    # ------------------------------------------------------------------
    def _cache_insert(self, sig, value):
        """LRU insert bounded by PADDLE_TPU_JIT_CACHE_SIZE; evictions are
        counted (jit_cache.evictions) — a serving process churning through
        more signatures than the cache holds is recompiling, and the
        counter is how you see it."""
        from paddle_tpu import profiler as _profiler
        while len(self._cache) >= self._cache_capacity:
            self._cache.pop(next(iter(self._cache)))
            _profiler.runtime_metrics.inc("jit_cache.evictions")
        self._cache[sig] = value
        self._cache_inserts += 1

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True, sentinel=None):
        """``sentinel``: an optional :class:`paddle_tpu.fault.Sentinel`
        guarding this step — its device-side finite/spike checks run
        before the state write-back, and a trip discards the update and
        raises :class:`~paddle_tpu.fault.NumericalFault` (buffer
        donation is disabled for guarded programs so the pre-step scope
        state survives the discard).  ``sentinel=None`` is the donating
        fast path with zero added synchronization."""
        program = program if program is not None else default_main_program()
        if not isinstance(program, Program):
            raise TypeError("executor requires a Program")
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope if scope is not None else global_scope()

        fetch_names = [f.name if isinstance(f, framework.Variable) else f
                       for f in fetch_list]

        if _env_flag("PADDLE_TPU_VERIFY"):
            self._maybe_verify(program, feed, fetch_names)
        program = self._maybe_optimize(program, feed, fetch_names)
        block = program.global_block()

        with _span("executor.run"):
            return self._run_traced(program, block, feed, fetch_names,
                                    scope, return_numpy, sentinel=sentinel)

    # ------------------------------------------------------------------
    def _maybe_optimize(self, program, feed, fetch_names):
        """``PADDLE_TPU_OPT=1``: run the analysis/opt pass pipeline
        over the program ONCE per ``(program, version, feeds,
        fetches)`` before first compile — the executor then traces and
        compiles the optimized clone.  Memoized exactly like the jit
        cache: a cached step pays one dict lookup; mutating the program
        (``bump_version``) re-optimizes.  The input program is never
        mutated, and every pass is verify-sandwiched (a pass that
        introduces any diagnostic reverts — see analysis/opt)."""
        if not _env_flag("PADDLE_TPU_OPT"):
            return program
        if getattr(program, "_opt_report", None) is not None:
            return program  # already an optimized clone (direct call)
        key = (id(program), program._version, tuple(sorted(feed or ())),
               tuple(fetch_names))
        cached = self._opt_cache.get(key)
        if cached is not None:
            return cached
        from paddle_tpu.analysis.opt import optimize_program
        optimized, report = optimize_program(
            program, feed_names=tuple(feed or ()),
            fetch_names=tuple(fetch_names))
        logger.debug("PADDLE_TPU_OPT: %r", report)
        if getattr(program, "_release_memory", False):
            # the interpret-mode early-release plan keys op indices —
            # rebuild it against the optimized op list
            from paddle_tpu.memory_optimization_transpiler import \
                release_memory
            release_memory(optimized)
        if len(self._opt_cache) > 256:  # id()-reuse bound, not a cache
            self._opt_cache.clear()
        self._opt_cache[key] = optimized
        return optimized

    # ------------------------------------------------------------------
    def _maybe_verify(self, program, feed, fetch_names):
        """PADDLE_TPU_VERIFY=1: run the structural verifier
        (paddle_tpu.analysis) BEFORE first compile, so an ill-formed
        program fails with named vars/ops instead of a deep trace
        error.  Memoized per (program, version): a cached step pays one
        set lookup (<5% guard in tests/test_analysis.py), and mutating
        the program (bump_version) re-verifies."""
        key = (id(program), program._version)
        if key in self._verified:
            return
        from paddle_tpu import analysis
        analysis.verify_program(program, feed_names=tuple(feed),
                                fetch_names=tuple(fetch_names),
                                where="executor.run")
        if len(self._verified) > 4096:  # id() reuse bound, not a cache
            self._verified.clear()
        self._verified.add(key)

    def _run_traced(self, program, block, feed, fetch_names, scope,
                    return_numpy, sentinel=None):
        """Body of :meth:`run`, phase-annotated: ``executor.feed``
        (host->device conversion + reader pre-pass), ``executor.dispatch``
        (compile lookup + XLA launch), ``executor.fetch`` (state
        write-back + host conversion) — the spans that answer "where did
        step N spend its time"."""
        from paddle_tpu.obs import perf as _perf
        phases = _perf.step_phases_enabled()
        feed_arrays = {}
        device = self._feed_device()
        t_feed = time.perf_counter()
        with _span("executor.feed"):
            for name, value in feed.items():
                var = block.var(name) if block.has_var(name) else None
                lod = None
                if isinstance(value, tuple) and len(value) == 2 and \
                        isinstance(value[1], (list, tuple)):
                    value, lod = value
                dtype = var.dtype if var is not None else None
                _enforce_feed(name, value, var)
                if lod is not None and len(lod) == 1 and \
                        _lod_buckets_enabled(program):
                    # bucketed ragged mode (lod.py): pad rows to a bucket
                    # and feed the row-splits as data, so the jit key is
                    # the bucket, not the exact lod
                    from paddle_tpu.lod import (bucket_ragged_feed,
                                                SPLITS_SUFFIX)
                    value, splits, meta = bucket_ragged_feed(
                        name, np.asarray(value), lod)
                    feed_arrays[name] = _as_device_array(value, dtype,
                                                         device)
                    feed_arrays[name + SPLITS_SUFFIX] = _as_device_array(
                        splits, "int32", device)
                    scope.set_lod(name, meta)
                    continue
                feed_arrays[name] = _as_device_array(value, dtype, device)
                # a dense feed must also CLEAR any stale lod from a
                # previous ragged feed of the same variable
                scope.set_lod(name, lod)

            _run_reader_ops(block, scope, feed_arrays, device)
        feed_dt = time.perf_counter() - t_feed

        with _span("executor.dispatch") as dsp:
            compiled = self._get_compiled(program, block, feed_arrays,
                                          tuple(fetch_names), scope,
                                          donate=sentinel is None)

            ro_state = {n: self._state_value(scope, n, device)
                        for n in compiled.ro_names}
            inout_state = {n: self._state_value(scope, n, device)
                           for n in compiled.inout_names}

            self._run_counter += 1
            key = jax.random.PRNGKey(
                (program.random_seed or 0) * 1000003 + self._run_counter)

            t0 = time.perf_counter()
            fetches, new_state = compiled.fn(feed_arrays, ro_state,
                                             inout_state, key)
            dsp.set(fetches=len(fetch_names))
        dt = time.perf_counter() - t0
        from paddle_tpu import profiler as _profiler
        _profiler.runtime_metrics.observe("executor.step_seconds", dt)
        holder = getattr(compiled, "perf", None)
        perf_record = holder["record"] if holder else None
        _perf.census_tick(scope)
        with _span("executor.fetch"):
            if sentinel is not None:
                # the guard runs BEFORE write-back: a NumericalFault here
                # leaves the scope holding the (undonated) pre-step state
                # — the skip-step rung of the escalation ladder
                fetches, new_state = sentinel.after_step(
                    fetch_names, fetches, new_state,
                    repro=lambda: self._repro_payload(
                        program, feed_arrays, ro_state, inout_state,
                        fetch_names),
                    # for the fused health norms: the pre-step state
                    # (valid: guarded steps never donate) and which of
                    # its names are Parameters
                    prev_state=inout_state,
                    param_names=getattr(compiled, "param_names", ()))
            if _check_nan_inf_enabled(program):
                _check_nan_inf(fetch_names, fetches, new_state)
            if phases:
                # profile-step mode only: one explicit sync separates
                # "device still computing" from host-side conversion
                tw = time.perf_counter()
                for v in list(fetches) + list(new_state.values()):
                    if hasattr(v, "block_until_ready"):
                        try:
                            v.block_until_ready()
                        except Exception:
                            pass
                t_fetch = time.perf_counter()
                _profiler.runtime_metrics.observe(
                    "perf.step.device_wait_seconds", t_fetch - tw)
            for n, v in new_state.items():
                scope.set_var(n, v)
            result = [np.asarray(v) for v in fetches] if return_numpy \
                else list(fetches)
            gauge = _mfu_gauge_for(program)
            if return_numpy and perf_record is not None and gauge:
                # live MFU over the WHOLE step (feed staging -> fetch
                # materialization): the numpy conversion above BLOCKED
                # on the device, so this is an honest bench-style wall
                # time (host feed/fetch overhead included, same as the
                # analytical MFU bench.py reports).  The
                # return_numpy=False path hands back async arrays — its
                # submit time would overstate MFU by the async-dispatch
                # factor, so no gauge from it.
                _perf.note_step(perf_record, time.perf_counter() - t_feed,
                                gauge=gauge,
                                devices=getattr(self, "device_count", 1))
            if phases:
                _profiler.runtime_metrics.observe(
                    "perf.step.feed_seconds", feed_dt)
                _profiler.runtime_metrics.observe(
                    "perf.step.dispatch_seconds", dt)
                _profiler.runtime_metrics.observe(
                    "perf.step.fetch_seconds",
                    time.perf_counter() - t_fetch)
            return result

    # ------------------------------------------------------------------
    def _repro_payload(self, program, feed_arrays, ro_state, inout_state,
                       fetch_names):
        """Self-contained replay payload for a sentinel quarantine
        bundle: the program, PRE-step state, the batch, and the RNG
        coordinates needed to re-execute this exact step offline
        (``paddle_tpu replay``).  Built lazily — only on a trip."""
        state = {}
        for src in (ro_state, inout_state):
            for n, v in src.items():
                state[n] = np.asarray(v)
        return {"program": program.to_dict(),
                "random_seed": program.random_seed,
                "run_counter": self._run_counter,
                "feed": {n: np.asarray(v)
                         for n, v in feed_arrays.items()},
                "state": state,
                "fetch_names": list(fetch_names)}

    # ------------------------------------------------------------------
    def warmup(self, program=None, feed_shapes=None, fetch_list=None,
               scope=None, allow_state_updates=False):
        """AOT warmup: trace + lower + compile ``program`` for each
        declared feed signature BEFORE real traffic arrives, so the first
        real request pays zero compile time.

        ``feed_shapes``: a dict ``name -> concrete shape`` (one
        signature), or a list of such dicts (one per serving bucket).
        Every listed dim must be concrete — warmup exists to pin exact
        signatures.  Dtypes come from the program's variables.  Each
        signature is executed once on zero-filled feeds, which lands the
        executable in this executor's jit cache and — when
        PADDLE_TPU_COMPILE_CACHE is set — in the persistent XLA cache,
        where a restarted process finds it again.

        Warmup EXECUTES the program, so a program that writes persistable
        state (a training step: parameters, optimizer moments) would be
        mutated by zero-filled feeds — that is refused unless
        ``allow_state_updates`` opts in: ``True`` allows every state
        write, or an iterable of variable names allows exactly those
        (the generation decode step declares its KV-cache tensors this
        way — cache writes are intended, parameter writes still refuse).

        Returns a :class:`paddle_tpu.obs.perf.WarmupReport` — an ``int``
        equal to the number of signatures that were freshly compiled
        (0 = everything was already warm; existing callers keep
        working), whose ``buckets`` list carries one entry per declared
        signature: wall seconds, fresh-compile count, and whether the
        executable came ``"warm"`` (already in the jit LRU),
        ``"persistent-hit"`` (loaded from the PADDLE_TPU_COMPILE_CACHE
        dir), or ``"cold"`` (backend-compiled).  A rolling restart's
        "warm via compile cache" claim is checkable per bucket from a
        replica's ``/stats`` instead of inferred from global counters."""
        program = program if program is not None else default_main_program()
        specs = feed_shapes if isinstance(feed_shapes, (list, tuple)) \
            else [feed_shapes or {}]
        block = program.global_block()
        if allow_state_updates is not True:
            allowed = set(allow_state_updates or ())
            written = [n for op in block.ops if op.type not in _SKIP_OPS
                       for n in op.output_arg_names
                       if n not in allowed and block.has_var(n) and
                       block.var(n).persistable]
            if written:
                raise ValueError(
                    f"warmup would EXECUTE this program, mutating "
                    f"persistable state ({sorted(set(written))[:3]}...) "
                    f"with zero-filled feeds — warm an inference program "
                    f"instead, or pass allow_state_updates=True if the "
                    f"state writes are intended")
        # count INSERTS, not the cache-size delta: a full LRU evicting
        # during warmup would otherwise report 0 (or negative) compiles
        before = self._cache_inserts
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.obs.perf import WarmupReport
        buckets = []
        with _profiler.record_latency("executor.warmup_seconds"):
            for spec in specs:
                feed = {}
                for name, shape in spec.items():
                    if shape is None or any(
                            d is None or int(d) < 0 for d in shape):
                        raise ValueError(
                            f"warmup feed {name!r} needs a concrete "
                            f"shape, got {shape}")
                    var = block.var(name) if block.has_var(name) else None
                    dtype = (var.dtype if var is not None
                             and var.dtype is not None else "float32")
                    from paddle_tpu.io import synth_feed_value
                    feed[name] = synth_feed_value(shape, dtype)
                ins0 = self._cache_inserts
                hits0 = _profiler.runtime_metrics.counter(
                    "compile_cache.hits")
                t0 = time.perf_counter()
                self.run(program=program, feed=feed, fetch_list=fetch_list,
                         scope=scope)
                fresh = self._cache_inserts - ins0
                hit = _profiler.runtime_metrics.counter(
                    "compile_cache.hits") - hits0
                buckets.append({
                    "signature": {n: list(map(int, s))
                                  for n, s in spec.items()},
                    "compiles": fresh,
                    "seconds": time.perf_counter() - t0,
                    # per-bucket provenance of the executable: how a
                    # rolling restart proves "warm via compile cache"
                    "cache": ("warm" if fresh == 0 else
                              "persistent-hit" if hit > 0 else "cold"),
                })
        compiled = self._cache_inserts - before
        _profiler.runtime_metrics.inc("warmup.signatures", len(specs))
        _profiler.runtime_metrics.inc("warmup.compiles", compiled)
        return WarmupReport(compiled, buckets)

    # ------------------------------------------------------------------
    def run_steps(self, program=None, feed=None, fetch_list=None, steps=1,
                  scope=None, return_numpy=True):
        """Run ``steps`` iterations of ``program`` in ONE device dispatch.

        The training loop runs ON the device (``lax.scan`` over the step
        function with the state donated as the carry), so host<->device
        latency is paid once per call instead of once per step — the TPU
        analog of the reference's double-buffered reader pipeline
        (``operators/reader/create_double_buffer_reader_op.cc``) which
        exists to hide exactly this latency on GPU.

        ``feed`` values may be either one batch (reused every step) or
        stacked ``[steps, ...]`` arrays (leading axis = step axis, sliced
        per step in-graph).  Fetches come back stacked ``[steps, ...]``.
        """
        program = program if program is not None else default_main_program()
        if not isinstance(program, Program):
            raise TypeError("executor requires a Program")
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope if scope is not None else global_scope()
        steps = int(steps)

        fetch_names = [f.name if isinstance(f, framework.Variable) else f
                       for f in fetch_list]

        if _env_flag("PADDLE_TPU_VERIFY"):
            self._maybe_verify(program, feed, fetch_names)
        program = self._maybe_optimize(program, feed, fetch_names)
        block = program.global_block()

        device = self._feed_device()
        per_step_feed = {}
        const_feed = {}

        def is_lod_pair(v):
            return isinstance(v, tuple) and len(v) == 2 and \
                isinstance(v[1], (list, tuple))

        for name, value in feed.items():
            if isinstance(value, list) and value and \
                    all(is_lod_pair(v) for v in value):
                # per-step ragged batches: bucketed mode pads the whole
                # window to ONE bucket signature and threads the
                # row-splits through the device-side loop as data — the
                # streaming-LoD counterpart of the stacked dense feed
                if not _lod_buckets_enabled(program):
                    raise ValueError(
                        f"run_steps got per-step LoD feeds for {name!r}; "
                        f"enable bucketed mode (program.lod_buckets = "
                        f"True) so the window shares one executable")
                if len(value) != steps:
                    raise ValueError(
                        f"run_steps: {name!r} has {len(value)} ragged "
                        f"batches for {steps} steps")
                from paddle_tpu.lod import (bucket_ragged_feed,
                                            next_bucket, SPLITS_SUFFIX)
                var = block.var(name) if block.has_var(name) else None
                dtype = var.dtype if var is not None else None
                rows = [np.asarray(v[0]).shape[0] for v in value]
                mls = []
                n_seqs = set()
                for _, lod in value:
                    sp = np.asarray(lod[-1], np.int64)
                    lens = sp[1:] - sp[:-1]
                    mls.append(int(lens.max()) if len(lens) else 0)
                    n_seqs.add(len(sp) - 1)
                if len(n_seqs) != 1:
                    raise ValueError(
                        f"run_steps: {name!r} batches disagree on "
                        f"sequence count {sorted(n_seqs)}")
                nb = next_bucket(max(max(rows), 1))
                tb = next_bucket(max(max(mls), 1))
                padded_steps, splits_steps = [], []
                meta = None
                for v, lod in value:
                    padded, splits, meta = bucket_ragged_feed(
                        name, np.asarray(v), lod, n_bucket=nb,
                        t_bucket=tb)
                    padded_steps.append(padded)
                    splits_steps.append(splits)
                per_step_feed[name] = _as_device_array(
                    np.stack(padded_steps), dtype, device)
                per_step_feed[name + SPLITS_SUFFIX] = _as_device_array(
                    np.stack(splits_steps), "int32", device)
                scope.set_lod(name, meta)
                continue
            if is_lod_pair(value):
                raise ValueError(
                    f"run_steps does not support a single LoD feed (got "
                    f"one for {name!r}); pass a LIST of per-step "
                    f"(value, lod) batches under program.lod_buckets, "
                    f"or bucket/pad ragged batches and use run()")
            var = block.var(name) if block.has_var(name) else None
            dtype = var.dtype if var is not None else None
            arr = _as_device_array(value, dtype, device)
            want_shape = tuple(var.shape) \
                if var is not None and var.shape is not None else None
            # an array with exactly one extra leading dim of length `steps`
            # is treated as stacked per-step batches (documented behavior;
            # reshape away any coincidental match)
            if want_shape is not None and arr.ndim == len(want_shape) + 1 \
                    and arr.shape[0] == steps:
                per_step_feed[name] = arr        # stacked [steps, ...]
            else:
                const_feed[name] = arr           # one batch, reused
            scope.set_lod(name, None)

        # reader ops: pull `steps` batches and ride the per-step axis of
        # the device-side loop (double-buffer + scan = the full pipeline)
        reader_feed = {}
        _run_reader_ops(block, scope, reader_feed, device, steps=steps)
        per_step_feed.update(reader_feed)

        sample = dict(const_feed)
        sample.update({n: a[0] for n, a in per_step_feed.items()})
        parts = self._prepare(program, block, sample, tuple(fetch_names),
                              scope)
        sig = parts["sig"] + ("run_steps", steps,
                              tuple(sorted(per_step_feed)))
        step = parts["step"]
        inout_names = parts["inout_names"]
        create_state = parts["create_state"]
        ro_names = parts["ro_names"]

        ro_state = {n: self._state_value(scope, n, device)
                    for n in ro_names}
        inout_state = {n: self._state_value(scope, n, device)
                       for n in inout_names}

        self._run_counter += 1
        base_key = jax.random.PRNGKey(
            (program.random_seed or 0) * 1000003 + self._run_counter)

        if parts["interpret"]:
            # host ops: plain Python loop (still correct, just not fused)
            keys = jax.random.split(base_key, steps)
            outs = []
            for i in range(steps):
                feeds_i = dict(const_feed)
                feeds_i.update({n: a[i] for n, a in per_step_feed.items()})
                fetches, new_state = step(feeds_i, ro_state, inout_state,
                                          keys[i])
                inout_state = dict(inout_state)
                inout_state.update(new_state)
                outs.append(fetches)
            for n, v in inout_state.items():
                scope.set_var(n, v)
            stacked = [jnp.stack([o[i] for o in outs])
                       for i in range(len(fetch_names))]
            return [np.asarray(v) for v in stacked] if return_numpy \
                else stacked

        from paddle_tpu import profiler as _profiler
        if sig in self._cache:
            self._cache[sig] = self._cache.pop(sig)
            fn = self._cache[sig]
            _profiler.runtime_metrics.inc("jit_cache.hits")
        else:
            _profiler.runtime_metrics.inc("jit_cache.misses")
            def multi(const_feeds, per_feeds, ro_state, carry, base_key):
                keys = jax.random.split(base_key, steps)

                def body(carry, xs):
                    key, step_feeds = xs
                    feeds = dict(const_feeds)
                    feeds.update(step_feeds)
                    fetches, new_state = step(feeds, ro_state, carry, key)
                    new_carry = {n: new_state.get(n, carry[n])
                                 for n in carry}
                    return new_carry, tuple(fetches)

                carry, ys = jax.lax.scan(body, carry, (keys, per_feeds))
                return ys, carry

            fn = jax.jit(multi, donate_argnums=(3,))
            from paddle_tpu.obs import perf as _perf
            if _perf.capture_enabled():
                fn = _perf.instrument_jit(
                    fn, label=_perf.jit_label(
                        per_step_feed or const_feed, fetch_names,
                        tag=f"scan{steps}"))
            self._cache_insert(sig, fn)

        carry = dict(inout_state)
        # write-only persistables (create_state) ride the carry too so the
        # final value lands back in the scope like run() does; uninitialized
        # ones are seeded with zeros of their traced shape
        missing = [n for n in create_state if n not in carry]
        seeded = [n for n in missing if scope.find_var(n) is not None]
        for n in seeded:
            carry[n] = self._state_value(scope, n, device)
        still = [n for n in missing if n not in carry]
        if still:
            _, out_shapes = jax.eval_shape(
                step, sample, ro_state, inout_state, jax.random.PRNGKey(0))
            for n in still:
                if n in out_shapes:
                    sd = out_shapes[n]
                    carry[n] = jnp.zeros(sd.shape, sd.dtype)
        t0 = time.perf_counter()
        ys, final = fn(const_feed, per_step_feed, ro_state, carry, base_key)
        for n, v in final.items():
            scope.set_var(n, v)
        result = [np.asarray(v) for v in ys] if return_numpy else list(ys)
        from paddle_tpu.obs import perf as _perf
        gauge = _mfu_gauge_for(program)
        if return_numpy and gauge:
            # MFU over the whole on-device window: XLA's cost analysis
            # counts the scan BODY once regardless of trip count, so
            # the captured FLOPs scale by `steps`; ONLY the numpy
            # conversion above blocks on the device, so only this path
            # yields an honest window wall time (async submit time
            # would overstate MFU by orders of magnitude)
            holder = getattr(fn, "perf", None)
            _perf.note_step(holder["record"] if holder else None,
                            time.perf_counter() - t0,
                            gauge=gauge,
                            devices=getattr(self, "device_count", 1),
                            flops_scale=steps)
        _perf.census_tick(scope)
        return result

    # ------------------------------------------------------------------
    def run_pipeline(self, program=None, pipeline=None, fetch_list=None,
                     scope=None, max_steps=None, return_numpy=True,
                     on_step=None, sentinel=None, ledger=None):
        """Drive one epoch (or ``max_steps`` batches) of a
        ``datapipe`` pipeline through :meth:`run`.

        Each batch must be a feed dict (``name -> array``) — the shape a
        ``Batch`` stage with dict samples (or a custom collate) emits;
        batches already placed by a ``DevicePrefetch`` stage skip the
        host->device copy inside :meth:`run`.  Fires the ``train.step``
        failpoint per batch (so ``PADDLE_TPU_CHAOS`` kill drills target
        this loop) and records ``datapipe.step_seconds``.  Stopping at
        ``max_steps`` closes the iterator cleanly: threaded stages
        quiesce with their position intact, so a following
        ``pipeline.state_dict()`` checkpoints mid-epoch.

        ``on_step(step_index, fetches)`` runs after each batch (metrics,
        checkpointing).  Returns the list of per-batch fetch lists.

        ``sentinel``: a :class:`paddle_tpu.fault.Sentinel` turns this
        loop into the automatic recovery loop — a tripped check skips
        the poisoned update, quarantines the batch as a repro bundle,
        and after K strikes rolls back to the sentinel's last
        known-good checkpoint (which also rewinds the pipeline's
        iterator position) and resumes.  Skipped steps never appear in
        the returned fetch lists, and a rollback also drops the entries
        it rewound (their batches re-run and re-append), so each
        applied batch appears exactly once.

        ``ledger``: a :class:`paddle_tpu.obs.ledger.RunLedger` appends
        one step row per APPLIED batch (skipped/poisoned steps write no
        row), BEFORE ``on_step`` runs — so a checkpoint committed by
        ``on_step`` carries a sidecar whose ``rows_total`` includes its
        own step, the exactly-once resume invariant.  When omitted, the
        sentinel's checkpoint manager's ``ledger`` attribute (if any)
        is used, so wiring the ledger into the manager arms the whole
        loop.  Disabled path is a single ``None`` check per step."""
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.fault import chaos as _chaos
        from paddle_tpu.fault.sentinel import NumericalFault
        if pipeline is None:
            raise ValueError("run_pipeline requires a datapipe pipeline")
        outs = []
        # checkpoint step -> len(outs) when the manager committed it,
        # keyed by the step number the checkpoint was SAVED under (which
        # need not match this loop's 0-based index — a resumed trainer
        # may number globally); observed via the manager's in-process
        # last_committed_step after each on_step so the rollback branch
        # can truncate exactly.  NOT latest_step(): that lists the
        # directory (per-step I/O), and a restarted trainer renumbering
        # from 0 under a directory still holding a prior run's higher
        # ckpt-N would never see its own commits through it
        marks = {}
        mgr = sentinel.manager if sentinel is not None else None
        last_ckpt = getattr(mgr, "last_committed_step", None) \
            if mgr is not None else None
        if ledger is None and mgr is not None:
            ledger = getattr(mgr, "ledger", None)
        fetch_name_list = [v.name if hasattr(v, "name") else str(v)
                           for v in (fetch_list or [])]
        it = iter(pipeline)
        try:
            step = 0
            # check the budget BEFORE pulling: a batch pulled past the
            # limit would be dropped (lost from the resume sequence)
            while max_steps is None or step < max_steps:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                stall = time.perf_counter() - t0
                # recorded only on success: a normal epoch-end
                # StopIteration is not an error-tagged span
                _record_span("datapipe.next", t0, stall, step=step)
                _chaos.fire("train.step", step=step)
                try:
                    with _span("train.step", step=step):
                        with _profiler.record_latency(
                                "datapipe.step_seconds"):
                            # program by KEYWORD: ParallelExecutor.run's
                            # first positional is fetch_list, not program
                            fetches = self.run(program=program, feed=batch,
                                               fetch_list=fetch_list,
                                               scope=scope,
                                               return_numpy=return_numpy,
                                               sentinel=sentinel)
                        if ledger is not None:
                            ledger.note_step(fetch_names=fetch_name_list,
                                             fetches=fetches,
                                             stall_seconds=stall)
                        if on_step is not None:
                            on_step(step, fetches)
                except NumericalFault as fault:
                    if sentinel is None:
                        raise
                    restored = sentinel.handle_fault(fault, step=step)
                    if restored is not None:
                        mgr = sentinel.manager
                        if getattr(mgr, "last_restore_rewound", False) \
                                and hasattr(pipeline, "load_state_dict"):
                            # the rollback rewound the pipeline's
                            # position; the open iterator still points
                            # at the pre-rollback stream — reopen from
                            # the restored state
                            close = getattr(it, "close", None)
                            if close is not None:
                                close()
                            it = iter(pipeline)
                            # drop the entries the rollback undid:
                            # their batches re-run from the rewound
                            # stream, keeping the returned list
                            # exactly-once.  The mark maps the restored
                            # checkpoint number back to this loop's own
                            # outs length; a checkpoint this loop never
                            # committed (restart resuming a prior run's
                            # ckpt) rewinds past everything we returned
                            del outs[marks.get(restored, 0):]
                        else:
                            # params-only rollback: no datapipe on the
                            # manager, or the restored checkpoint
                            # carried no iterator state — the stream
                            # cannot be rewound.  Keep consuming the
                            # current iterator (reopening would restart
                            # the epoch) and say what was lost
                            logger.warning(
                                "sentinel rollback restored step %s "
                                "params-only (no datapipe state to "
                                "rewind): batches since that step "
                                "cannot be replayed — attach datapipe= "
                                "to CheckpointManager for exact-once "
                                "semantics", restored)
                    step += 1
                    continue
                outs.append(fetches)
                if mgr is not None and on_step is not None:
                    # did on_step commit a checkpoint this step?  Its
                    # saved position is AFTER this batch, so the mark
                    # includes the entry just appended
                    ckpt = getattr(mgr, "last_committed_step", None)
                    if ckpt is not None and ckpt != last_ckpt:
                        marks[ckpt] = len(outs)
                        last_ckpt = ckpt
                step += 1
        finally:
            close = getattr(it, "close", None)  # plain iterables lack it
            if close is not None:
                close()
        return outs

    # ------------------------------------------------------------------
    def _feed_device(self):
        """Target placement for feed arrays; ParallelExecutor overrides to
        None so sharded placement happens against the mesh instead."""
        return self.place.jax_device()

    # ------------------------------------------------------------------
    def _state_value(self, scope, name, device):
        v = scope.find_var(name)
        if v is None:
            raise RuntimeError(
                f"variable {name!r} is not initialized in the scope — "
                f"run the startup program first")
        if isinstance(v, np.ndarray):
            # commit to the target device: mixed committed/uncommitted
            # arguments would give the same computation two jit signatures
            # (one extra compile on the second call)
            v = jax.device_put(jnp.asarray(v), device) if device is not None \
                else jnp.asarray(v)
            scope.set_var(name, v)
        return v

    # ------------------------------------------------------------------
    def _signature(self, program, block, feed_arrays, fetch_names, scope):
        """Cheap cache key — no per-op work, safe to compute every step.

        LoD (ragged row-splits) is static trace-time metadata on TPU: a
        distinct lod means a distinct compiled executable (bucket batches
        upstream to bound recompiles; reference carries LoD on the tensor,
        lod_tensor.h:110).
        """
        feed_lods = tuple(sorted(
            (n, _freeze_lod(scope.find_lod(n))) for n in feed_arrays
            if scope.find_lod(n) is not None))
        from paddle_tpu import profiler as _profiler
        from paddle_tpu.obs import numerics as _numerics
        return (id(program), program._version, block.idx, _amp_enabled(program),
                id(scope),  # interpret-mode steps bind the scope (ScopeEnv)
                _profiler.op_profiling_enabled(),  # forces interpret mode
                _numerics.probing_enabled(),  # forces interpret mode
                bool(getattr(program, "_release_memory", False)),
                tuple(sorted((n, str(a.dtype), a.shape)
                             for n, a in feed_arrays.items())),
                feed_lods,
                tuple(fetch_names))

    # ------------------------------------------------------------------
    def _prepare(self, program, block, feed_arrays, fetch_names, scope):
        """Classify block variables and build the traceable step function.

        Returns a dict with the cache signature, the (untraced) ``step``
        callable, the state-name partitions, and the interpret flag.
        O(#ops) — callers should hit the signature cache first.
        """
        sig = self._signature(program, block, feed_arrays, fetch_names,
                              scope)

        feed_names = tuple(sorted(feed_arrays))

        # classify non-feed external inputs (state) and written persistables
        produced = set(feed_names)
        reads = []
        writes = []
        for op in block.ops:
            if op.type in _SKIP_OPS:
                continue
            for n in op.input_arg_names:
                if n and n not in produced:
                    reads.append(n)
            for n in op.output_arg_names:
                if n:
                    produced.add(n)
                    writes.append(n)
        # also: sub-block reads of outer vars.  Conservatively include any
        # var referenced by sub-blocks of ops in this block.
        for op in block.ops:
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    for n in _external_reads(a, produced):
                        reads.append(n)

        state_names = []
        seen = set()
        for n in reads:
            if n not in seen and n not in feed_names:
                seen.add(n)
                state_names.append(n)

        written_state = []
        for n in writes:
            try:
                var = block.var(n)
            except KeyError:
                continue
            if var.persistable and n not in written_state:
                written_state.append(n)
        # fetched non-persistable vars that are never produced in this block
        # (e.g. fetching a param) are state reads handled below.
        for n in fetch_names:
            if n not in produced and n not in state_names and \
                    n not in feed_names:
                state_names.append(n)

        inout_names = tuple(n for n in state_names if n in written_state)
        ro_names = tuple(n for n in state_names if n not in written_state)
        # persistables written but never read still need write-back
        create_state = tuple(n for n in written_state if n not in inout_names)

        uses_rng = True  # cheap: always thread a key; XLA drops it if unused

        training = not program._is_inference
        from paddle_tpu import profiler as _profiler
        interpret = _has_host_ops(
            block, dyn=_lod_buckets_enabled(program))
        if interpret and not getattr(program, "expect_host_ops", False):
            _warn_host_op_cliff(program, block)
        interpret = interpret or _profiler.op_profiling_enabled()
        from paddle_tpu.obs import numerics as _numerics
        interpret = interpret or _numerics.probing_enabled()
        # the opt pipeline's compile-amortization gate: a run-once
        # initializer whose static cost proves the XLA compile can
        # never pay for itself executes op-by-op eagerly instead
        # (34-51% of the zoo's measured cold start; JAX PRNG is
        # deterministic across eager and compiled, so init values are
        # unchanged)
        interpret = interpret or getattr(program, "_opt_interpret",
                                         False)

        from paddle_tpu.lod import DynLoD, SPLITS_SUFFIX
        lod_map = {}
        for n in feed_arrays:
            lod = scope.find_lod(n)
            if lod is None:
                continue
            if isinstance(lod, tuple) and lod and lod[0] == "dyn":
                lod_map[n] = DynLoD(n + SPLITS_SUFFIX, lod[1], lod[2])
            else:
                lod_map[n] = [list(level) for level in lod]

        amp = _amp_enabled(program)

        persist_names = _persistable_names(program) if interpret else None

        # interpret-mode early release per the memory plan (the compiled
        # path needs none of this: XLA buffer assignment frees dead values)
        release_map = None
        if interpret and getattr(program, "_release_memory", False):
            plan = getattr(program, "_memory_plan", None)
            if plan is not None and block.idx in plan.last_use:
                protect = set(fetch_names) | set(inout_names) | \
                    set(create_state) | set(persist_names or ())
                dead_after = {}
                for name, idx in plan.last_use[block.idx].items():
                    if name not in protect:
                        dead_after.setdefault(idx, []).append(name)
                stats = {"bytes": 0, "vars": 0}
                program._release_stats = stats  # measured drop, per run
                release_map = {block.idx: {"dead_after": dead_after,
                                           "stats": stats}}

        def step(feeds, ro_state, inout_state, rng_key):
            if interpret:
                # shared-scope semantics for persistables (CSP threads)
                env = ScopeEnv(scope, persist_names)
            else:
                env = {}
            env.update(feeds)
            env.update(ro_state)
            env.update(inout_state)
            aux = {"rng_counter": 0, "scope": scope,
                   "lower_block": lower_block, "lod": dict(lod_map),
                   "amp": amp, "interpret": interpret, "block": block,
                   # set only by the opt pipeline: ops statically
                   # proven key-free skip their per-op fold_in
                   "rng_plan": True
                   if getattr(program, "_opt_rng_plan", False)
                   else None}
            if release_map is not None:
                stats = release_map[block.idx]["stats"]
                stats["bytes"] = stats["vars"] = 0  # per-run measurement
                aux["release"] = release_map
            # whole-step scope: every emitted HLO op (including scan/
            # slicing glue outside the per-op ptop_ scopes) carries it,
            # so tenant-proof WHOLE-STEP device time is one
            # scope_device_seconds("pt_step") read
            with jax.named_scope("pt_step"):
                lower_block(block, env, rng_key, training, aux)
                fetches = [env[n] for n in
                           self.fetch_missing_check(fetch_names, env)]
                new_state = {n: env[n]
                             for n in inout_names + create_state
                             if n in env}
            return fetches, new_state

        # which inout state names are Parameters — the sentinel's fused
        # health norms (train.param_norm / train.grad_norm) reduce over
        # exactly these
        param_names = tuple(
            n for n in inout_names + create_state
            if isinstance(_safe_var(block, n), framework.Parameter))

        return {"sig": sig, "step": step, "feed_names": feed_names,
                "ro_names": ro_names, "inout_names": inout_names,
                "create_state": create_state, "interpret": interpret,
                "uses_rng": uses_rng, "param_names": param_names}

    # ------------------------------------------------------------------
    def _get_compiled(self, program, block, feed_arrays, fetch_names, scope,
                      donate=True):
        from paddle_tpu import profiler as _profiler
        # donation is part of the executable's identity: a sentinel-
        # guarded step (donate=False) must be able to discard its update,
        # so the pre-step state buffers have to stay valid
        sig = self._signature(program, block, feed_arrays, fetch_names,
                              scope) + (("donate", donate),)
        if sig in self._cache:
            self._cache[sig] = self._cache.pop(sig)  # LRU bump
            _profiler.runtime_metrics.inc("jit_cache.hits")
            return self._cache[sig]
        _profiler.runtime_metrics.inc("jit_cache.misses")
        with _profiler.record_latency("executor.prepare_seconds"):
            parts = self._prepare(program, block, feed_arrays, fetch_names,
                                  scope)

        if parts["interpret"]:
            # op-by-op eager execution — needed when a host op (data-
            # dependent shapes, numpy DP) is in the block; the reference's
            # analogous path is its per-op CPU-kernel interpreter
            fn = parts["step"]
        else:
            fn = jax.jit(parts["step"],
                         donate_argnums=(2,) if donate else ())
            from paddle_tpu.obs import perf as _perf
            if _perf.capture_enabled():
                # the first call AOT-compiles and captures the cost/
                # memory record for this jit key (paddle_tpu profile
                # compile, the live MFU gauge, the headroom check)
                fn = _perf.instrument_jit(
                    fn, label=_perf.jit_label(feed_arrays, fetch_names))
        compiled = _CompiledBlock(fn, parts["feed_names"],
                                  parts["ro_names"], parts["inout_names"],
                                  tuple(fetch_names), parts["uses_rng"])
        compiled.donated = donate and not parts["interpret"]
        compiled.perf = getattr(fn, "perf", None)
        compiled.param_names = parts["param_names"]
        self._cache_insert(sig, compiled)
        return compiled

    @staticmethod
    def fetch_missing_check(fetch_names, env):
        for n in fetch_names:
            if n not in env:
                raise KeyError(f"fetch target {n!r} was not produced by the "
                               f"program and is not in the scope")
        return fetch_names

    def close(self):
        self._cache.clear()


def _safe_var(block, name):
    try:
        return block.var(name)
    except Exception:
        return None


def _mfu_gauge_for(program):
    """Which MFU gauge a program's dispatches feed: an explicit
    ``_mfu_gauge`` tag wins (GenPredictor tags its decode program
    ``gen.decode_mfu``); untagged TRAINING programs land in
    ``train.mfu``; untagged inference programs (a serving Predictor, a
    prefill) derive none — a one-shot prefill must not overwrite the
    training/decode gauges the fleet rollups read."""
    tagged = getattr(program, "_mfu_gauge", None)
    if tagged:
        return tagged
    return None if program._is_inference else "train.mfu"


def _enforce_feed(name, value, var):
    """PADDLE_ENFORCE-style feed validation (reference ``enforce.h`` +
    runtime InferShape): catch shape/rank mismatches at the feed boundary
    with a named message instead of a deep XLA trace error."""
    if var is None or var.shape is None:
        return
    shape = np.shape(value)
    want = tuple(var.shape)
    if len(shape) != len(want):
        raise ValueError(
            f"feed variable {name!r}: expected rank {len(want)} "
            f"(shape {want}), got rank {len(shape)} (shape {shape})")
    ragged = getattr(var, "lod_level", 0) or 0
    for i, (got_d, want_d) in enumerate(zip(shape, want)):
        if i == 0 and ragged:
            continue  # LoD feeds have data-dependent row counts
        if want_d is not None and want_d >= 0 and got_d != want_d:
            raise ValueError(
                f"feed variable {name!r}: expected shape {want} "
                f"(-1 = any), got {shape}")


def _env_flag(name, default="0"):
    """Shared env-var truthiness parsing for the gflags-style config
    layer (SURVEY.md §5.6)."""
    import os
    return os.environ.get(name, default).strip().lower() \
        not in ("0", "", "false", "off", "no")


def _lod_buckets_enabled(program):
    """Bucketed dynamic-LoD mode (lod.py): per-program ``lod_buckets``
    attr or the PADDLE_TPU_LOD_BUCKETS env var."""
    if getattr(program, "lod_buckets", None) is not None:
        return bool(program.lod_buckets)
    return _env_flag("PADDLE_TPU_LOD_BUCKETS")


def _check_nan_inf_enabled(program):
    """check_nan_inf executor mode (reference FLAGS_check_nan_inf,
    ``executor.cc:28,352`` CheckTensorNANOrInf): per-program flag or the
    PADDLE_TPU_CHECK_NAN_INF env var."""
    if getattr(program, "check_nan_inf", None) is not None:
        return bool(program.check_nan_inf)
    return _env_flag("PADDLE_TPU_CHECK_NAN_INF")


def _check_nan_inf(fetch_names, fetches, new_state):
    """Raise naming the first non-finite fetched value or state var —
    the named-tensor diagnostic CheckTensorNANOrInf gives on the
    reference (a device-side jax debug_nans check would lose the name)."""
    def bad(v):
        try:
            a = np.asarray(v)
        except TypeError:
            return False
        return np.issubdtype(a.dtype, np.floating) and \
            not np.isfinite(a).all()

    for name, v in zip(fetch_names, fetches):
        if bad(v):
            raise RuntimeError(
                f"Operator output {name!r} contains NaN/Inf "
                f"(check_nan_inf mode)")
    for name, v in new_state.items():
        if bad(v):
            raise RuntimeError(
                f"Variable {name!r} contains NaN/Inf after the step "
                f"(check_nan_inf mode)")


def _amp_enabled(program):
    """Mixed precision: per-program ``Program.amp`` wins; env default
    PADDLE_TPU_AMP=1 covers existing scripts (gflags-style config,
    SURVEY.md §5.6)."""
    if getattr(program, "amp", None) is not None:
        return bool(program.amp)
    return _env_flag("PADDLE_TPU_AMP")


_WARNED_HOST_OP_BLOCKS = set()


def _warn_host_op_cliff(program, block):
    """One host op anywhere switches the WHOLE block to op-by-op eager
    execution — warn once per (program, block) naming the culprits so a
    user adding e.g. edit_distance to a training graph learns why the
    step got slow (VERDICT r1 'host-op cliff')."""
    key = (id(program), block.idx)
    if key in _WARNED_HOST_OP_BLOCKS:
        return
    _WARNED_HOST_OP_BLOCKS.add(key)
    culprits = []

    def scan(blk):
        for op in blk.ops:
            opdef = registry.lookup(op.type)
            if opdef is not None and opdef.host:
                culprits.append(op.type)
            for a in op.attrs.values():
                if isinstance(a, framework.Block):
                    scan(a)

    scan(block)
    import warnings
    warnings.warn(
        f"block {block.idx} contains host op(s) "
        f"{sorted(set(culprits))} — the whole block runs op-by-op eager "
        f"instead of one compiled XLA computation; keep host ops "
        f"(metrics/decoding) in a separate program to keep training "
        f"compiled", stacklevel=3)


def _has_host_ops(block, dyn=False):
    """``dyn=True`` (bucketed dynamic-LoD mode): ops whose bucketed
    branch is fully traced (``host_dyn_ok``) do not force interpret."""
    for op in block.ops:
        opdef = registry.lookup(op.type)
        if opdef is not None and opdef.host and \
                not (dyn and opdef.host_dyn_ok):
            return True
        for a in op.attrs.values():
            if isinstance(a, framework.Block) and _has_host_ops(a, dyn):
                return True
    return False


def _freeze_lod(lod):
    """Nested row-splits list -> hashable tuple (jit cache key component).
    Bucketed-mode metas ("dyn", B, T_bucket) are already hashable — that
    IS the point: the exact splits stay out of the key."""
    if lod is None:
        return None
    if isinstance(lod, tuple) and lod and lod[0] == "dyn":
        return lod
    return tuple(tuple(int(x) for x in level) for level in lod)


def _external_reads(block, produced_outer):
    """Names read inside ``block`` (recursively) that neither the block nor
    the outer trace produces — they must come from scope state."""
    produced = set(produced_outer)
    ext = []
    for op in block.ops:
        for n in op.input_arg_names:
            if n and n not in produced and not block.has_var_local(n):
                ext.append(n)
        for n in op.output_arg_names:
            produced.add(n)
        for a in op.attrs.values():
            if isinstance(a, framework.Block):
                ext.extend(_external_reads(a, produced))
    return ext


def fetch_var(name, scope=None, return_numpy=True):
    scope = scope or global_scope()
    v = scope.find_var(name)
    if v is None:
        raise KeyError(f"variable {name!r} not found in scope")
    return np.asarray(v) if return_numpy else v
