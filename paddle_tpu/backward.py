"""IR-level reverse-mode autodiff.

Re-design of the reference's ``python/paddle/fluid/backward.py``:
``append_backward(loss)`` walks the block's ops in reverse, asks each op's
grad maker for ``<type>_grad`` op descs (``_append_backward_ops_:273``),
sums duplicated gradients (``_addup_repetitive_outputs_:117``), prunes
branches where no path leads to a trainable input
(``_remove_no_grad_branch_:167``), and appends the grad ops to the program.

The grad ops are ordinary IR ops; the executor traces forward+backward+
optimizer into one XLA computation, so XLA's CSE and fusion see the whole
step (and dedupe the forward recomputation done by auto-vjp grad ops).
"""

from __future__ import annotations

import collections

from paddle_tpu import framework
from paddle_tpu.framework import grad_var_name, GRAD_SUFFIX, unique_name
from paddle_tpu.ops import registry

__all__ = ["append_backward", "calc_gradient"]


def _get_grad_maker(op):
    opdef = registry.lookup(op.type)
    if opdef is not None and not opdef.has_grad:
        return None
    if opdef is not None and opdef.grad_maker is not None:
        return opdef.grad_maker
    return registry.default_grad_maker


def _collect_no_grad_set(block, no_grad_set):
    result = set(no_grad_set or ())
    for var in block.vars.values():
        if var.stop_gradient:
            result.add(var.name)
    parent = block.parent_block
    while parent is not None:
        for var in parent.vars.values():
            if var.stop_gradient:
                result.add(var.name)
        parent = parent.parent_block
    return result


def _ops_on_path(block, loss_name, no_grad_set):
    """Indices of ops on a differentiable path from inputs to the loss."""
    needed = {loss_name}
    on_path = []
    for idx in range(len(block.ops) - 1, -1, -1):
        op = block.ops[idx]
        if any(o in needed for o in op.output_arg_names):
            on_path.append(idx)
            needed.update(op.input_arg_names)
    return set(on_path)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, target_gradient=None):
    """Append grad ops for ``loss``; returns list of (param, grad_var)
    (reference ``backward.py:425``).  ``target_gradient`` optionally seeds
    d(loss) with a caller-supplied cotangent Variable instead of ones."""
    assert isinstance(loss, framework.Variable)
    block = loss.block
    program = block.program
    no_grad = _collect_no_grad_set(block, no_grad_set)

    on_path = _ops_on_path(block, loss.name, no_grad)

    # seed: d loss / d loss = 1 (or the supplied cotangent)
    loss_grad_name = grad_var_name(loss.name)
    if target_gradient is not None:
        block.append_op(type="assign",
                        inputs={"X": [target_gradient.name]},
                        outputs={"Out": [loss_grad_name]})
    else:
        block.append_op(
            type="fill_constant",
            outputs={"Out": [loss_grad_name]},
            attrs={"shape": list(loss.shape or (1,)), "value": 1.0,
                   "dtype": loss.dtype})
    gv = block.create_var(name=loss_grad_name, shape=loss.shape or (1,),
                          dtype=loss.dtype)
    gv.stop_gradient = True

    # available grads: forward var name -> list of grad var names feeding it
    grads_of = collections.defaultdict(list)
    grads_of[loss.name].append(loss_grad_name)

    fwd_ops = [(i, op) for i, op in enumerate(block.ops[:])]

    for idx, op in reversed(fwd_ops):
        if idx not in on_path:
            continue
        maker = _get_grad_maker(op)
        if maker is None:
            continue
        # does any output of this op have a pending gradient?
        out_has_grad = any(n in grads_of for n in op.output_arg_names)
        if not out_has_grad:
            continue

        # materialize summed grads for this op's outputs
        for out_name in set(op.output_arg_names):
            glist = grads_of.get(out_name)
            if glist and len(glist) > 1:
                summed = grad_var_name(out_name)
                # sum into the canonical name (reference _addup_repetitive_)
                tmp = unique_name(summed + "@RENAME")
                block.append_op(type="sum", inputs={"X": list(glist)},
                                outputs={"Out": [tmp]})
                v0 = block.var(glist[0])
                nv = block.create_var(name=tmp, shape=v0.shape,
                                      dtype=v0.dtype)
                nv.stop_gradient = True
                grads_of[out_name] = [tmp]

        grad_descs, input_grad_map = maker(op, block, no_grad)
        for desc in grad_descs:
            # rewire grad-op inputs: slot S@GRAD names are canonical
            # grad_var_name()s; replace with the actual available grad vars
            actual_inputs = {}
            for slot, names in desc["inputs"].items():
                if slot.endswith(GRAD_SUFFIX):
                    base_names = desc["inputs"].get(slot[:-len(GRAD_SUFFIX)],
                                                    [])
                    actual = []
                    for i, n in enumerate(names):
                        base = base_names[i] if i < len(base_names) else None
                        if base is None and n.endswith(GRAD_SUFFIX):
                            # maker omitted the forward-output slot (e.g.
                            # dropout_grad takes Out@GRAD but not Out);
                            # canonical grad names encode the base var
                            base = n[:-len(GRAD_SUFFIX)]
                        if base is not None and base in grads_of:
                            actual.append(grads_of[base][0])
                        else:
                            actual.append("")  # missing grad -> zeros
                    actual_inputs[slot] = actual
                else:
                    actual_inputs[slot] = names
            # rename grad outputs that would collide with an existing
            # pending contribution (reference _addup_repetitive_outputs_:
            # a var read by N ops receives N distinct grad names, summed
            # at consumption time)
            actual_outputs = {}
            for slot, names in desc["outputs"].items():
                if not slot.endswith(GRAD_SUFFIX):
                    actual_outputs[slot] = list(names)
                    continue
                in_slot = slot[:-len(GRAD_SUFFIX)]
                fwd_names = desc["inputs"].get(in_slot, [])
                renamed = []
                for i, gname in enumerate(names):
                    if not gname:
                        renamed.append(gname)
                        continue
                    fwd_name = fwd_names[i] if i < len(fwd_names) else None
                    if fwd_name is not None and grads_of.get(fwd_name):
                        gname = unique_name(gname + "@RENAME")
                    renamed.append(gname)
                actual_outputs[slot] = renamed
            gop = block.append_op(type=desc["type"], inputs=actual_inputs,
                                  outputs=actual_outputs,
                                  attrs=desc["attrs"])
            if callbacks:
                for cb in callbacks:
                    cb(block, gop)
            # declare grad output vars + record availability
            for slot, names in actual_outputs.items():
                if not slot.endswith(GRAD_SUFFIX):
                    continue
                in_slot = slot[:-len(GRAD_SUFFIX)]
                fwd_names = desc["inputs"].get(in_slot, [])
                for i, gname in enumerate(names):
                    if not gname:
                        continue
                    fwd_name = fwd_names[i] if i < len(fwd_names) else None
                    if fwd_name is not None:
                        fv = block.var(fwd_name)
                        nv = block.create_var(name=gname, shape=fv.shape,
                                              dtype=fv.dtype)
                        nv.stop_gradient = True
                        if gname not in grads_of[fwd_name]:
                            grads_of[fwd_name].append(gname)

    # final dedup: leaf vars (params, feeds) have no producing op on the
    # path, so their pending contributions were never summed — sum them
    # into the canonical grad name now
    for fwd_name, glist in list(grads_of.items()):
        if len(glist) <= 1:
            continue
        canonical = grad_var_name(fwd_name)
        block.append_op(type="sum", inputs={"X": list(glist)},
                        outputs={"Out": [canonical]})
        try:
            fv = block.var(fwd_name)
            nv = block.create_var(name=canonical, shape=fv.shape,
                                  dtype=fv.dtype)
            nv.stop_gradient = True
        except KeyError:
            pass
        grads_of[fwd_name] = [canonical]

    param_and_grads = []
    if parameter_list is not None:
        params = [block.program.global_block().var(p)
                  if isinstance(p, str) else p for p in parameter_list]
    else:
        params = [p for p in program.global_block().all_parameters()
                  if p.trainable]
    for p in params:
        glist = grads_of.get(p.name, [])
        if not glist:
            continue
        if len(glist) > 1:
            canonical = grad_var_name(p.name)
            block.append_op(type="sum", inputs={"X": list(glist)},
                            outputs={"Out": [canonical]})
            nv = block.create_var(name=canonical, shape=p.shape,
                                  dtype=p.dtype)
            nv.stop_gradient = True
            grads_of[p.name] = [canonical]
        grad_var = block.var(grads_of[p.name][0])
        param_and_grads.append((p, grad_var))

    # post-transpile contract (paddle_tpu.analysis): the grad ops this
    # pass just appended must leave the program structurally well-formed
    # — a broken grad maker fails HERE with named ops/vars, not as an
    # XLA trace error at the first Executor.run
    from paddle_tpu.analysis import verify_transpiled
    verify_transpiled(program, where="backward.append_backward")
    return param_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of ``targets`` w.r.t. ``inputs`` (reference
    ``backward.py:555``).  Returns grad Variables aligned with inputs."""
    if isinstance(targets, framework.Variable):
        targets = [targets]
    if isinstance(inputs, framework.Variable):
        inputs = [inputs]
    if target_gradients is not None and not isinstance(target_gradients,
                                                      (list, tuple)):
        target_gradients = [target_gradients]
    assert len(targets) == 1, "calc_gradient supports a single target"
    names = [v.name for v in inputs]
    seed = target_gradients[0] if target_gradients else None
    pg = append_backward(targets[0], parameter_list=[],
                         no_grad_set=no_grad_set, target_gradient=seed)
    block = targets[0].block
    result = []
    for name in names:
        g = grad_var_name(name)
        result.append(block.var(g) if block.has_var(g) else None)
    return result
