"""Continuous-batching decode benchmark: aggregate tokens/s and
time-to-first-token under N closed-loop clients with MIXED generation
lengths, iteration-level admission (``gen_admission=continuous``) vs
the PR 2 request-level batching semantics (``gen_admission=batch``:
new requests admitted only between whole batches — each batch runs
start-to-finish as a unit, exactly how one-shot ``/predict`` generation
holds its MicroBatcher slot for the full sequence).

Device work is MODELED WITH A SLEEP — the ``gen.decode.stall``
failpoint (armed ``delay:SECS``) fires once per decode ITERATION inside
the predictor lock, so the server behaves like one device that advances
the whole slot batch per fixed-cost step while the GIL stays free.  On
the 2-vCPU bench host that is the honest cost model: what the bench
measures is pure scheduling capability — slot occupancy.  Request-level
batching finishes a mixed-length batch at the pace of its LONGEST
member (short sequences hold dead slots; arrivals queue behind the
whole batch), while continuous batching refills slots between steps.
The tokens/s ratio is that occupancy gap; the TTFT gap is admission
latency (next-step admission vs wait-for-batch-drain).

    python bench_decode.py --clients 8 --duration 3 --out BENCH_DECODE.json
"""

from __future__ import annotations

import argparse
import json
import tempfile
import threading
import time

# short 4s dominate with a heavy tail: request-level batches then run
# ~MAX steps while holding mostly-finished slots
DEFAULT_LENGTHS = (4, 4, 4, 48, 4, 4, 32, 4)


def build_bundle(dirname, num_slots=8):
    """Toy-scale generation bundle: the decode compute is deliberately
    negligible — the armed ``gen.decode.stall`` delay IS the device
    time."""
    from paddle_tpu.models import gen_lm
    gen_lm.export_gen_model(dirname, gen_lm.GenConfig(),
                            num_slots=num_slots)
    return dirname


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]


def _stream_generate(host, port, prompt, max_new, timeout=120):
    """One streamed /generate; returns (ttft_seconds, tokens)."""
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    t0 = time.perf_counter()
    conn.request("POST", "/generate",
                 json.dumps({"prompt": prompt,
                             "max_new_tokens": max_new}).encode(),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        resp.read()
        conn.close()
        raise RuntimeError(f"/generate replied {resp.status}")
    ttft = None
    tokens = 0
    while True:
        line = resp.readline()
        if not line:
            break
        ev = json.loads(line)
        if "token" in ev:
            if ttft is None:
                ttft = time.perf_counter() - t0
            tokens += 1
        if ev.get("done"):
            break
    conn.close()
    return ttft, tokens


def run_mode(bundle_dir, admission, clients, duration, step_ms,
             lengths=DEFAULT_LENGTHS, prompt_len=4):
    """One serving run: closed-loop clients against a gen server with
    the given admission policy; device time = ``step_ms`` per decode
    iteration.  Returns the stats dict."""
    from paddle_tpu.fault import chaos
    from paddle_tpu.serving import InferenceServer

    chaos.clear()
    chaos.inject("gen.decode.stall", delay=step_ms / 1000.0)
    server = InferenceServer(bundle_dir, port=0, warmup=True,
                             request_timeout=120.0,
                             gen_admission=admission,
                             gen_queue_size=256)
    server.start_background()
    try:
        assert server.wait_until_ready(300)
        host, port = server.addr
        stats = [{"ttfts": [], "tokens": 0, "requests": 0,
                  "failures": []} for _ in range(clients)]

        def loop(idx, out, stop_at):
            i = 0
            while time.monotonic() < stop_at:
                n = lengths[(idx + i) % len(lengths)]
                prompt = [1 + ((idx + i + j) % 40)
                          for j in range(prompt_len)]
                i += 1
                try:
                    ttft, tokens = _stream_generate(host, port, prompt, n)
                    out["ttfts"].append(ttft)
                    out["tokens"] += tokens
                    out["requests"] += 1
                except Exception as e:     # a LOST request
                    out["failures"].append(repr(e))

        stop_at = time.monotonic() + duration
        threads = [threading.Thread(target=loop,
                                    args=(i, stats[i], stop_at))
                   for i in range(clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t_start
        ttfts = [t for s in stats for t in s["ttfts"] if t is not None]
        tokens = sum(s["tokens"] for s in stats)
        failures = [f for s in stats for f in s["failures"]]
        return {
            "admission": admission,
            "clients": clients,
            "requests_ok": sum(s["requests"] for s in stats),
            "failures": len(failures),
            "failure_samples": failures[:3],
            "elapsed_sec": elapsed,
            "tokens": tokens,
            "tokens_per_sec": tokens / elapsed if elapsed > 0 else 0.0,
            "ttft_ms": {
                "p50": (_percentile(ttfts, 50) or 0) * 1e3,
                "p99": (_percentile(ttfts, 99) or 0) * 1e3,
            },
        }
    finally:
        chaos.clear()
        server.shutdown()


def run_bench(clients=8, duration=3.0, step_ms=20.0, bundle_dir=None,
              lengths=DEFAULT_LENGTHS):
    """Continuous vs request-level admission over the same bundle and
    cost model; returns the JSON-ready summary."""
    if bundle_dir is None:
        bundle_dir = build_bundle(
            tempfile.mkdtemp(prefix="ptdecode_") + "/bundle")
    kw = dict(clients=clients, duration=duration, step_ms=step_ms,
              lengths=lengths)
    continuous = run_mode(bundle_dir, "continuous", **kw)
    batch = run_mode(bundle_dir, "batch", **kw)
    ratio = continuous["tokens_per_sec"] / batch["tokens_per_sec"] \
        if batch["tokens_per_sec"] else None
    return {
        "clients": clients,
        "duration_sec": duration,
        "decode_step_ms": step_ms,
        "gen_lengths": list(lengths),
        "modes": {"continuous": continuous, "request_level": batch},
        "tokens_per_sec_ratio": ratio,
        "ttft_p99_ms": {
            "continuous": continuous["ttft_ms"]["p99"],
            "request_level": batch["ttft_ms"]["p99"],
        },
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--step-ms", type=float, default=20.0)
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    summary = run_bench(clients=args.clients, duration=args.duration,
                        step_ms=args.step_ms)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("decode", summary, args,
                                   "bench_decode.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
