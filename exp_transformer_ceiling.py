"""Transformer-base MFU ceiling artifact (r5) — the ResNet-style rigor
(BENCH_RESNET_CEILING.md) applied to the flagship bench model.

Two measurements, both tenant-proof DEVICE time (xplane named scopes;
wall clocks on this backend carry dispatch/sync latency and foreign
tenants — see profiler.measure_device_seconds):

  part A (``ours``):    per-IR-op decomposition of the framework's
                        Transformer-base training step (B=256, S=256,
                        bf16 AMP, Adam) via the executor's ptop_ scopes,
                        async-DMA excluded — replacing the discredited
                        r3 accounting.
  part B (``purejax``): a hand-written pure-JAX training step of the
                        SAME model (same shapes, post-LN, composed
                        attention, dropout 0.1, bf16 casts at matmul
                        inputs with f32 master params, f32 Adam) — the
                        toolchain bound: no Program IR, no executor, no
                        framework overhead.  What XLA gives this step is
                        the ceiling for ours.

Run:  python exp_transformer_ceiling.py ours|purejax|both

Reference workload: /root/reference/benchmark/fluid/machine_translation.py:1
(Transformer/NMT flagship); model config mirrors
test_parallel_executor.py:308 ModelHyperParams.
"""

import os
import sys
import tempfile
from functools import partial

os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"

import numpy as np

BATCH = int(os.environ.get("CEIL_BATCH", "256"))
SEQ = int(os.environ.get("CEIL_SEQ", "256"))
STEPS = int(os.environ.get("CEIL_STEPS", "16"))


# --------------------------------------------------------------------------
# part A: the framework step, per-op attributed
# --------------------------------------------------------------------------

def run_ours():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import profiler
    from paddle_tpu.models import transformer as T

    hp = T.ModelHyperParams()
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        avg_cost, _ = T.transformer(BATCH, SEQ, SEQ, hp)
        fluid.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    main_prog.amp = True

    batches = [T.fake_batch(BATCH, SEQ, SEQ, hp, seed=s)
               for s in range(STEPS)]
    stacked = {k: jax.device_put(np.stack([b[k] for b in batches]))
               for k in batches[0]}
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(2):  # compile + settle
            exe.run_steps(main_prog, feed=stacked,
                          fetch_list=[avg_cost.name], steps=STEPS)
        td = tempfile.mkdtemp(prefix="ptceil_")
        jax.profiler.start_trace(td)
        exe.run_steps(main_prog, feed=stacked,
                      fetch_list=[avg_cost.name], steps=STEPS)
        jax.profiler.stop_trace()

    # tenant-proof total: every event inside one of OUR ptop_ scopes
    total_s = profiler.scope_device_seconds(td, "ptop_")
    _, rows = profiler.compiled_op_table(td)
    import shutil
    shutil.rmtree(td, ignore_errors=True)
    print(f"OURS device: {total_s * 1e3 / STEPS:.2f} ms/step "
          f"(scope-attributed, async-excluded, {STEPS} steps)")
    for op, calls, sec in rows:
        if sec * 1e3 / STEPS >= 0.05:
            print(f"  {op:34s} {calls:6d} {sec * 1e3 / STEPS:9.3f} ms/step")
    return total_s / STEPS


# --------------------------------------------------------------------------
# part B: pure-JAX same-model training step (the toolchain bound)
# --------------------------------------------------------------------------

def run_purejax():
    import jax
    import jax.numpy as jnp
    from paddle_tpu import profiler
    from paddle_tpu.models.transformer import (ModelHyperParams,
                                               position_encoding_init)

    hp = ModelHyperParams()
    D, DFF, H, DK = hp.d_model, hp.d_inner_hid, hp.n_head, hp.d_key
    V, NL, DROP = hp.src_vocab_size, hp.n_layer, hp.dropout
    if os.environ.get("CEIL_DROP") is not None:
        DROP = float(os.environ["CEIL_DROP"])
    bf16 = jnp.bfloat16

    rng = np.random.RandomState(0)

    def w(*shape):
        return jnp.asarray(rng.normal(0, 0.02, shape), jnp.float32)

    def layer_params(cross):
        p = {"q": w(D, D), "k": w(D, D), "v": w(D, D), "o": w(D, D),
             "ln1_g": jnp.ones(D), "ln1_b": jnp.zeros(D),
             "f1": w(D, DFF), "f1b": jnp.zeros(DFF),
             "f2": w(DFF, D), "f2b": jnp.zeros(D),
             "ln2_g": jnp.ones(D), "ln2_b": jnp.zeros(D)}
        if cross:
            p.update({"cq": w(D, D), "ck": w(D, D), "cv": w(D, D),
                      "co": w(D, D),
                      "ln3_g": jnp.ones(D), "ln3_b": jnp.zeros(D)})
        return p

    params = {
        "src_emb": w(V, D), "trg_emb": w(V, D), "proj": w(D, V),
        "enc": [layer_params(False) for _ in range(NL)],
        "dec": [layer_params(True) for _ in range(NL)],
    }
    pos_tab = jnp.asarray(position_encoding_init(hp.max_length, D))
    causal = jnp.triu(jnp.full((1, 1, SEQ, SEQ), -1e9, jnp.float32), 1)

    def scoped(name):
        def deco(fn):
            def wrapped(*a, **kw):
                with jax.named_scope(name):
                    return fn(*a, **kw)
            return wrapped
        return deco

    @scoped("pjx_ln")
    def ln(x, g, b):
        x = x.astype(jnp.float32)
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * g + b

    @scoped("pjx_drop")
    def drop(x, key, i):
        if not DROP:
            return x
        keep = jax.random.bernoulli(jax.random.fold_in(key, i),
                                    1.0 - DROP, x.shape)
        return jnp.where(keep, x, jnp.zeros((), x.dtype))

    def mm(x, wmat):  # AMP discipline: bf16 at every matmul input
        return x.astype(bf16) @ wmat.astype(bf16)

    @scoped("pjx_attn")
    def attention(x, kv, p, bias, pre):
        B, S = x.shape[0], x.shape[1]
        q = mm(x, p[pre + "q"]).reshape(B, S, H, DK).transpose(0, 2, 1, 3)
        k = mm(kv, p[pre + "k"]).reshape(B, -1, H, DK).transpose(0, 2, 1, 3)
        v = mm(kv, p[pre + "v"]).reshape(B, -1, H, DK).transpose(0, 2, 1, 3)
        # bf16 scores end-to-end: the f32 [B,H,S,S] temporaries otherwise
        # push the step past HBM (the framework's f32-score path relies on
        # XLA remat; the bound should be the lean formulation)
        with jax.named_scope("pjx_sdpa"):
            s = (q @ k.transpose(0, 1, 3, 2)) * (DK ** -0.5) \
                + bias.astype(bf16)
            wts = jax.nn.softmax(s, axis=-1)
            ctx = (wts @ v).transpose(0, 2, 1, 3).reshape(B, S, D)
        return mm(ctx, p[pre + "o"])

    @scoped("pjx_ffn")
    def ffn(x, p):
        h = jax.nn.relu(mm(x, p["f1"]) + p["f1b"])
        return mm(h, p["f2"]) + p["f2b"]

    def loss_fn(ps, batch, key):
        src, trg = batch["src_word"], batch["trg_word"]
        lbl, lw = batch["lbl_word"], batch["lbl_weight"]
        pad_bias = ((batch["src_mask"] * 1e9) - 1e9) \
            .reshape(BATCH, 1, 1, SEQ)
        ki = iter(range(100))

        def embed(ids, tab):
            e = tab[ids] * (D ** 0.5) + pos_tab[:SEQ][None]
            return drop(e, key, next(ki))

        def enc_layer(x, p, k0):
            a = attention(x, x, p, pad_bias, "")
            x = ln(x + drop(a, key, k0), p["ln1_g"], p["ln1_b"])
            return ln(x + drop(ffn(x, p), key, k0 + 1),
                      p["ln2_g"], p["ln2_b"])

        def dec_layer(y, enc_out, p, k0):
            a = attention(y, y, p, causal, "")
            y = ln(y + drop(a, key, k0), p["ln1_g"], p["ln1_b"])
            c = attention(y, enc_out, p, pad_bias, "c")
            y = ln(y + drop(c, key, k0 + 1), p["ln3_g"], p["ln3_b"])
            return ln(y + drop(ffn(y, p), key, k0 + 2),
                      p["ln2_g"], p["ln2_b"])

        if os.environ.get("CEIL_REMAT"):
            # per-layer remat, matmul outputs saved — the standard
            # pure-JAX memory/FLOPs trade (jax.checkpoint docs)
            pol = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            enc_layer = jax.checkpoint(enc_layer, policy=pol,
                                       static_argnums=(2,))
            dec_layer = jax.checkpoint(dec_layer, policy=pol,
                                       static_argnums=(3,))

        x = embed(src, ps["src_emb"])
        for li, p in enumerate(ps["enc"]):
            x = enc_layer(x, p, 2 + 2 * li)
        enc_out = x
        y = embed(trg, ps["trg_emb"])
        for li, p in enumerate(ps["dec"]):
            y = dec_layer(y, enc_out, p, 20 + 3 * li)
        with jax.named_scope("pjx_ce"):
            logits16 = mm(y, ps["proj"])  # bf16 residual (1.3G, not 2.6G)
            logits = logits16.astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            nll = lse - jnp.take_along_axis(logits, lbl[..., None],
                                            -1).squeeze(-1)
            return (nll * lw).sum() / lw.sum()

    # f32 Adam on the f32 master params
    def adam_update(g, p, m, v, t):
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        return p - 1e-4 * mh / (jnp.sqrt(vh) + 1e-8), m, v

    opt = {"m": jax.tree.map(jnp.zeros_like, params),
           "v": jax.tree.map(jnp.zeros_like, params),
           "t": jnp.zeros((), jnp.int32)}

    batches = {
        "src_word": rng.randint(1, V, (STEPS, BATCH, SEQ)).astype("int32"),
        "trg_word": rng.randint(1, V, (STEPS, BATCH, SEQ)).astype("int32"),
        "lbl_word": rng.randint(1, V, (STEPS, BATCH, SEQ)).astype("int32"),
        "src_mask": np.ones((STEPS, BATCH, SEQ), "float32"),
        "lbl_weight": np.ones((STEPS, BATCH, SEQ), "float32"),
    }
    batches = {k: jax.device_put(v) for k, v in batches.items()}

    def body(carry, batch):
        ps, op = carry
        with jax.named_scope("pjxstep"):
            t = op["t"] + 1
            key = jax.random.fold_in(jax.random.PRNGKey(0), t)
            loss, grads = jax.value_and_grad(loss_fn)(ps, batch, key)
            with jax.named_scope("pjx_adam"):
                flat_g, treedef = jax.tree.flatten(grads)
                flat = [adam_update(g.astype(jnp.float32), p, m, v, t)
                        for g, p, m, v in zip(
                            flat_g, treedef.flatten_up_to(ps),
                            treedef.flatten_up_to(op["m"]),
                            treedef.flatten_up_to(op["v"]))]
                ps = jax.tree.unflatten(treedef, [f[0] for f in flat])
                new_m = jax.tree.unflatten(treedef, [f[1] for f in flat])
                new_v = jax.tree.unflatten(treedef, [f[2] for f in flat])
        return (ps, {"m": new_m, "v": new_v, "t": t}), loss

    # donate the master params + Adam state, as the executor's run_steps
    # does — without donation both generations live and the step OOMs
    @partial(jax.jit, donate_argnums=(0, 1))
    def run(ps, op, bs):
        (ps, op), losses = jax.lax.scan(body, (ps, op), bs)
        return ps, op, losses

    state = (params, opt)
    state = run(*state, batches)[:2]  # compile + settle
    state = run(*state, batches)[:2]

    holder = [state]

    def once():
        ps, op, losses = run(*holder[0], batches)
        jax.block_until_ready(losses)
        holder[0] = (ps, op)
        return losses

    import collections
    import shutil
    td = tempfile.mkdtemp(prefix="pjxceil_")
    jax.profiler.start_trace(td)
    once()
    jax.profiler.stop_trace()
    total_ps = 0
    by_label = collections.Counter()
    for cands, dur in profiler.iter_trace_events(td, device_only=True,
                                                 exclude_async=True):
        hit = next((c for c in cands if "pjxstep" in c), None)
        if hit is None:
            continue
        total_ps += dur
        label = "other"
        for part in str(hit).split("/"):
            if part.startswith("pjx_"):
                label = part          # deepest pjx_ component wins
        by_label[label] += dur
    shutil.rmtree(td, ignore_errors=True)
    dev_s = total_ps / 1e12
    per_step = dev_s / STEPS
    for label, ps in by_label.most_common():
        print(f"  {label:12s} {ps / 1e12 * 1e3 / STEPS:8.3f} ms/step")
    from paddle_tpu.models.transformer import matmul_param_count
    import bench
    flops_per_token = 6 * matmul_param_count(hp) + 12 * SEQ * D * (3 * NL)
    toks = BATCH * SEQ / per_step
    mfu = toks * flops_per_token / bench.peak_flops_per_chip()
    print(f"PUREJAX device: {per_step * 1e3:.2f} ms/step "
          f"-> {toks:,.0f} tok/s, MFU {mfu:.3f}")
    return per_step


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    ours = run_ours() if which in ("ours", "both") else None
    pjx = run_purejax() if which in ("purejax", "both") else None
    if ours and pjx:
        print(f"RATIO ours/purejax = {ours / pjx:.3f}")

