"""Input-pipeline throughput microbenchmark: the serial DataFeeder loop
vs the datapipe stack on an INPUT-BOUND synthetic trainer (CPU; the
comparison is host-pipeline economics, not FLOPs).

The workload is the canonical data-starvation shape: each sample is a
zlib-compressed payload behind a simulated storage fetch (``--io-ms``
of GIL-free latency — the NFS/GCS/disk read a real corpus pays; the
``tf.data`` benchmarks model remote reads the same way).  Decode =
fetch latency + real decompress + normalize.  The serial path fetches
and decodes inline, rebuilds feed arrays through ``DataFeeder``, and
runs one step at a time — fetch, decode, convert, and compute strictly
serialized, which is exactly how the 2018-era reader loop starves an
accelerator.  The datapipe path runs the same decode through
``source -> parallel map -> batch -> device prefetch``: fetches overlap
each other across map workers, and batch N+1's decode/transfer overlaps
step N's compute.

    python bench_datapipe.py --out BENCH_DATAPIPE.json
    python bench_datapipe.py --smoke      # fast CI schema check
"""

from __future__ import annotations

import argparse
import json
import time
import zlib

import numpy as np


def make_payloads(n_samples, feature_dim, payload_floats, seed=0):
    """Deterministic compressed samples.  The payload is a tiled random
    block — highly compressible, so decompression does real LZ work
    instead of degenerating into a stored-block memcpy."""
    rng = np.random.RandomState(seed)
    block = rng.rand(max(payload_floats // 64, feature_dim)) \
        .astype("float32")
    payloads = []
    for i in range(n_samples):
        raw = np.tile(block + (i % 7) * 1e-3,
                      max(payload_floats // block.size, 1))
        payloads.append((zlib.compress(raw.tobytes(), 6),
                         np.float32(i % 10)))
    return payloads


def decode(sample, feature_dim, io_ms=0.0):
    """The per-sample host work both paths must pay: a simulated storage
    fetch (GIL-free wait, like the blocking read it stands in for),
    then decompress, reinterpret, normalize, crop to the model width."""
    blob, label = sample
    if io_ms > 0:
        time.sleep(io_ms / 1e3)
    raw = np.frombuffer(zlib.decompress(blob), dtype=np.float32)
    x = raw[:feature_dim] - raw.mean()
    return {"x": x.astype("float32"),
            "y": np.array([label], dtype="float32")}


def build_trainer(feature_dim, hidden):
    import paddle_tpu as fluid
    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[feature_dim], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.fc(input=x, size=hidden, act="relu")
        pred = layers.fc(input=h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=1e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    return exe, main, loss


def run_serial(payloads, feature_dim, hidden, batch_size, steps, io_ms):
    """The 2018-era loop: inline fetch+decode per sample, DataFeeder
    feed-dict rebuild per batch, one blocking dispatch per step."""
    import paddle_tpu as fluid

    exe, main, loss = build_trainer(feature_dim, hidden)
    with fluid.program_guard(main):
        feeder = fluid.DataFeeder(feed_list=["x", "y"],
                                  place=fluid.CPUPlace(),
                                  program=main)

    def batches(with_io=True):
        buf = []
        for sample in payloads:
            d = decode(sample, feature_dim, io_ms if with_io else 0.0)
            buf.append((d["x"], d["y"]))
            if len(buf) == batch_size:
                yield buf
                buf = []

    # warmup compile (shape-stable afterwards; no simulated io)
    warm = next(batches(with_io=False))
    exe.run(main, feed=feeder.feed(warm), fetch_list=[loss.name])

    done = 0
    t0 = time.perf_counter()
    for batch in batches():
        exe.run(main, feed=feeder.feed(batch), fetch_list=[loss.name])
        done += 1
        if done >= steps:
            break
    elapsed = time.perf_counter() - t0
    return {"mode": "serial_datafeeder", "steps": done,
            "elapsed_sec": elapsed,
            "samples_per_sec": done * batch_size / elapsed}


def run_datapipe(payloads, feature_dim, hidden, batch_size, steps,
                 io_ms, workers, prefetch_depth):
    import paddle_tpu.datapipe as dp
    from paddle_tpu import profiler

    exe, main, loss = build_trainer(feature_dim, hidden)

    def build_pipe(with_io=True):
        ms = io_ms if with_io else 0.0
        return (dp.InMemorySource(payloads)
                  .map(lambda s: decode(s, feature_dim, ms),
                       workers=workers)
                  .batch(batch_size, drop_last=True)
                  .prefetch(depth=prefetch_depth))

    # warmup compile outside the measurement
    warm_it = iter(build_pipe(with_io=False))
    exe.run(main, feed=next(warm_it), fetch_list=[loss.name])
    warm_it.close()

    profiler.runtime_metrics.reset()   # stall/throughput of the run only
    pipe = build_pipe()
    t0 = time.perf_counter()
    outs = exe.run_pipeline(main, pipe, fetch_list=[loss.name],
                            max_steps=steps)
    elapsed = time.perf_counter() - t0
    snap = profiler.runtime_metrics.snapshot()
    stall = (snap["series"].get("datapipe.prefetch.stall_seconds") or
             {}).get("total")
    return {"mode": "datapipe", "steps": len(outs),
            "elapsed_sec": elapsed,
            "samples_per_sec": len(outs) * batch_size / elapsed,
            "prefetch_stall_sec_total": stall,
            "pipeline_items": {
                k: v for k, v in snap["counters"].items()
                if k.startswith("datapipe.")}}


def run_bench(n_samples=1024, feature_dim=64, payload_floats=1 << 16,
              hidden=64, batch_size=16, io_ms=2.5, workers=16,
              prefetch_depth=2, smoke=False):
    steps = n_samples // batch_size - 2
    payloads = make_payloads(n_samples, feature_dim, payload_floats)
    serial = run_serial(payloads, feature_dim, hidden, batch_size, steps,
                        io_ms)
    pipe = run_datapipe(payloads, feature_dim, hidden, batch_size, steps,
                        io_ms, workers, prefetch_depth)
    speedup = (pipe["samples_per_sec"] / serial["samples_per_sec"]
               if serial["samples_per_sec"] else None)
    return {
        "workload": {"n_samples": n_samples, "feature_dim": feature_dim,
                     "payload_floats": payload_floats, "hidden": hidden,
                     "batch_size": batch_size, "io_ms": io_ms,
                     "workers": workers, "prefetch_depth": prefetch_depth,
                     "steps": steps},
        "smoke": bool(smoke),
        "serial": serial,
        "datapipe": pipe,
        "speedup": speedup,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n-samples", type=int, default=1024)
    ap.add_argument("--feature-dim", type=int, default=64)
    ap.add_argument("--payload-floats", type=int, default=1 << 16,
                    help="decompressed floats per sample payload "
                         "(decode CPU-cost knob)")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--io-ms", type=float, default=2.5,
                    help="simulated per-sample storage fetch latency")
    ap.add_argument("--workers", type=int, default=16)
    ap.add_argument("--prefetch-depth", type=int, default=2)
    ap.add_argument("--smoke", action="store_true",
                    help="small fast run for CI schema checks")
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    kw = dict(n_samples=args.n_samples, feature_dim=args.feature_dim,
              payload_floats=args.payload_floats, hidden=args.hidden,
              batch_size=args.batch_size, io_ms=args.io_ms,
              workers=args.workers, prefetch_depth=args.prefetch_depth,
              smoke=args.smoke)
    if args.smoke:
        kw.update(n_samples=min(args.n_samples, 256),
                  payload_floats=min(args.payload_floats, 1 << 14),
                  io_ms=min(args.io_ms, 1.0))
    summary = run_bench(**kw)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("datapipe", summary, args,
                                   "bench_datapipe.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
