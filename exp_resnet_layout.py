"""Experiment: ResNet-50 train-step layout A/B (round-4).

Per-shape xplane profiling (exp_resnet_conv.py) showed XLA's TPU convs
at 97% of peak for C>=128 but only 24% (NCHW) / 42% (NHWC) at the
C=64 stage and ~7% on the K=64 1x1s — so the model-level question is
layout + backward shapes, not kernel quality.  This benchmarks a
PURE-JAX ResNet-50 training step (conv+BN+ReLU+residual+pool+fc, SGD)
in NCHW vs NHWC, bf16 activations / f32 params, one jit, and reports
median wall step plus the xplane device total.  Whatever wins bounds
what the IR lowering should target.
"""

from __future__ import annotations

import functools
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from bench import measure_trials

BATCH = 256
BLOCKS = {2: 3, 3: 4, 4: 6, 5: 3}        # resnet-50


def init_params(rng):
    params = {}

    def conv(name, cin, cout, k):
        params[name + ".w"] = (rng.randn(k, k, cin, cout)
                               * (2.0 / (k * k * cin)) ** 0.5
                               ).astype("float32")
        params[name + ".g"] = np.ones(cout, "float32")
        params[name + ".b"] = np.zeros(cout, "float32")

    conv("stem", 3, 64, 7)
    cin = 64
    for stage, n in BLOCKS.items():
        width = 64 * 2 ** (stage - 2)
        for i in range(n):
            base = f"s{stage}b{i}"
            conv(base + ".a", cin, width, 1)
            conv(base + ".b", width, width, 3)
            conv(base + ".c", width, width * 4, 1)
            if cin != width * 4:
                conv(base + ".sc", cin, width * 4, 1)
            cin = width * 4
    params["fc.w"] = (rng.randn(2048, 1000) * 0.02).astype("float32")
    params["fc.b"] = np.zeros(1000, "float32")
    return {k: jnp.asarray(v) for k, v in params.items()}


REAL_BN = False    # set by main(): training-BN statistics variant


def conv_bn_relu(params, name, x, stride, nhwc, relu=True):
    w = params[name + ".w"].astype(jnp.bfloat16)
    if nhwc:
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        dn = ("NCHW", "HWIO", "NCHW")
    k = w.shape[0]
    pad = "SAME" if k > 1 else "VALID"
    # bf16 in/out (a f32 preferred output would make the conv vjp mix
    # dtypes, which lax rejects; the MXU accumulates f32 internally);
    # BN math in f32
    out = jax.lax.conv_general_dilated(
        x, w, (stride, stride), pad, dimension_numbers=dn)
    caxis = 3 if nhwc else 1
    shape = [1, 1, 1, 1]
    shape[caxis] = -1
    # inference-style folded BN (scale+shift); training-BN statistics are
    # elementwise reductions that fuse either way and don't change the
    # layout question
    out = out.astype(jnp.float32)
    if REAL_BN:
        axes = (0, 1, 2) if nhwc else (0, 2, 3)
        mean = jnp.mean(out, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(out - mean), axis=axes, keepdims=True)
        out = (out - mean) * jax.lax.rsqrt(var + 1e-5)
    out = out * params[name + ".g"].reshape(shape) \
        + params[name + ".b"].reshape(shape)
    if relu:
        out = jnp.maximum(out, 0.0)
    return out.astype(jnp.bfloat16)


def resnet50(params, x, nhwc):
    x = conv_bn_relu(params, "stem", x, 2, nhwc)
    window = [1, 3, 3, 1] if nhwc else [1, 1, 3, 3]
    strides = [1, 2, 2, 1] if nhwc else [1, 1, 2, 2]
    # pool in f32 with a literal -inf init: the max-pool monoid matcher
    # (which makes reduce_window differentiable) wants the literal
    x = jax.lax.reduce_window(
        x.astype(jnp.float32), -jnp.inf, jax.lax.max, window, strides,
        "SAME").astype(jnp.bfloat16)
    cin = 64
    for stage, n in BLOCKS.items():
        width = 64 * 2 ** (stage - 2)
        for i in range(n):
            base = f"s{stage}b{i}"
            stride = 2 if (i == 0 and stage > 2) else 1
            sc = x
            if cin != width * 4:
                sc = conv_bn_relu(params, base + ".sc", x, stride, nhwc,
                                  relu=False)
            h = conv_bn_relu(params, base + ".a", x, stride, nhwc)
            h = conv_bn_relu(params, base + ".b", h, 1, nhwc)
            h = conv_bn_relu(params, base + ".c", h, 1, nhwc, relu=False)
            x = jnp.maximum(h + sc, 0.0).astype(jnp.bfloat16)
            cin = width * 4
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2) if nhwc else (2, 3))
    logits = x @ params["fc.w"] + params["fc.b"]
    return logits


def loss_fn(params, x, labels, nhwc):
    logits = resnet50(params, x, nhwc)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return jnp.mean(lse - picked)


def make_step(nhwc):
    @jax.jit
    def step(params, x, labels):
        # named_scope: device-time reads match THIS program's events only
        # (the shared chip's tracer also records other tenants)
        with jax.named_scope("resnet_train_step"):
            l, g = jax.value_and_grad(loss_fn)(params, x, labels, nhwc)
            new = jax.tree_util.tree_map(lambda p, gr: p - 0.1 * gr,
                                         params, g)
        return l, new

    return step


def main():
    global REAL_BN, BATCH
    rng = np.random.RandomState(0)
    import os
    variants = [
        # (batch, nhwc, real_bn)
        (256, False, False), (256, True, False),
        (256, True, True), (512, True, False),
    ]
    if os.environ.get("RESNET_VARIANT"):       # e.g. "256,1,1" = one only
        b, h, r = os.environ["RESNET_VARIANT"].split(",")
        variants = [(int(b), h == "1", r == "1")]
    for BATCH, nhwc, REAL_BN in variants:
        params = init_params(rng)
        labels = jnp.asarray(rng.randint(0, 1000, BATCH))
        flops_fwd = 7.72e9 * BATCH  # analytic conv+fc fwd GFLOPs/img
        x = jnp.asarray(rng.rand(BATCH, 224, 224, 3).astype("float32"))
        if not nhwc:
            x = jnp.transpose(x, (0, 3, 1, 2))
        x = x.astype(jnp.bfloat16)
        step = make_step(nhwc)
        l, params2 = step(params, x, labels)
        float(l)  # compile + settle

        def run_once():
            out = step(params, x, labels)
            float(out[0])

        dt, trials = measure_trials(run_once, n_trials=5)

        # ground truth: total DEVICE seconds of one step off the xplane
        # trace (wall clock carries ~100ms of dispatch+sync latency)
        from paddle_tpu.profiler import measure_device_seconds
        dev_s = measure_device_seconds(run_once,
                                       scope="resnet_train_step")

        mfu = flops_fwd * 3 / dev_s / 197e12
        print(json.dumps({
            "layout": "NHWC" if nhwc else "NCHW",
            "batch": BATCH, "real_bn": REAL_BN,
            "step_ms": round(dt * 1e3, 1),
            "device_ms": round(dev_s * 1e3, 1),
            "img_per_s_device": round(BATCH / dev_s, 1),
            "mfu_3x_device": round(mfu, 3),
            "trials_ms": [round(t * 1e3, 1) for t in trials],
        }))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
