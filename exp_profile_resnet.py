"""Per-IR-op device-time profile of the ResNet-50 training step (r4),
with the fixed (async-excluded) attribution.  Prints the op table plus
the device busy time per step."""

import os
import tempfile

os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"

import numpy as np
import jax

import paddle_tpu as fluid
from paddle_tpu.models import resnet as R
from paddle_tpu import profiler

BATCH, STEPS = 256, 2

main_prog, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main_prog, startup):
    avg_cost, acc, feeds = R.resnet_train_program(BATCH)
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9) \
        .minimize(avg_cost)
main_prog.amp = True
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    batches = [{
        "image": rng.rand(BATCH, 3, 224, 224).astype("float32"),
        "label": rng.randint(0, 1000, (BATCH, 1)).astype("int64"),
    } for _ in range(STEPS)]
    stacked = {k: jax.device_put(np.stack([b[k] for b in batches]))
               for k in batches[0]}
    exe.run_steps(main_prog, feed=stacked, fetch_list=[avg_cost.name],
                  steps=STEPS)  # compile + settle
    td = tempfile.mkdtemp()
    jax.profiler.start_trace(td)
    exe.run_steps(main_prog, feed=stacked, fetch_list=[avg_cost.name],
                  steps=STEPS)
    jax.profiler.stop_trace()
    _, rows = profiler.compiled_op_table(td)
    import shutil
    shutil.rmtree(td, ignore_errors=True)
    # NOTE: whole-plane busy time is meaningless on the shared chip (the
    # tracer records other tenants too — exp_probe_trace.py); the
    # scope-attributed table below is the trustworthy signal
    total = sum(r[2] for r in rows)
    print(f"attributed: {total * 1e3 / STEPS:.1f} ms/step")
    for op, calls, sec in rows[:18]:
        print(f"  {op:32s} {calls:6d} {sec * 1e3 / STEPS:9.3f} ms/step")
