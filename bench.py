"""Benchmark driver: Transformer-base training throughput on one chip.

Prints ONE JSON line:
  {"metric": "transformer_base_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/sec", "vs_baseline": R}

``vs_baseline`` is achieved MFU / 0.45 — the BASELINE.json north-star target
(Transformer-base >=45% MFU).  MFU uses 6*matmul_params + attention FLOPs
per token against the chip's peak, where matmul_params excludes the input
embeddings (gather, not matmul) and layernorm scale/bias — see
``models.transformer.matmul_param_count``.  Timing is the median of
``PADDLE_TPU_BENCH_TRIALS`` (default 5) measured trials after warmup; when
the trial spread exceeds 3x (a transient hit the chip) a second round is
run and merged before taking the median.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def peak_flops_per_chip():
    """Best-effort peak (bf16) FLOP/s for the local accelerator.

    Lives in the library now (``paddle_tpu.obs.perf`` — the live
    ``train.mfu`` gauge and this bench must share one denominator);
    kept here as a delegate for the sibling bench scripts.  The CPU
    fallback value is finite but meaningless — every recorded run is
    tagged with its ``mfu_basis`` and ``bench check`` refuses to
    compare records across bases."""
    from paddle_tpu.obs.perf import peak_flops_per_chip as _peak
    return _peak()


def measure_trials(run_once, n_trials=None):
    """Robust wall-clock measurement shared by all benchmarks: time
    ``n_trials`` calls of ``run_once`` (default from PADDLE_TPU_BENCH_TRIALS,
    5); when the spread exceeds 3x (a transient hit the shared chip), run
    one more round and merge before taking the median.  ``run_once`` must
    block until device completion.  Returns (median_seconds, all_trials).
    """
    import os
    if n_trials is None:
        n_trials = int(os.environ.get("PADDLE_TPU_BENCH_TRIALS", "5"))

    def one_round():
        dts = []
        for _ in range(max(1, n_trials)):
            t0 = time.perf_counter()
            run_once()
            dts.append(time.perf_counter() - t0)
        return dts

    trial_dts = one_round()
    if len(trial_dts) >= 2 and max(trial_dts) > 3 * min(trial_dts):
        trial_dts += one_round()
    return float(np.median(trial_dts)), trial_dts


def main():
    import argparse
    import os

    model = os.environ.get("PADDLE_TPU_BENCH_MODEL", "transformer") \
        or "transformer"
    if model != "transformer":
        import importlib
        modules = {"resnet": "bench_resnet", "lstm": "bench_lstm",
                   "seq2seq": "bench_seq2seq"}
        if model not in modules:
            raise SystemExit(
                f"PADDLE_TPU_BENCH_MODEL={model!r}: valid values are "
                f"transformer, {', '.join(modules)}")
        importlib.import_module(modules[model]).main()
        return
    from paddle_tpu.obs import bench_history
    parser = argparse.ArgumentParser(description="transformer training "
                                                 "throughput bench")
    bench_history.add_record_args(parser)
    args, _unknown = parser.parse_known_args()
    import jax
    # optional precision override (measured per-chip; f32 already uses the
    # MXU via bf16 passes on TPU)
    prec = os.environ.get("PADDLE_TPU_MATMUL_PRECISION")
    if prec:
        jax.config.update("jax_default_matmul_precision", prec)
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as T

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    hp = T.ModelHyperParams()
    if on_tpu:
        # operating-point overrides (long-context runs: S >= 512 takes
        # the in-model flash path per BENCH_ATTENTION.md's crossover)
        batch = int(os.environ.get("PADDLE_TPU_BENCH_BATCH", "256"))
        seq = int(os.environ.get("PADDLE_TPU_BENCH_SEQ", "256"))
        hp.max_length = max(hp.max_length, seq)
        warmup_calls, steps = 2, 16
    else:  # tiny smoke config for dev machines
        hp.d_model, hp.d_inner_hid, hp.n_layer = 64, 128, 2
        hp.n_head, hp.d_key, hp.d_value = 4, 16, 16
        hp.src_vocab_size = hp.trg_vocab_size = 1000
        batch, seq = 4, 32
        warmup_calls, steps = 1, 4

    # input mode: "memory" (default) stages pre-stacked device arrays;
    # "recordio" exercises the full reader-op pipeline (recordio file ->
    # open_recordio_file -> double_buffer -> read ops feeding run_steps)
    input_mode = os.environ.get("PADDLE_TPU_BENCH_INPUT", "memory")

    main_prog = fluid.Program()
    startup = fluid.Program()
    batches = [T.fake_batch(batch, seq, seq, hp, seed=s)
               for s in range(steps)]
    keys = ["src_word", "trg_word", "src_mask", "lbl_word", "lbl_weight"]
    recordio_path = None
    if input_mode == "recordio":
        import tempfile
        from paddle_tpu.recordio_writer import (
            convert_reader_to_recordio_file)
        recordio_path = os.path.join(tempfile.mkdtemp(), "bench.recordio")

        def _samples():
            # one record per STEP batch; the file holds warmup_calls+1
            # passes and the reader's pass_num=10**6 REWINDS it, which is
            # what keeps measured trials 2..N supplied with data
            for _ in range(warmup_calls + 1):
                for b in batches:
                    yield tuple(b[k] for k in keys)

        # RAW chunks: zlib decode of ~20MB/call would dominate the host
        # side of the pipeline
        convert_reader_to_recordio_file(recordio_path, _samples,
                                        compressor=0)

    with fluid.program_guard(main_prog, startup):
        input_vars = None
        if input_mode == "recordio":
            from paddle_tpu import layers as L
            reader = L.open_recordio_file(
                filename=recordio_path,
                shapes=[(batch, seq), (batch, seq), (batch, seq),
                        (batch, seq), (batch, seq)],
                lod_levels=[0] * 5,
                dtypes=["int32", "int32", "float32", "int32", "float32"],
                pass_num=10**6)
            reader = L.double_buffer(reader, capacity=steps + 2)
            input_vars = L.read_file(reader)
        avg_cost, _ = T.transformer(batch, seq, seq, hp,
                                    input_vars=input_vars)
        opt = fluid.optimizer.Adam(learning_rate=1e-4)
        opt.minimize(avg_cost)
    # bf16 compute with f32 master weights (mixed precision)
    main_prog.amp = on_tpu

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        # distinct batches, stacked on a leading step axis and staged to
        # the device ONCE; the training loop then runs on-device
        # (Executor.run_steps = lax.scan over the step with donated state),
        # so per-step host->device latency is off the measured path — the
        # double-buffered-reader discipline of the reference
        # (operators/reader/create_double_buffer_reader_op.cc), TPU-style.
        if input_mode == "recordio":
            stacked = {}
        else:
            stacked = {k: jax.device_put(np.stack([b[k] for b in batches]))
                       for k in batches[0]}
        for _ in range(warmup_calls):
            exe.run_steps(main_prog, feed=stacked,
                          fetch_list=[avg_cost.name], steps=steps)
        # Robustness: a single-trial measurement on a shared chip can be
        # poisoned by transient contention (a 19x-slow wall clock was
        # observed once with bit-identical numerics).  Run several trials
        # and report the median; print per-trial stats to stderr.
        last_losses = [None]

        def run_once():
            # run_steps returns numpy (return_numpy=True), which blocks
            # on the device — no extra sync needed before the clock.
            last_losses[0] = exe.run_steps(
                main_prog, feed=stacked,
                fetch_list=[avg_cost.name], steps=steps)

        dt, trial_dts = measure_trials(run_once)
        loss = np.asarray(last_losses[0][0])[-1]

    tokens = batch * seq * steps  # target-side tokens, the NMT convention
    tokens_per_sec = tokens / dt

    # FLOPs/token: the analytical 6N-matmul + attention accounting,
    # shared with the library (models.transformer.train_flops_per_token
    # — the cross-check test in tests/test_perf.py holds it against the
    # XLA cost_analysis FLOPs of the compiled step).  With src_len ==
    # trg_len, each counted (target) token pairs with one source token,
    # so encoder work per counted token is the full encoder stack.
    from paddle_tpu.obs import perf as _perf
    n_params = T.param_count(hp)
    n_matmul = T.matmul_param_count(hp)
    flops_per_token = T.train_flops_per_token(hp, seq)
    peak, mfu_basis = _perf.peak_flops_info()
    mfu = tokens_per_sec * flops_per_token / peak
    # the DEVICE-side view of the same run: the live gauge derived from
    # the compiled step's cost-analysis FLOPs, and the compile wall
    # time this cold process paid (both guarded by `bench check`)
    from paddle_tpu.profiler import runtime_metrics
    measured_mfu = runtime_metrics.gauge("train.mfu")
    compile_seconds = _perf.total_compile_seconds()

    print(json.dumps({
        "metric": "transformer_base_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.45, 4),
    }))
    step_mss = ", ".join(f"{t / steps * 1e3:.1f}" for t in trial_dts)
    print(f"# loss={float(np.asarray(loss).reshape(()))}"
          f" mfu={mfu:.3f} mfu_basis={mfu_basis}"
          f" measured_mfu={'-' if measured_mfu is None else round(measured_mfu, 4)}"
          f" compile_s={compile_seconds:.1f}"
          f" params={n_params / 1e6:.1f}M"
          f" matmul_params={n_matmul / 1e6:.1f}M"
          f" step_ms_median={dt / steps * 1e3:.1f}"
          f" trials=[{step_mss}]", file=sys.stderr)
    summary = {"tokens_per_sec_per_chip": tokens_per_sec, "mfu": mfu,
               "measured_mfu": measured_mfu,
               "compile_seconds": compile_seconds}
    bench_history.record_from_args("train_transformer", summary, args,
                                   source="bench.py",
                                   mfu_basis=mfu_basis)


if __name__ == "__main__":
    main()
