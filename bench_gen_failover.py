"""Resumable-session failover benchmark: the ISSUE-20 chaos drill as a
measured artifact — N router-fronted generative replicas, M concurrent
streams, the busiest owner hard-killed mid-decode — recording what the
failover COSTS (time-to-next-token after the kill, whole-stream resume
overhead versus an unkilled reference) while asserting what it may
never cost (lost tokens, duplicated tokens, client-visible errors:
exactly-once delivery is an invariant, not a tolerance).

Device work is MODELED WITH A SLEEP — the ``gen.decode.stall``
failpoint fires once per decode iteration, so each replica behaves like
one device producing tokens at a fixed cadence while the GIL stays
free (the same honest 2-vCPU cost model as bench_fleet.py).  The
hard-kill is ``InferenceServer.abort_streams()`` — the in-process
SIGKILL analog: every live stream on the victim fails with a retryable
error at a token boundary, exactly what a resume-capable router sees
when a real replica dies mid-chunk.

    python bench_gen_failover.py --streams 6 --replicas 3 \
        --out BENCH_GEN_FAILOVER.json
"""

from __future__ import annotations

import argparse
import json
import tempfile
import threading
import time


def build_bundle(dirname, num_slots=8):
    from paddle_tpu.models import gen_lm
    gen_lm.export_gen_model(dirname, gen_lm.GenConfig(),
                            num_slots=num_slots)
    return dirname


def _prompts(n):
    # distinct prompts, fixed (greedy decode is deterministic, so the
    # reference and drill runs are comparable token-for-token)
    base = [[2, 9], [5, 3], [7, 1], [4, 4], [6, 2], [3, 8],
            [1, 7], [8, 5], [9, 2], [2, 6]]
    return [base[i % len(base)] + [i // len(base)] if i >= len(base)
            else base[i] for i in range(n)]


def _read_stream(host, port, payload, timeout=120):
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("POST", "/generate", json.dumps(payload).encode(),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body, []
    events, stamps = [], []
    while True:
        line = resp.readline()
        if not line:
            break
        events.append(json.loads(line))
        stamps.append(time.monotonic())
        if events[-1].get("done"):
            break
    conn.close()
    return 200, events, stamps


def _stream_tokens(events):
    return [(e["index"], e["token"]) for e in events if "token" in e]


def run_streams(servers, router, prompts, max_new, kill_after=None,
                drain_deadline_s=None):
    """Drive one concurrent stream per prompt through the router.  With
    ``kill_after``, hard-kill the replica owning the first stream to
    deliver that many tokens; with ``drain_deadline_s``, bound-drain
    that owner instead (the rolling-restart migration path).  Returns
    per-stream results plus the drill bookkeeping."""
    results = [None] * len(prompts)

    def consume(i):
        results[i] = _read_stream(
            router.addr[0], router.addr[1],
            {"prompt": prompts[i], "max_new_tokens": max_new})

    threads = [threading.Thread(target=consume, args=(i,))
               for i in range(len(prompts))]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    t_kill = None
    victim = None
    if kill_after is not None or drain_deadline_s is not None:
        trigger_at = kill_after if kill_after is not None else 2
        deadline = time.monotonic() + 60
        owner = None
        while time.monotonic() < deadline:
            snap = router.sessions.snapshot()
            ready = [s for s in snap["sessions"]
                     if s["delivered"] >= trigger_at]
            if ready:
                owner = ready[0]["replica"]
                break
            time.sleep(0.005)
        if owner is None:
            raise RuntimeError("no stream reached the kill point")
        victim = next(
            s for s in servers
            if f"{s.addr[0]}:{s.addr[1]}" == owner)
        t_kill = time.monotonic()
        if kill_after is not None:
            victim.abort_streams()
        else:
            victim.drain_sessions(deadline_s=drain_deadline_s)
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t_start
    return {"results": results, "elapsed_sec": elapsed,
            "t_kill": t_kill, "victim": victim}


def _audit(run, prompts, max_new):
    """Exactly-once audit: per-stream index coverage against the full
    expected range — anything missing is LOST, anything repeated is
    DUPLICATED, anything non-200 / error-tailed is a client error."""
    lost = dup = errors = 0
    token_seqs = []
    for status, events, _ in run["results"]:
        if status != 200:
            errors += 1
            token_seqs.append(None)
            continue
        if any(e.get("error") for e in events) or \
                not any(e.get("done") for e in events):
            errors += 1
        pairs = _stream_tokens(events)
        idxs = [i for i, _ in pairs]
        dup += len(idxs) - len(set(idxs))
        lost += len(set(range(max_new)) - set(idxs))
        token_seqs.append([t for _, t in sorted(set(pairs))])
    return lost, dup, errors, token_seqs


def _ttft_after_kill(run):
    """Worst time-to-next-token across streams measured from the kill:
    the resumed stream pays re-route + full re-prefill here, so the max
    is the failover's client-visible token gap."""
    t_kill = run["t_kill"]
    worst = 0.0
    for status, events, stamps in run["results"]:
        if status != 200:
            continue
        after = [s for s, e in zip(stamps, events)
                 if s > t_kill and "token" in e]
        if after and any(s <= t_kill for s in stamps):
            worst = max(worst, after[0] - t_kill)
    return worst * 1e3


def run_bench(streams=6, replicas=3, max_new=12, stall_ms=30.0,
              kill_after=3, bundle_dir=None):
    from paddle_tpu import profiler
    from paddle_tpu.fault import chaos
    from paddle_tpu.fleet import FleetRouter
    from paddle_tpu.serving import InferenceServer

    if bundle_dir is None:
        bundle_dir = build_bundle(
            tempfile.mkdtemp(prefix="ptgenfo_") + "/bundle")
    profiler.runtime_metrics.reset()
    chaos.clear()
    prompts = _prompts(streams)

    def fleet():
        srvs = []
        for _ in range(replicas):
            s = InferenceServer(bundle_dir, port=0, warmup=True,
                                request_timeout=60.0)
            s.start_background()
            srvs.append(s)
        for s in srvs:
            assert s.wait_until_ready(300)
        r = FleetRouter(
            replicas=[f"{s.addr[0]}:{s.addr[1]}" for s in srvs])
        r.start_background()
        return srvs, r

    def teardown(srvs, r):
        r.shutdown()
        for s in srvs:
            s.shutdown()

    chaos.inject("gen.decode.stall", delay=stall_ms / 1000.0)
    try:
        # -- unkilled reference: the token-identity oracle and the
        # overhead denominator
        srvs, router = fleet()
        try:
            ref = run_streams(srvs, router, prompts, max_new)
        finally:
            teardown(srvs, router)
        ref_lost, ref_dup, ref_errors, ref_tokens = _audit(
            ref, prompts, max_new)

        # -- kill drill: busiest owner hard-killed mid-decode
        srvs, router = fleet()
        resumes0 = profiler.runtime_metrics.counter(
            "gen.session.resumes")
        spliced0 = profiler.runtime_metrics.counter(
            "gen.session.spliced_tokens")
        try:
            kill = run_streams(srvs, router, prompts, max_new,
                               kill_after=kill_after)
        finally:
            teardown(srvs, router)
        lost, dup, errors, kill_tokens = _audit(kill, prompts, max_new)

        # -- drain drill: the same fleet topology, the owner
        # bound-drained instead (rolling-restart migration)
        srvs, router = fleet()
        migrations0 = profiler.runtime_metrics.counter(
            "gen.session.migrations")
        try:
            drain = run_streams(srvs, router, prompts, max_new,
                                drain_deadline_s=0.05)
        finally:
            teardown(srvs, router)
        d_lost, d_dup, d_errors, drain_tokens = _audit(
            drain, prompts, max_new)
    finally:
        chaos.clear()

    return {
        "streams": streams,
        "replicas": replicas,
        "max_new_tokens": max_new,
        "stall_ms": stall_ms,
        "reference": {
            "elapsed_sec": ref["elapsed_sec"],
            "lost_tokens": ref_lost,
            "dup_tokens": ref_dup,
            "client_errors": ref_errors,
        },
        "kill_drill": {
            "elapsed_sec": kill["elapsed_sec"],
            "killed_replica":
                f"{kill['victim'].addr[0]}:{kill['victim'].addr[1]}",
            "ttft_after_failover_ms": _ttft_after_kill(kill),
            "lost_tokens": lost,
            "dup_tokens": dup,
            "client_errors": errors,
            "token_identical": kill_tokens == ref_tokens,
            "resumes": profiler.runtime_metrics.counter(
                "gen.session.resumes") - resumes0,
            "spliced_tokens": profiler.runtime_metrics.counter(
                "gen.session.spliced_tokens") - spliced0,
        },
        "drain_drill": {
            "elapsed_sec": drain["elapsed_sec"],
            "lost_tokens": d_lost,
            "dup_tokens": d_dup,
            "client_errors": d_errors,
            "token_identical": drain_tokens == ref_tokens,
            "migrations": profiler.runtime_metrics.counter(
                "gen.session.migrations") - migrations0,
        },
        "resume_overhead_ratio":
            kill["elapsed_sec"] / ref["elapsed_sec"]
            if ref["elapsed_sec"] else None,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--streams", type=int, default=6)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--stall-ms", type=float, default=30.0)
    ap.add_argument("--kill-after", type=int, default=3)
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    summary = run_bench(streams=args.streams, replicas=args.replicas,
                        max_new=args.max_new, stall_ms=args.stall_ms,
                        kill_after=args.kill_after)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("gen_failover", summary, args,
                                   "bench_gen_failover.py")
    ok = (summary["kill_drill"]["lost_tokens"] == 0
          and summary["kill_drill"]["dup_tokens"] == 0
          and summary["kill_drill"]["client_errors"] == 0
          and summary["kill_drill"]["token_identical"]
          and summary["drain_drill"]["client_errors"] == 0)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
