"""Paged-KV decode benchmark: REAL decode-step compute (no modeled
sleeps) for the same generation model exported two ways — the dense
per-slot ``[num_slots, max_len, H*D]`` cache pool vs the paged
``[num_pages, page_len, H*D]`` pool behind a per-slot page table.

Both predictors hold the pool at a fixed mean prefix occupancy
(default 25% of ``max_len``, chosen to land exactly on a declared page
bucket) and run the same single-token decode iteration.  The dense
step reads every slot's full ``max_len`` rows regardless of occupancy;
the paged step feeds the page table sliced to the covering page bucket,
so its reads scale with the live prefix.  Two numbers fall out:

* ``speedup`` — median dense step wall time / median paged step wall
  time (target: >= 1.5x at 25% occupancy);
* ``bytes_ratio`` — decode-executable bytes accessed, paged / dense,
  from XLA ``cost_analysis()`` via the compile capture
  (``paddle_tpu.obs.perf.records``), with the static analyzer's
  ``cost.estimate`` as fallback when the backend reports no bytes
  (target: <= 0.5x).

    JAX_PLATFORMS=cpu python bench_paged.py --out BENCH_PAGED.json
"""

from __future__ import annotations

import argparse
import json
import statistics
import tempfile
import time

import numpy as np


def _hp():
    from paddle_tpu.models import gen_lm
    hp = gen_lm.GenConfig()
    hp.vocab_size, hp.d_model, hp.d_ffn = 64, 128, 128
    hp.n_head, hp.d_head, hp.n_layer = 8, 16, 4
    hp.max_len = 512
    return hp


def _export(dirname, hp, num_slots, paged):
    from paddle_tpu.models import gen_lm
    gen_lm.export_gen_model(dirname, hp, num_slots=num_slots,
                            paged=paged)
    return dirname


def _seed_slots(pred, prompt_len, rng):
    """Drop every slot at ``prompt_len`` live rows with synthetic K/V
    (decode numerics are irrelevant to step timing; skipping real
    prefill keeps the bench on the decode path only)."""
    hd = int(pred._dec_prog.global_block()
             .var(pred.cache_vars[0]).shape[-1])
    kv = [rng.standard_normal((1, prompt_len, hd)).astype(np.float32)
          for _ in range(len(pred.cache_vars))]
    for slot in range(pred.num_slots):
        if pred.paged:
            pred.alloc_slot_pages(slot, pred.pages_needed(prompt_len))
        pred.write_slot(slot, kv, prompt_len)


def _step_args(pred, prompt_len):
    """Fixed-occupancy single-token decode feed (positions do not
    advance between timed steps, so every step reads the same page
    bucket / mask)."""
    S, L = pred.num_slots, pred.max_len
    tokens = np.ones(S, np.int32)
    positions = np.full(S, prompt_len, np.int32)
    if pred.paged:
        return dict(tokens=tokens, positions=positions,
                    lens=np.full(S, prompt_len + 1, np.int32))
    onehot = np.zeros((S, L), np.float32)
    onehot[:, prompt_len] = 1.0
    mask = np.zeros((S, L), np.float32)
    mask[:, :prompt_len + 1] = 1.0
    return dict(tokens=tokens, positions=positions,
                pos_onehot=onehot, attn_mask=mask)


def _time_decode(pred, prompt_len, steps, warm=3):
    args = _step_args(pred, prompt_len)
    for _ in range(warm):
        pred.decode_step(**args)
    samples = []
    for _ in range(steps):
        t0 = time.perf_counter()
        logits = pred.decode_step(**args)
        np.asarray(logits)
        samples.append(time.perf_counter() - t0)
    return 1e3 * statistics.median(samples)


def _decode_bytes_xla(marker):
    """bytes-accessed of the captured decode executable whose jit label
    carries ``marker`` (a decode-only feed name); None when the backend
    reported no cost analysis."""
    from paddle_tpu.obs import perf
    for r in reversed(perf.records()):
        if marker in r["label"]:
            return r["bytes_accessed"]
    return None


def _decode_bytes_static(pred, pages_fed=None):
    """Static-analyzer fallback: ``cost.estimate`` over the decode
    program, with the page-table feed pinned to the fed bucket so the
    paged estimate prices what the step actually read."""
    from paddle_tpu.analysis import cost
    prog = pred._dec_prog
    if pages_fed is None:
        return cost.estimate(prog).total_bytes
    var = prog.global_block().var("gen_page_table")
    saved = var.shape
    try:
        var.shape = (saved[0], int(pages_fed))
        return cost.estimate(prog).total_bytes
    finally:
        var.shape = saved


def run_bench(args):
    from paddle_tpu.gen import GenPredictor
    from paddle_tpu.lod import row_bucket

    hp = _hp()
    # live rows land EXACTLY on a page bucket: lens = prompt_len + 1
    prompt_len = int(hp.max_len * args.occupancy) - 1
    rng = np.random.default_rng(7)
    out = {}
    for mode in ("paged", "dense"):
        with tempfile.TemporaryDirectory() as tmp:
            _export(tmp, hp, args.slots, paged=(mode == "paged"))
            pred = GenPredictor(tmp)
            _seed_slots(pred, prompt_len, rng)
            ms = _time_decode(pred, prompt_len, args.steps)
            entry = {"decode_step_ms": ms}
            if mode == "paged":
                need = -(-(prompt_len + 1) // pred.page_len)
                entry["pages_fed"] = int(min(
                    row_bucket(need, edges=pred.page_buckets),
                    pred.pages_per_slot))
                entry["page_len"] = pred.page_len
                entry["bytes_xla"] = _decode_bytes_xla("gen_page_table")
                entry["bytes_static"] = _decode_bytes_static(
                    pred, entry["pages_fed"])
            else:
                entry["bytes_xla"] = _decode_bytes_xla("gen_attn_mask")
                entry["bytes_static"] = _decode_bytes_static(pred)
            out[mode] = entry

    use_xla = (out["paged"]["bytes_xla"] is not None and
               out["dense"]["bytes_xla"] is not None and
               out["dense"]["bytes_xla"] > 0)
    src = "bytes_xla" if use_xla else "bytes_static"
    summary = {
        "model": {"d_model": hp.d_model, "n_head": hp.n_head,
                  "d_head": hp.d_head, "n_layer": hp.n_layer,
                  "max_len": hp.max_len},
        "num_slots": args.slots,
        "occupancy_pct": round(100.0 * (prompt_len + 1) / hp.max_len, 1),
        "steps": args.steps,
        "paged": out["paged"],
        "dense": out["dense"],
        "speedup": out["dense"]["decode_step_ms"] /
        out["paged"]["decode_step_ms"],
        "bytes_source": "xla" if use_xla else "static",
        "bytes_ratio": out["paged"][src] / out["dense"][src],
    }
    return summary


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--slots", type=int, default=16)
    parser.add_argument("--steps", type=int, default=30,
                        help="timed decode iterations per mode")
    parser.add_argument("--occupancy", type=float, default=0.25,
                        help="mean live prefix as a fraction of max_len")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="write the summary JSON here")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(parser)
    args = parser.parse_args(argv)

    summary = run_bench(args)
    print(json.dumps(summary, indent=2))
    print(f"\ndecode step: dense "
          f"{summary['dense']['decode_step_ms']:.3f} ms, paged "
          f"{summary['paged']['decode_step_ms']:.3f} ms "
          f"-> speedup {summary['speedup']:.2f}x at "
          f"{summary['occupancy_pct']}% occupancy")
    print(f"decode bytes ({summary['bytes_source']}): ratio "
          f"{summary['bytes_ratio']:.3f}x")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")
    bench_history.record_from_args("paged", summary, args,
                                   source="bench_paged.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
