"""Sharded-embedding bench: the CTR workload's three acceptance claims
as one measurable artifact.

1. **Memory scaling** — the wide_and_deep embedding tables, row-sharded
   over the dp mesh by ``embedding.plan_sharded_tables``, occupy
   ~1/N of the replicated per-device bytes (``table_bytes_ratio``,
   measured from the live arrays' ``sharding.shard_shape`` and
   cross-checked against the HBM census's ``embedding`` collection).
2. **Numerical transparency** — the sharded-table run reproduces the
   single-host replicated baseline's losses BITWISE (the batch stays
   replicated — batch 9 doesn't divide dp4 — so the only difference
   between the runs is the table partitioning), and the dp4 kill →
   dp2 shrink-resume drill restores the sharded table plus the sparse
   Adam moments within ``loss_delta_rel <= 1e-6``.
3. **Sparse-update scaling** — a 4x larger vocab with the SAME touched
   rows must not move the step time (``step_time_vocab_ratio`` ~ 1):
   the SelectedRows update prices by referenced rows, not table height.

    python bench_embedding.py --out BENCH_EMBEDDING.json
    python bench_embedding.py --smoke      # fast CI schema check
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

TRAINER = r'''
import argparse
import json
import os
import time

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
from paddle_tpu.embedding import plan_sharded_tables, registered_tables
from paddle_tpu.fault import CheckpointManager, chaos
from paddle_tpu.models import wide_and_deep
from paddle_tpu.obs.perf import hbm_census
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.scope import global_scope

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", default="")
ap.add_argument("--dp", type=int, default=1)
ap.add_argument("--vocab", type=int, default=64)
ap.add_argument("--id-range", type=int, default=0)  # 0 = full vocab
ap.add_argument("--slots", type=int, default=4)
ap.add_argument("--emb-dim", type=int, default=8)
ap.add_argument("--steps", type=int, default=8)
ap.add_argument("--batch", type=int, default=9)
ap.add_argument("--out", required=True)
args = ap.parse_args()
id_range = args.id_range or args.vocab

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    cost, acc, feed_names = wide_and_deep.wide_and_deep_train_program(
        args.batch, vocab_size=args.vocab, num_slots=args.slots,
        emb_dim=args.emb_dim)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(cost)

# deterministic stream: id_range (not vocab) bounds the draw, so the
# vocab-scaling probes see IDENTICAL batches at every table height
rng = np.random.RandomState(7)
n = args.steps * args.batch
ids = rng.randint(0, id_range, (n, args.slots, 1)).astype("int64")
dense = rng.rand(n, 8).astype("float32")
label = rng.randint(0, 2, (n, 1)).astype("int64")
samples = [{"slot_ids": ids[i], "dense": dense[i], "label": label[i]}
           for i in range(n)]
pipe = dp.InMemorySource(samples).batch(args.batch, drop_last=True)

exe = fluid.Executor()
exe.run(startup)

# dp=1 is the REPLICATED baseline: same ParallelExecutor jit path on a
# 1-device mesh, tables unsharded — so the sharded runs differ from it
# by the table partitioning alone, and bitwise loss comparison is fair.
# ZeRO stays OFF here on purpose: resharding the dense Adam moments
# moves XLA's fusion boundaries and costs a ulp on the dense updates,
# while the row-sharded tables alone are numerically transparent —
# which is exactly the claim this bench measures.
mgr = None
mesh = make_mesh((args.dp,), ("data",), devices=jax.devices()[:args.dp])
if args.dp > 1:
    plan = plan_sharded_tables(main, mesh_axis="data",
                               mesh_axes={"data": args.dp})
    pexe = ParallelExecutor(loss_name=cost.name, main_program=main,
                            mesh=mesh, param_shardings=plan.rules())
    if args.ckpt:
        # the drill carries the table plan's row shards (tables plus
        # sparse accumulators) across the mesh change; dense state is
        # replicated and round-trips whole
        mgr = CheckpointManager(args.ckpt, keep=5, executor=pexe,
                                main_program=main, datapipe=pipe,
                                mesh=mesh,
                                shard_specs=plan.checkpoint_specs())
else:
    pexe = ParallelExecutor(loss_name=cost.name, main_program=main,
                            mesh=mesh)
run_step = lambda batch: pexe.run(feed=batch, fetch_list=[cost.name])

resumed = mgr.restore_last_good() if mgr else None
step = resumed or 0

losses, times = [], []
for batch in pipe:
    step += 1
    chaos.fire("train.step", step=step)
    t0 = time.perf_counter()
    (lv,) = run_step(batch)
    loss_val = float(np.asarray(lv).reshape(-1)[0])  # sync point
    times.append(time.perf_counter() - t0)
    losses.append(loss_val)
    if mgr:
        mgr.save_async(step)
        mgr.mark_good(step)                  # drains the pending commit

scope = global_scope()
table_bytes = 0
for name in registered_tables():
    arr = scope.find_var(name)
    shard = (arr.sharding.shard_shape(arr.shape)
             if hasattr(arr, "sharding") else arr.shape)
    table_bytes += int(np.prod(shard)) * int(arr.dtype.itemsize)
census = hbm_census(scope)

warmup = min(2, max(len(times) - 1, 0))
with open(args.out, "w") as f:
    json.dump({"dp": args.dp, "vocab": args.vocab, "id_range": id_range,
               "steps": len(losses), "resumed_from": resumed,
               "losses": losses, "final_loss": losses[-1],
               "table_bytes_per_device": table_bytes,
               "census_embedding_bytes": int(census.get("embedding", 0)),
               "step_seconds": sum(times[warmup:]) /
                               max(len(times) - warmup, 1)}, f)
'''

KILL_EXIT_CODE = 137


def _run_trainer(trainer, out, dp, vocab, steps, batch, slots=4,
                 emb_dim=8, id_range=0, ckpt="", chaos_spec=None,
                 timeout=600):
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_TPU_CHAOS", None)
    if chaos_spec:
        env["PADDLE_TPU_CHAOS"] = chaos_spec
    r = subprocess.run(
        [sys.executable, trainer, "--dp", str(dp), "--vocab", str(vocab),
         "--id-range", str(id_range), "--slots", str(slots),
         "--emb-dim", str(emb_dim), "--steps", str(steps),
         "--batch", str(batch), "--ckpt", ckpt, "--out", out],
        cwd=repo_root, env=env, capture_output=True, text=True,
        timeout=timeout)
    return r


def run_bench(dp_from=4, dp_to=2, vocab=64, slots=4, emb_dim=8,
              steps=8, batch=9, kill_after=5, probe_vocab=4096,
              probe_scale=4, probe_steps=12, smoke=False):
    if smoke:
        steps, probe_vocab, probe_steps = min(steps, 6), 512, 8
    # batch 9 divides neither dp4 nor dp2: feeds stay REPLICATED, so
    # the sharded runs differ from the baseline only by the table
    # partitioning — the bitwise-equality claim isolates exactly that
    summary = {
        "workload": {"dp_from": dp_from, "dp_to": dp_to, "vocab": vocab,
                     "slots": slots, "emb_dim": emb_dim, "steps": steps,
                     "batch": batch, "kill_after": kill_after},
        "smoke": bool(smoke),
        "reshard_failures": 0,
    }
    with tempfile.TemporaryDirectory(prefix="bench_embedding_") as tmp:
        trainer = os.path.join(tmp, "trainer.py")
        with open(trainer, "w") as f:
            f.write(TRAINER)
        common = dict(vocab=vocab, steps=steps, batch=batch,
                      slots=slots, emb_dim=emb_dim)

        def load(path):
            with open(path) as f:
                return json.load(f)

        # replicated single-host baseline
        base_out = os.path.join(tmp, "base.json")
        r = _run_trainer(trainer, base_out, 1, **common)
        if r.returncode != 0:
            raise RuntimeError(f"baseline run failed: {r.stderr[-2000:]}")
        base = load(base_out)
        summary["replicated"] = base

        # row-sharded over the dp_from mesh (also the drill reference)
        ref_out = os.path.join(tmp, "ref.json")
        r = _run_trainer(trainer, ref_out, dp_from,
                         ckpt=os.path.join(tmp, "ref_ckpt"), **common)
        if r.returncode != 0:
            raise RuntimeError(f"sharded run failed: {r.stderr[-2000:]}")
        ref = load(ref_out)
        summary["sharded"] = ref
        summary["losses_bitwise_equal"] = base["losses"] == ref["losses"]
        summary["table_bytes_ratio"] = (ref["table_bytes_per_device"] /
                                        base["table_bytes_per_device"])

        # chaos run: hard-killed mid-step on the full mesh
        ckpt = os.path.join(tmp, "ckpt")
        got_out = os.path.join(tmp, "got.json")
        r = _run_trainer(trainer, got_out, dp_from, ckpt=ckpt,
                         chaos_spec=f"train.step=kill@{kill_after}",
                         **common)
        if r.returncode != KILL_EXIT_CODE:
            raise RuntimeError(
                f"kill run exited {r.returncode}, wanted "
                f"{KILL_EXIT_CODE}: {r.stderr[-2000:]}")
        summary["killed"] = {"exit_code": r.returncode,
                             "at_step": kill_after + 1}

        # resume on the SHRUNK mesh: the sharded table + sparse moments
        # re-slice dp4 -> dp2 through the restore plan
        r = _run_trainer(trainer, got_out, dp_to, ckpt=ckpt, **common)
        if r.returncode != 0:
            summary["reshard_failures"] = 1
            raise RuntimeError(f"shrink-resume failed: "
                               f"{r.stderr[-2000:]}")
        resume = load(got_out)
        summary["resume"] = resume
        summary["loss_delta_rel"] = (
            abs(resume["final_loss"] - ref["final_loss"]) /
            max(abs(ref["final_loss"]), 1e-12))
        summary["exactly_once"] = (resume["resumed_from"] +
                                   resume["steps"] == steps)

        # sparse-update scaling: same touched rows, 4x the vocab — the
        # SelectedRows path must price by rows, so step time stays flat
        probes = {}
        for tag, pv in (("small", probe_vocab),
                        ("large", probe_vocab * probe_scale)):
            p_out = os.path.join(tmp, f"probe_{tag}.json")
            r = _run_trainer(trainer, p_out, 1, vocab=pv, id_range=64,
                             steps=probe_steps, batch=batch,
                             slots=slots, emb_dim=emb_dim)
            if r.returncode != 0:
                raise RuntimeError(f"vocab probe {tag} failed: "
                                   f"{r.stderr[-2000:]}")
            probes[tag] = load(p_out)
        summary["sparse_scaling"] = {
            "vocab_small": probes["small"]["vocab"],
            "vocab_large": probes["large"]["vocab"],
            "touched_id_range": 64,
            "step_seconds_small": probes["small"]["step_seconds"],
            "step_seconds_large": probes["large"]["step_seconds"],
            "step_time_vocab_ratio": (probes["large"]["step_seconds"] /
                                      max(probes["small"]["step_seconds"],
                                          1e-12)),
        }
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dp-from", type=int, default=4)
    ap.add_argument("--dp-to", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=9)
    ap.add_argument("--kill-after", type=int, default=5)
    ap.add_argument("--probe-vocab", type=int, default=4096)
    ap.add_argument("--smoke", action="store_true",
                    help="small fast run for CI schema checks")
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    summary = run_bench(dp_from=args.dp_from, dp_to=args.dp_to,
                        vocab=args.vocab, steps=args.steps,
                        batch=args.batch, kill_after=args.kill_after,
                        probe_vocab=args.probe_vocab, smoke=args.smoke)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("embedding", summary, args,
                                   "bench_embedding.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
