"""Serving-throughput microbenchmark: closed-loop clients vs the
lock-serialized batch-1 predictor and vs the dynamic micro-batching
server (CPU; the comparison is dispatch-count economics, not FLOPs).

Each mode starts an :class:`InferenceServer` over the same tiny saved
model, runs ``--clients`` closed-loop threads against ``/predict`` for
``--duration`` seconds, and reports request throughput + latency
percentiles.  The batched server coalesces the concurrent requests into
padded row-bucketed dispatches (one compiled call per batch), so its
sustained RPS should exceed the serialized predictor's by roughly the
achieved batch occupancy.

    python bench_serving.py --clients 8 --duration 3 --out bench.json
"""

from __future__ import annotations

import argparse
import http.client
import json
import tempfile
import threading
import time

import numpy as np


def build_model(dirname, feature_dim=32, hidden=2048, depth=12):
    """Save an MLP inference model with a flexible batch dim (batching
    needs ``[-1, feature_dim]`` feeds).  The default is deliberately
    wide and deep: on weight-traffic-bound layers a batch of N rows
    costs barely more than one row, which is the regime dynamic
    batching exists for (and the regime real serving models live in)."""
    import paddle_tpu as fluid
    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[feature_dim])
        h = x
        for _ in range(depth):
            h = layers.fc(input=h, size=hidden, act="relu")
        pred = layers.fc(input=h, size=4)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(dirname, ["x"], [pred], exe,
                                      main_program=main)
    return dirname


class _Client:
    """Persistent keep-alive connection (one per closed-loop thread)."""

    def __init__(self, host, port, timeout=60.0):
        self.host, self.port, self.timeout = host, port, timeout
        self.conn = http.client.HTTPConnection(host, port, timeout=timeout)

    def post(self, path, payload):
        body = json.dumps(payload).encode()
        try:
            self.conn.request("POST", path, body,
                              {"Content-Type": "application/json"})
            r = self.conn.getresponse()
            data = r.read()
        except Exception:
            self.conn.close()
            self.conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
            raise
        if r.status != 200:
            raise RuntimeError(f"{r.status}: {data[:200]!r}")
        return json.loads(data)

    def get(self, path):
        self.conn.request("GET", path)
        r = self.conn.getresponse()
        return json.loads(r.read())

    def close(self):
        self.conn.close()


def _closed_loop(client, payload, stop_at, out):
    """One closed-loop client: issue requests back-to-back until the
    deadline, recording per-request latency and failures."""
    while time.monotonic() < stop_at:
        t0 = time.perf_counter()
        try:
            client.post("/predict", payload)
            out["latencies"].append(time.perf_counter() - t0)
        except Exception:
            out["failures"] += 1


def _percentile(xs, q):
    from paddle_tpu.profiler import _nearest_rank
    return _nearest_rank(sorted(xs), q)


def run_mode(model_dir, batching, clients, duration, rows_per_request=1,
             feature_dim=32, max_batch_size=32, max_batch_delay=0.01):
    """Start one server, drive it with closed-loop clients, return a
    stats dict."""
    from paddle_tpu import profiler
    from paddle_tpu.serving import InferenceServer

    profiler.runtime_metrics.reset()  # occupancy of THIS mode only
    server = InferenceServer(
        model_dir, port=0, batching=batching, warmup=True,
        max_batch_size=max_batch_size, max_batch_delay=max_batch_delay,
        max_inflight=max(64, clients * 4), request_timeout=60.0)
    server.start_background()
    try:
        assert server.wait_until_ready(300)
        host, port = server.addr
        rng = np.random.RandomState(0)
        payloads = [
            {"feeds": {"x": rng.rand(rows_per_request,
                                     feature_dim).astype("float32").tolist()}}
            for _ in range(clients)]
        conns = [_Client(host, port) for _ in range(clients)]
        # untimed warmup round: first-request compiles (exact unbucketed
        # shapes on the serialized path) stay out of the measurement
        for conn, pl in zip(conns, payloads):
            conn.post("/predict", pl)
        stats = [{"latencies": [], "failures": 0} for _ in range(clients)]
        stop_at = time.monotonic() + duration
        threads = [threading.Thread(target=_closed_loop,
                                    args=(conns[i], payloads[i], stop_at,
                                          stats[i]))
                   for i in range(clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        lats = [x for s in stats for x in s["latencies"]]
        ok = len(lats)
        failures = sum(s["failures"] for s in stats)
        snap = conns[0].get("/stats")
        for conn in conns:
            conn.close()
        occupancy = snap.get("histograms", {}).get(
            "serving.batch_occupancy", {})
        return {
            "mode": "batched" if batching else "serialized",
            "requests_ok": ok,
            "failures": failures,
            "elapsed_sec": elapsed,
            "rps": ok / elapsed if elapsed > 0 else 0.0,
            "latency_ms": {
                "p50": (_percentile(lats, 50) or 0) * 1e3,
                "p95": (_percentile(lats, 95) or 0) * 1e3,
                "p99": (_percentile(lats, 99) or 0) * 1e3,
            },
            "batch_occupancy": occupancy,
        }
    finally:
        server.shutdown()


def run_bench(clients=8, duration=3.0, rows_per_request=1, feature_dim=32,
              hidden=2048, depth=12, max_batch_size=32,
              max_batch_delay=0.01, model_dir=None):
    """Both modes over one model; returns the JSON-ready summary."""
    own_dir = model_dir is None
    tmp = tempfile.mkdtemp(prefix="ptserve_") if own_dir else None
    model_dir = model_dir or build_model(tmp + "/model",
                                         feature_dim=feature_dim,
                                         hidden=hidden, depth=depth)
    kw = dict(clients=clients, duration=duration,
              rows_per_request=rows_per_request, feature_dim=feature_dim,
              max_batch_size=max_batch_size,
              max_batch_delay=max_batch_delay)
    serialized = run_mode(model_dir, batching=False, **kw)
    batched = run_mode(model_dir, batching=True, **kw)
    speedup = (batched["rps"] / serialized["rps"]
               if serialized["rps"] else None)
    return {
        "clients": clients,
        "duration_sec": duration,
        "rows_per_request": rows_per_request,
        "serialized": serialized,
        "batched": batched,
        "speedup": speedup,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--rows-per-request", type=int, default=1)
    ap.add_argument("--feature-dim", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--depth", type=int, default=12)
    ap.add_argument("--max-batch-size", type=int, default=32)
    ap.add_argument("--max-batch-delay", type=float, default=0.01)
    ap.add_argument("--quick", action="store_true",
                    help="fast smoke run (4 clients, 1s, narrower model)")
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    if args.quick:
        args.clients, args.duration = min(args.clients, 4), 1.0
        args.hidden, args.depth = min(args.hidden, 1024), min(args.depth, 4)
    summary = run_bench(clients=args.clients, duration=args.duration,
                        rows_per_request=args.rows_per_request,
                        feature_dim=args.feature_dim, hidden=args.hidden,
                        depth=args.depth,
                        max_batch_size=args.max_batch_size,
                        max_batch_delay=args.max_batch_delay)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("serving", summary, args,
                                   "bench_serving.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
