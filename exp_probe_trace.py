"""Probe: span-vs-sum of the XLA Ops line for a run_steps trace, and how
many files/planes the trace dir holds (validates device_busy accounting)."""
import os, tempfile, glob
os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
import numpy as np
import jax
import paddle_tpu as fluid
from paddle_tpu.models import resnet as R

BATCH, STEPS = 256, 2
main_prog, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main_prog, startup):
    avg_cost, acc, feeds = R.resnet_train_program(BATCH)
    fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9) \
        .minimize(avg_cost)
main_prog.amp = True
scope = fluid.Scope()
with fluid.scope_guard(scope):
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    batches = [{"image": rng.rand(BATCH, 3, 224, 224).astype("float32"),
                "label": rng.randint(0, 1000, (BATCH, 1)).astype("int64")}
               for _ in range(STEPS)]
    stacked = {k: jax.device_put(np.stack([b[k] for b in batches]))
               for k in batches[0]}
    exe.run_steps(main_prog, feed=stacked, fetch_list=[avg_cost.name],
                  steps=STEPS)
    td = tempfile.mkdtemp()
    jax.profiler.start_trace(td)
    exe.run_steps(main_prog, feed=stacked, fetch_list=[avg_cost.name],
                  steps=STEPS)
    jax.profiler.stop_trace()

from paddle_tpu.profiler import _iter_xplanes
print("xplane files:",
      len(glob.glob(td + "/**/*.xplane.pb", recursive=True)))
for plane in _iter_xplanes(td):
    if not plane.name.startswith("/device:"):
        continue
    for line in plane.lines:
        if not line.events:
            continue
        total = sum(ev.duration_ps for ev in line.events)
        t0 = min(ev.offset_ps for ev in line.events)
        t1 = max(ev.offset_ps + ev.duration_ps for ev in line.events)
        print(f"  plane={plane.name} line={line.name!r} "
              f"n={len(line.events)} sum={total/1e9:.1f}ms "
              f"span={(t1-t0)/1e9:.1f}ms")
import shutil
shutil.rmtree(td, ignore_errors=True)
