"""Elastic-resume drill + wall-time bench: kill a dp4 ZeRO run
mid-step, resume on dp2 from the last-good shard checkpoint, and time
the resume (restore planning + shard re-slicing + device placement).

This is the acceptance drill of docs/fault_tolerance.md "Elastic
resume" run as a measurable artifact: the resumed run must reach the
same final loss as an uninterrupted reference run (``loss_delta_rel``),
the restore plan must verify on the shrunk mesh with zero
``reshard_failures``, and ``resume_seconds`` — the time
``restore_last_good(mesh=dp2)`` takes — is recorded into
``BENCH_TRAJECTORY.json`` (``--record-trajectory``) so ``paddle_tpu
bench check`` guards resume wall-time against regression.

    python bench_elastic.py --out BENCH_ELASTIC.json
    python bench_elastic.py --smoke      # fast CI schema check
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

TRAINER = r'''
import argparse
import json
import os
import time

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
from paddle_tpu import layers
from paddle_tpu.fault import CheckpointManager, chaos
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import make_mesh

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", required=True)
ap.add_argument("--dp", type=int, required=True)
ap.add_argument("--hidden", type=int, default=64)
ap.add_argument("--samples", type=int, default=160)
ap.add_argument("--batch", type=int, default=16)
ap.add_argument("--out", required=True)
args = ap.parse_args()

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, args.hidden, act="relu", param_attr="w1",
                  bias_attr="b1")
    pred = layers.fc(h, 1, param_attr="w2", bias_attr="b2")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

rng = np.random.RandomState(7)
w_true = np.arange(1.0, 9.0, dtype="float32").reshape(8, 1) * 0.2
xs = rng.rand(args.samples, 8).astype("float32")
samples = [{"x": xs[i], "y": (xs[i:i + 1] @ w_true)[0].astype("float32")}
           for i in range(args.samples)]
pipe = dp.InMemorySource(samples).batch(args.batch, drop_last=True)

mesh = make_mesh((args.dp,), ("data",), devices=jax.devices()[:args.dp])
exe = fluid.Executor()
exe.run(startup)
pexe = ParallelExecutor(loss_name=loss.name, main_program=main,
                        mesh=mesh, zero=True)
mgr = CheckpointManager(args.ckpt, keep=5, executor=pexe,
                        main_program=main, datapipe=pipe, mesh=mesh,
                        shard_specs=pexe.zero_plan.checkpoint_specs())
t0 = time.perf_counter()
resumed = mgr.restore_last_good()
restore_seconds = time.perf_counter() - t0
step = resumed or 0

losses = []
for batch in pipe:
    step += 1
    chaos.fire("train.step", step=step)
    (lv,) = pexe.run(feed=batch, fetch_list=[loss.name])
    losses.append(float(np.asarray(lv).reshape(-1)[0]))
    mgr.save_async(step)
    mgr.mark_good(step)                  # drains the pending commit

with open(args.out, "w") as f:
    json.dump({"final_loss": losses[-1], "resumed_from": resumed,
               "steps": len(losses), "dp": args.dp,
               "restore_seconds": restore_seconds}, f)
'''

KILL_EXIT_CODE = 137


def _run_trainer(workdir, trainer, ckpt, out, dp, hidden, samples,
                 batch, chaos_spec=None, timeout=600):
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_TPU_CHAOS", None)
    if chaos_spec:
        env["PADDLE_TPU_CHAOS"] = chaos_spec
    r = subprocess.run(
        [sys.executable, trainer, "--ckpt", ckpt, "--dp", str(dp),
         "--hidden", str(hidden), "--samples", str(samples),
         "--batch", str(batch), "--out", out],
        cwd=repo_root, env=env, capture_output=True, text=True,
        timeout=timeout)
    return r


def run_bench(dp_from=4, dp_to=2, hidden=128, samples=160, batch=16,
              kill_after=5, smoke=False):
    if smoke:
        hidden, samples = min(hidden, 32), min(samples, 96)
    steps_total = samples // batch
    summary = {
        "workload": {"dp_from": dp_from, "dp_to": dp_to,
                     "hidden": hidden, "samples": samples,
                     "batch": batch, "steps": steps_total,
                     "kill_after": kill_after},
        "smoke": bool(smoke),
        "reshard_failures": 0,
    }
    with tempfile.TemporaryDirectory(prefix="bench_elastic_") as tmp:
        trainer = os.path.join(tmp, "trainer.py")
        with open(trainer, "w") as f:
            f.write(TRAINER)
        common = dict(hidden=hidden, samples=samples, batch=batch)

        # uninterrupted reference on the full mesh
        ref_out = os.path.join(tmp, "ref.json")
        r = _run_trainer(tmp, trainer, os.path.join(tmp, "ref_ckpt"),
                         ref_out, dp_from, **common)
        if r.returncode != 0:
            raise RuntimeError(f"reference run failed: "
                               f"{r.stderr[-2000:]}")
        with open(ref_out) as f:
            ref = json.load(f)
        summary["reference"] = ref

        # chaos run: hard-killed mid-step on the full mesh
        ckpt = os.path.join(tmp, "ckpt")
        got_out = os.path.join(tmp, "got.json")
        r = _run_trainer(tmp, trainer, ckpt, got_out, dp_from,
                         chaos_spec=f"train.step=kill@{kill_after}",
                         **common)
        if r.returncode != KILL_EXIT_CODE:
            raise RuntimeError(
                f"kill run exited {r.returncode}, wanted "
                f"{KILL_EXIT_CODE}: {r.stderr[-2000:]}")
        summary["killed"] = {"exit_code": r.returncode,
                             "at_step": kill_after + 1}

        # resume on the SHRUNK mesh from the last-good shard checkpoint
        r = _run_trainer(tmp, trainer, ckpt, got_out, dp_to, **common)
        if r.returncode != 0:
            summary["reshard_failures"] = 1
            raise RuntimeError(f"shrink-resume failed: "
                               f"{r.stderr[-2000:]}")
        with open(got_out) as f:
            resume = json.load(f)
        summary["resume"] = resume

    ref_loss, got_loss = ref["final_loss"], resume["final_loss"]
    summary["loss_delta_rel"] = abs(got_loss - ref_loss) / max(
        abs(ref_loss), 1e-12)
    summary["exactly_once"] = (resume["resumed_from"] +
                               resume["steps"] == steps_total)
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dp-from", type=int, default=4)
    ap.add_argument("--dp-to", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--samples", type=int, default=160)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--kill-after", type=int, default=5)
    ap.add_argument("--smoke", action="store_true",
                    help="small fast run for CI schema checks")
    ap.add_argument("--out", default=None, help="write the JSON summary")
    from paddle_tpu.obs import bench_history
    bench_history.add_record_args(ap)
    args = ap.parse_args(argv)
    summary = run_bench(dp_from=args.dp_from, dp_to=args.dp_to,
                        hidden=args.hidden, samples=args.samples,
                        batch=args.batch, kill_after=args.kill_after,
                        smoke=args.smoke)
    text = json.dumps(summary, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    bench_history.record_from_args("elastic", summary, args,
                                   "bench_elastic.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
