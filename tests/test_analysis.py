"""Static analyzer (paddle_tpu.analysis): negative cases for every
diagnostic code, the zero-false-positive contract on clean programs,
the PADDLE_TPU_VERIFY executor hook, post-transpile verification, and
the <5% cached-run overhead guard.

``NEGATIVE_CASES`` is the machine-readable registry the scanner test
(test_analysis_registry.py) enforces: every ``PTA***`` code in
``DIAGNOSTIC_CODES`` must appear here with a builder that constructs a
deliberately broken program triggering it.
"""

import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis
from paddle_tpu.framework import Program


def _prog():
    p = Program()
    return p, p.global_block()


# ---------------------------------------------------------------------------
# negative-case registry: code -> builder returning
# (program, feed_names, fetch_names) that must emit that code
# ---------------------------------------------------------------------------

def _case_pta001_undeclared_input():
    p, b = _prog()
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    b.append_op(type="relu", inputs={"X": ["ghost"]},
                outputs={"Out": ["y"]})
    return p, None, ["y"]


def _case_pta002_read_before_write():
    p, b = _prog()
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    # a transpiler reordering gone wrong: consumer before producer
    b.append_op(type="relu", inputs={"X": ["t"]}, outputs={"Out": ["y"]})
    b.append_op(type="tanh", inputs={"X": ["x"]}, outputs={"Out": ["t"]})
    return p, None, ["y"]


def _case_pta003_missing_fetch():
    p, b = _prog()
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    b.append_op(type="relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]})
    return p, None, ["y", "no_such_var"]


def _case_pta004_param_redefined():
    p, b = _prog()
    b.create_parameter(shape=(2, 2), dtype="float32", name="w")
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    b.append_op(type="elementwise_add", inputs={"X": ["x"], "Y": ["w"]},
                outputs={"Out": ["y"]})
    # clobbers the parameter it already consumed — not an in-place
    # state update (relu neither reads w nor declares stateful_outputs)
    b.append_op(type="relu", inputs={"X": ["x"]}, outputs={"Out": ["w"]})
    return p, None, ["y"]


def _case_pta005_dtype_mismatch():
    p, b = _prog()
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    b.create_var(name="ids", shape=(2, 2), dtype="int64", is_data=True)
    b.append_op(type="elementwise_add",
                inputs={"X": ["x"], "Y": ["ids"]}, outputs={"Out": ["y"]})
    return p, None, ["y"]


def _case_pta006_shape_mismatch():
    p, b = _prog()
    b.create_var(name="x", shape=(4, 8), dtype="float32", is_data=True)
    b.create_parameter(shape=(16, 3), dtype="float32", name="w")
    b.append_op(type="mul", inputs={"X": ["x"], "Y": ["w"]},
                outputs={"Out": ["y"]})
    return p, None, ["y"]


def _case_pta007_dead_op():
    p, b = _prog()
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    b.append_op(type="relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]})
    b.append_op(type="tanh", inputs={"X": ["x"]}, outputs={"Out": ["z"]})
    return p, None, ["y"]  # z is never consumed nor fetched


def _case_pta008_unused_feed():
    p, b = _prog()
    b.create_var(name="x", shape=(2, 2), dtype="float32", is_data=True)
    b.create_var(name="unused", shape=(2, 2), dtype="float32",
                 is_data=True)
    b.append_op(type="relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]})
    return p, None, ["y"]


def _case_pta009_donation_hazard():
    p, b = _prog()
    b.create_parameter(shape=(2, 2), dtype="float32", name="w")
    b.create_var(name="g", shape=(2, 2), dtype="float32", is_data=True)
    b.create_var(name="lr", shape=(1,), dtype="float32", is_data=True)
    b.append_op(type="sgd",
                inputs={"Param": ["w"], "Grad": ["g"],
                        "LearningRate": ["lr"]},
                outputs={"ParamOut": ["w"]})
    # reads the donated param buffer AFTER its in-place update — a
    # sentinel skip-step discard cannot restore what this op consumed
    b.append_op(type="relu", inputs={"X": ["w"]}, outputs={"Out": ["y"]})
    return p, None, ["y"]


def _case_pta010_int64_truncation():
    p, b = _prog()
    b.append_op(type="fill_constant", outputs={"Out": ["big_id"]},
                attrs={"shape": [1], "dtype": "int64", "value": 2 ** 40})
    return p, None, ["big_id"]


#: enforced complete by tests/test_analysis_registry.py
NEGATIVE_CASES = {
    "PTA001": _case_pta001_undeclared_input,
    "PTA002": _case_pta002_read_before_write,
    "PTA003": _case_pta003_missing_fetch,
    "PTA004": _case_pta004_param_redefined,
    "PTA005": _case_pta005_dtype_mismatch,
    "PTA006": _case_pta006_shape_mismatch,
    "PTA007": _case_pta007_dead_op,
    "PTA008": _case_pta008_unused_feed,
    "PTA009": _case_pta009_donation_hazard,
    "PTA010": _case_pta010_int64_truncation,
}


@pytest.mark.parametrize("code", sorted(NEGATIVE_CASES))
def test_negative_case_triggers_code(code):
    program, feeds, fetches = NEGATIVE_CASES[code]()
    result = analysis.lint_program(program, feed_names=feeds,
                                   fetch_names=fetches)
    assert code in result.codes(), (
        f"deliberately broken program did not trigger {code}; got "
        f"{result.codes()}:\n{result.format()}")
    hit = next(d for d in result.diagnostics if d.code == code)
    # actionable: the diagnostic names a concrete var or op
    assert hit.var or hit.op_type, hit.format()


def test_diagnostics_carry_construction_site():
    program, feeds, fetches = NEGATIVE_CASES["PTA006"]()
    result = analysis.lint_program(program, feed_names=feeds,
                                   fetch_names=fetches)
    hit = next(d for d in result.diagnostics if d.code == "PTA006")
    assert hit.site is not None and hit.site[0].endswith(
        "test_analysis.py"), hit.site
    assert f":{hit.site[1]}" in hit.format()


# ---------------------------------------------------------------------------
# zero false positives on clean programs
# ---------------------------------------------------------------------------

def _clean_train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=True)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=8, act="relu")
        pred = fluid.layers.fc(input=h, size=2, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg = fluid.layers.mean(x=cost)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
    return main, startup, avg


def test_clean_program_has_zero_diagnostics():
    main, startup, avg = _clean_train_program()
    r = analysis.lint_program(main, fetch_names=[avg.name])
    assert not r.diagnostics, r.format()
    rs = analysis.lint_program(startup)
    assert not rs.diagnostics, rs.format()


def test_warn_list_reports_uncovered_op_types_only():
    main, _, avg = _clean_train_program()
    r = analysis.lint_program(main, fetch_names=[avg.name])
    covered = analysis.typecheck.covered_op_types()
    assert not (set(r.uncovered_op_types) & covered)


def test_analysis_mutates_nothing():
    main, _, avg = _clean_train_program()
    before = main.to_dict()
    version = main._version
    analysis.lint_program(main, fetch_names=[avg.name])
    assert main.to_dict() == before
    assert main._version == version


# ---------------------------------------------------------------------------
# PADDLE_TPU_VERIFY executor hook
# ---------------------------------------------------------------------------

class TestExecutorVerifyHook:
    def test_broken_program_fails_before_compile(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "1")
        program, _, _ = NEGATIVE_CASES["PTA002"]()
        exe = fluid.Executor(fluid.CPUPlace())
        with pytest.raises(analysis.ProgramVerificationError) as ei:
            exe.run(program, feed={"x": np.zeros((2, 2), np.float32)},
                    fetch_list=["y"])
        assert "PTA002" in str(ei.value)
        assert ei.value.where == "executor.run"

    def test_parallel_executor_inherits_hook(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "1")
        from paddle_tpu.parallel import ParallelExecutor
        program, _, _ = NEGATIVE_CASES["PTA001"]()
        pexe = ParallelExecutor(use_cuda=False, main_program=program)
        with pytest.raises(analysis.ProgramVerificationError):
            pexe.run(fetch_list=["y"],
                     feed={"x": np.zeros((2, 2), np.float32)})

    def test_clean_program_runs_and_memoizes(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "1")
        main, startup, avg = _clean_train_program()
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu.scope import Scope, scope_guard
        with scope_guard(Scope()):
            exe.run(startup)
            feed = {"x": np.random.rand(4, 4).astype(np.float32),
                    "label": np.zeros((4, 1), np.int64)}
            exe.run(main, feed=feed, fetch_list=[avg.name])
            keys = set(exe._verified)
            assert (id(main), main._version) in keys
            exe.run(main, feed=feed, fetch_list=[avg.name])
            assert set(exe._verified) == keys  # memo hit, no re-verify

    def test_mutation_reverifies(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "1")
        p, b = _prog()
        b.create_var(name="x", shape=(2, 2), dtype="float32",
                     is_data=True)
        b.append_op(type="relu", inputs={"X": ["x"]},
                    outputs={"Out": ["y"]})
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu.scope import Scope, scope_guard
        with scope_guard(Scope()):
            exe.run(p, feed={"x": np.zeros((2, 2), np.float32)},
                    fetch_list=["y"])
            # break the program; bump_version invalidates the memo
            b.append_op(type="relu", inputs={"X": ["late"]},
                        outputs={"Out": ["z"]})
            b.append_op(type="tanh", inputs={"X": ["y"]},
                        outputs={"Out": ["late"]})
            with pytest.raises(analysis.ProgramVerificationError):
                exe.run(p, feed={"x": np.zeros((2, 2), np.float32)},
                        fetch_list=["z"])


# ---------------------------------------------------------------------------
# post-transpile verification wiring
# ---------------------------------------------------------------------------

class TestPostTranspileVerification:
    def test_append_backward_verifies_its_output(self, monkeypatch):
        # a grad maker emitting an op that reads a var defined only
        # LATER must fail inside append_backward, naming the pass
        from paddle_tpu.ops import registry

        def bad_maker(op, block, no_grad_set):
            return [{"type": "relu",
                     "inputs": {"X": ["__not_yet_defined__"]},
                     "outputs": {"Out": ["X@GRAD"]},
                     "attrs": {}}], {"X": "X@GRAD"}

        opdef = registry.lookup("tanh")
        monkeypatch.setattr(opdef, "grad_maker", bad_maker)
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            x.stop_gradient = False
            y = fluid.layers.tanh(x)
            loss = fluid.layers.mean(x=y)
            with pytest.raises(analysis.ProgramVerificationError) as ei:
                fluid.append_backward(loss)
        assert ei.value.where == "backward.append_backward"
        assert "PTA001" in str(ei.value)
        # later ops never defined it: undeclared, not read-before-write

    def test_memory_optimize_verifies(self):
        program, _, _ = NEGATIVE_CASES["PTA002"]()
        from paddle_tpu.memory_optimization_transpiler import \
            memory_optimize
        with pytest.raises(analysis.ProgramVerificationError) as ei:
            memory_optimize(program)
        assert ei.value.where == "memory_optimize"

    def test_verify_transpiled_clean_is_quiet(self):
        main, _, avg = _clean_train_program()
        analysis.verify_transpiled(main, where="test")  # no raise


# ---------------------------------------------------------------------------
# pipeline i32 carrier lane: static half of the pack() range guard
# ---------------------------------------------------------------------------

def test_pipeline_carrier_int64_lint():
    p, b = _prog()
    b.append_op(type="fill_constant", outputs={"Out": ["big_id"]},
                attrs={"shape": [2], "dtype": "int64", "value": 2 ** 39})
    b.append_op(type="relu", inputs={"X": ["big_id"]},
                outputs={"Out": ["y"]})
    with pytest.raises(analysis.ProgramVerificationError) as ei:
        analysis.check_pipeline_carriers(b, [["big_id"]])
    assert "PTA010" in str(ei.value)
    # in-range constants cross boundaries freely
    p2, b2 = _prog()
    b2.append_op(type="fill_constant", outputs={"Out": ["small_id"]},
                 attrs={"shape": [2], "dtype": "int64", "value": 7})
    assert analysis.check_pipeline_carriers(b2, [["small_id"]]) == []


# ---------------------------------------------------------------------------
# overhead guard: PADDLE_TPU_VERIFY on a CACHED Executor.run
# (sleep-modeled, same idiom as tests/test_obs_overhead.py: the bench
# host has 2 noisy vCPUs, so the memoized hook's per-step cost is
# measured directly against a 1 ms modeled dispatch instead of racing
# two full executors)
# ---------------------------------------------------------------------------

STEP_SECONDS = 0.001
MAX_OVERHEAD_FRACTION = 0.05


def test_verify_hook_overhead_under_5_percent(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_VERIFY", "1")
    main, _, avg = _clean_train_program()
    exe = fluid.Executor(fluid.CPUPlace())
    from paddle_tpu.executor import _env_flag

    def hook_once():
        # exactly what a cached Executor.run adds per step: the env
        # gate plus the memoized verification lookup
        if _env_flag("PADDLE_TPU_VERIFY"):
            exe._maybe_verify(main, ("x", "label"), (avg.name,))

    hook_once()  # first call pays the real verification
    assert (id(main), main._version) in exe._verified

    def per_step(iters=2000):
        t0 = time.perf_counter()
        for _ in range(iters):
            hook_once()
        return (time.perf_counter() - t0) / iters

    cost = min(per_step() for _ in range(5))  # best-of-5 vs noisy CPU
    budget = STEP_SECONDS * MAX_OVERHEAD_FRACTION
    assert cost <= budget, (
        f"memoized PADDLE_TPU_VERIFY hook costs {cost * 1e6:.1f}us per "
        f"cached step — over {MAX_OVERHEAD_FRACTION:.0%} of a "
        f"{STEP_SECONDS * 1e3:.0f}ms step ({budget * 1e6:.0f}us)")


# ---------------------------------------------------------------------------
# CLI: lint a saved model dir (static — no params, no executor)
# ---------------------------------------------------------------------------

class TestLintCli:
    def _write_model(self, tmp_path, program, feeds, fetches):
        import json

        d = tmp_path / "model"
        d.mkdir()
        (d / "__model__").write_text(json.dumps({
            "program": program.to_dict(),
            "feed_var_names": feeds or [],
            "fetch_var_names": fetches or []}))
        return str(d)

    def test_broken_saved_model_exits_1(self, tmp_path, capsys):
        from paddle_tpu.cli import main
        program, feeds, fetches = NEGATIVE_CASES["PTA006"]()
        path = self._write_model(tmp_path, program, feeds, fetches)
        assert main(["lint", path]) == 1
        out = capsys.readouterr().out
        assert "PTA006" in out and "error" in out

    def test_clean_saved_model_exits_0(self, tmp_path, capsys):
        from paddle_tpu.cli import main
        main_prog, _, avg = _clean_train_program()
        inference = main_prog.prune([avg]).inference_optimize()
        path = self._write_model(tmp_path, inference, ["x", "label"],
                                 [avg.name])
        assert main(["lint", path]) == 0
        assert "0 error(s)" in capsys.readouterr().out

    def test_strict_promotes_warnings(self, tmp_path):
        from paddle_tpu.cli import main
        program, _, fetches = NEGATIVE_CASES["PTA008"]()
        path = self._write_model(tmp_path, program, ["x", "unused"],
                                 fetches)
        assert main(["lint", path]) == 0          # warning only
        assert main(["lint", "--strict", path]) == 1

    def test_json_report(self, tmp_path, capsys):
        import json

        from paddle_tpu.cli import main
        program, feeds, fetches = NEGATIVE_CASES["PTA010"]()
        path = self._write_model(tmp_path, program, feeds, fetches)
        assert main(["lint", "--json", path]) == 1
        report = json.loads(capsys.readouterr().out)
        codes = [d["code"] for t in report["targets"]
                 for d in t["diagnostics"]]
        assert "PTA010" in codes

    def test_bad_target_exits_2(self, tmp_path):
        from paddle_tpu.cli import main
        assert main(["lint", str(tmp_path / "nope")]) == 2
        assert main(["lint"]) == 2
