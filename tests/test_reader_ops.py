"""Reader-as-IR-op tests (mirror reference test_recordio_reader.py,
test_multi_pass_reader.py, test_cpp_reader.py): recordio-backed training
through open_recordio_file/open_files + shuffle/batch/double_buffer/
multi_pass + read_file, with the compiled step staying whole-block XLA."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.recordio_writer import convert_reader_to_recordio_file


def _write_samples(path, n=64, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, dim).astype("float32")
    w = rng.rand(dim, 1).astype("float32")
    ys = (xs @ w + 0.1).astype("float32")

    def reader():
        for i in range(n):
            yield (xs[i], ys[i])

    convert_reader_to_recordio_file(str(path), reader)
    return xs, ys


class TestRecordIOReader:
    def test_read_file_roundtrip(self, tmp_path):
        p = tmp_path / "data.recordio"
        xs, ys = _write_samples(p)
        reader = layers.open_recordio_file(
            filename=str(p), shapes=[(8,), (1,)], lod_levels=[0, 0],
            dtypes=["float32", "float32"])
        reader = layers.batch(reader, batch_size=16)
        x, y = layers.read_file(reader)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        for i in range(4):
            xv, yv = exe.run(fluid.default_main_program(),
                             fetch_list=[x, y])
            np.testing.assert_allclose(xv, xs[i * 16:(i + 1) * 16],
                                       rtol=1e-6)
            np.testing.assert_allclose(yv, ys[i * 16:(i + 1) * 16],
                                       rtol=1e-6)
        with pytest.raises(fluid.EOFException):
            exe.run(fluid.default_main_program(), fetch_list=[x, y])
        reader.reset()
        (xv, yv) = exe.run(fluid.default_main_program(), fetch_list=[x, y])
        np.testing.assert_allclose(xv, xs[:16], rtol=1e-6)

    def test_train_from_recordio(self, tmp_path):
        p = tmp_path / "train.recordio"
        _write_samples(p, n=128)
        reader = layers.open_recordio_file(
            filename=str(p), shapes=[(8,), (1,)], lod_levels=[0, 0],
            dtypes=["float32", "float32"], pass_num=20)
        reader = layers.shuffle(reader, buffer_size=64, seed=3)
        reader = layers.batch(reader, batch_size=32)
        reader = layers.double_buffer(reader)
        x, y = layers.read_file(reader)
        pred = layers.fc(input=x, size=1)
        loss = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        losses = []
        while True:
            try:
                (lv,) = exe.run(fluid.default_main_program(),
                                fetch_list=[loss])
            except fluid.EOFException:
                break
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert len(losses) >= 60  # 20 passes x 4 full batches
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])

    def test_open_files_multi(self, tmp_path):
        rows = []
        paths = []
        for i in range(3):
            p = tmp_path / f"f{i}.recordio"
            xs, _ = _write_samples(p, n=16, seed=i)
            rows.extend(xs[:, 0].tolist())
            paths.append(str(p))
        reader = layers.open_files(
            filenames=paths, shapes=[(8,), (1,)], lod_levels=[0, 0],
            dtypes=["float32", "float32"], thread_num=2)
        reader = layers.batch(reader, batch_size=8)
        x, y = layers.read_file(reader)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        seen = []
        for _ in range(6):  # 48 samples
            xv, _ = exe.run(fluid.default_main_program(),
                            fetch_list=[x, y])
            seen.extend(np.asarray(xv)[:, 0].tolist())
        assert len(seen) == 48
        assert set(np.round(seen, 5)) == set(np.round(rows, 5))

    def test_random_data_generator(self):
        reader = layers.random_data_generator(
            low=0.0, high=1.0, shapes=[(4, 3)], lod_levels=[0], seed=7)
        x = layers.read_file(reader)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        (a,) = exe.run(fluid.default_main_program(), fetch_list=[x])
        (b,) = exe.run(fluid.default_main_program(), fetch_list=[x])
        assert a.shape == (4, 3)
        assert (a >= 0).all() and (a < 1).all()
        assert not np.allclose(a, b)

    def test_run_steps_reader_pipeline(self, tmp_path):
        """read ops feed the device-side multi-step loop: one dispatch,
        `steps` batches pulled and stacked on the host."""
        p = tmp_path / "steps.recordio"
        _write_samples(p, n=128)
        reader = layers.open_recordio_file(
            filename=str(p), shapes=[(8,), (1,)], lod_levels=[0, 0],
            dtypes=["float32", "float32"], pass_num=50)
        reader = layers.batch(reader, batch_size=32)
        x, y = layers.read_file(reader)
        pred = layers.fc(input=x, size=1)
        loss = layers.mean(layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        out = exe.run_steps(fluid.default_main_program(),
                            fetch_list=[loss], steps=40)
        series = np.asarray(out[0]).reshape(-1)
        assert series.shape[0] == 40
        assert series[-1] < series[0] * 0.1
