"""Static FLOPs/bytes cost model (paddle_tpu/analysis/cost) and its
three consumers: bucket-edge selection, GenScheduler admission
weights, pipeline stage balancing."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import cost
from paddle_tpu.lod import row_bucket, select_bucket_edges


def _matmul_program(m=4, k=8, n=16):
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data("x", shape=[m, k], dtype="float32",
                        append_batch_size=False)
        y = layers.data("y", shape=[k, n], dtype="float32",
                        append_batch_size=False)
        out = fluid.layers.matmul(x, y)
    return main, out


class TestEstimate:
    def test_matmul_flops_exact(self):
        main, _ = _matmul_program(4, 8, 16)
        r = cost.estimate(main)
        assert r.total_flops == 2 * 4 * 8 * 16
        assert r.uncovered == []
        assert r.total_bytes > 0

    def test_report_schema_and_by_op_type(self):
        main, _ = _matmul_program()
        r = cost.estimate(main)
        assert cost.validate_cost_report(r.to_dict()) == []
        agg = r.by_op_type()
        assert agg["matmul"]["count"] == 1
        # schema negatives
        bad = r.to_dict()
        bad["total_flops"] = -1
        assert cost.validate_cost_report(bad)
        assert cost.validate_cost_report({"nope": 1})
        assert cost.validate_cost_report([])

    def test_unknown_op_lands_on_uncovered_not_guessed(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.create_var(name="x", shape=(4,), dtype="float32",
                         is_data=True)
            b.append_op("totally_unknown_op", inputs={"X": ["x"]},
                        outputs={"Out": ["o"]}, attrs={})
        r = cost.estimate(main)
        assert "totally_unknown_op" in r.uncovered
        row = next(p for p in r.per_op
                   if p["op_type"] == "totally_unknown_op")
        assert row["flops"] == 0 and row["bytes"] == 0

    def test_zoo_estimates_have_flops_and_validate(self):
        from paddle_tpu.models import build_train_program
        for name in ("mnist", "transformer"):
            main, _s, _fd, _ft = build_train_program(name)
            r = cost.estimate(main)
            assert r.total_flops > 0, name
            assert cost.validate_cost_report(r.to_dict()) == [], name

    def test_op_flops_conv_formula(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            img = layers.data("img", shape=[3, 8, 8], dtype="float32")
            out = fluid.layers.conv2d(img, num_filters=4,
                                      filter_size=3)
        block = main.global_block()
        conv = next(op for op in block.ops if op.type == "conv2d")
        flops = cost.op_flops(conv, block)
        o = block.var(conv.output("Output")[0])
        n, co, ho, wo = o.shape
        assert flops == 2 * max(n, 1) * ho * wo * co * 3 * 3 * 3

    def test_row_cost_fn_affine_and_monotone(self):
        main, _ = _matmul_program()
        fn = cost.row_cost_fn(main, batch_var="x", dim=0,
                              probe_rows=(4, 8))
        assert fn(8) > fn(4) > 0
        # affine: doubling rows doubles the matmul term
        assert fn(16) == pytest.approx(2 * fn(8) - fn(4) * 0,
                                       rel=0.5)


class TestSelectBucketEdges:
    def test_picks_observed_modes(self):
        # heavy mass at 7 and 32: padding everything to 32 wastes 4x
        # on the common case — the DP must cut at 7
        counts = [7] * 90 + [32] * 10
        edges = select_bucket_edges(counts, max_edges=2)
        assert edges == [7, 32]

    def test_single_edge_when_budget_is_one(self):
        edges = select_bucket_edges([3, 5, 9], max_edges=1)
        assert edges == [9]  # must cover the max

    def test_cost_weighting_changes_the_cut(self):
        # linear cost picks the big mode; a quadratic cost makes
        # padding small items to the large edge far more expensive,
        # pulling the budgeted edge toward the small mode
        counts = [4] * 10 + [5] * 10 + [16] * 2
        lin = select_bucket_edges(counts, max_edges=2)
        quad = select_bucket_edges(counts, max_edges=2,
                                   cost_of=lambda e: float(e) ** 3)
        assert lin[-1] == quad[-1] == 16
        assert set(quad) == {5, 16}

    def test_empty_and_row_bucket_integration(self):
        assert select_bucket_edges([]) == []
        edges = select_bucket_edges([3, 3, 3, 11], max_edges=2)
        assert row_bucket(2, edges) == 3
        assert row_bucket(11, edges) == 11
        # past the largest edge: pow2 ladder fallback keeps keys bounded
        assert row_bucket(17, edges) == 32


class TestGenConsumers:
    @pytest.fixture(scope="class")
    def bundle_dir(self, tmp_path_factory):
        from paddle_tpu.models import gen_lm
        d = str(tmp_path_factory.mktemp("costgen") / "bundle")
        hp = gen_lm.GenConfig()
        hp.vocab_size, hp.d_model, hp.d_ffn = 32, 16, 32
        hp.n_head = hp.n_layer = 2
        hp.d_head, hp.max_len = 16, 16
        gen_lm.export_gen_model(d, hp, num_slots=2)
        return d

    def test_prefill_cost_monotone_in_bucket(self, bundle_dir):
        from paddle_tpu.gen import GenPredictor
        p = GenPredictor(bundle_dir)
        buckets = sorted(set(p._bucket(n)
                             for n in (1, p.max_prompt_len)))
        if len(buckets) < 2:
            pytest.skip("bundle has a single prompt bucket")
        costs = [p.prefill_cost(b) for b in buckets]
        assert costs == sorted(costs)
        assert costs[0] > 0

    def test_plan_prompt_buckets(self, bundle_dir):
        from paddle_tpu.gen import GenPredictor
        p = GenPredictor(bundle_dir)
        lengths = [3] * 50 + [12] * 5
        edges = p.plan_prompt_buckets(lengths, max_edges=2)
        assert edges == [3, 12]
        assert all(e <= p.max_len for e in edges)

    def test_scheduler_prefill_budget_paces_admissions(self):
        """With a budget of one prompt's cost, each _admit pass admits
        exactly one queued request (plus the always-free first) —
        admission is paced by static cost, and the queue still
        drains."""
        from paddle_tpu.gen.scheduler import GenScheduler

        class FakePredictor:
            num_slots = 4
            vocab_size = 8
            max_len = 32
            max_prompt_len = 16
            eos_id = -1
            prefill_calls = []

            def prefill(self, prompt):
                self.prefill_calls.append(tuple(prompt))
                kv = np.zeros((1, 1), np.float32)
                logits = np.zeros(self.vocab_size, np.float32)
                logits[7] = 1.0
                return logits, kv

            def prefill_cost(self, n):
                return 100.0 * n

            def write_slot(self, *a):
                pass

            def clear_slot(self, *a):
                pass

            def decode_step(self, tokens, positions, onehot, mask):
                out = np.zeros((self.num_slots, self.vocab_size),
                               np.float32)
                out[:, 7] = 1.0
                return out

        pred = FakePredictor()
        s = GenScheduler(pred, queue_size=8, prefill_budget=250.0)
        try:
            streams = [s.submit([1, 2], max_new_tokens=2)
                       for _ in range(4)]
            for st in streams:
                toks = list(st)
                assert toks and toks[0] == 7
            assert st.finish_reason in ("length", "eos")
        finally:
            s.close()
        # every request was eventually prefilled despite the budget
        assert len(pred.prefill_calls) == 4

    def test_budget_is_continuous_only(self):
        """Batch admission refills the pool as one unit (the
        request-level baseline); a budget cut mid-refill would strand
        unfilled slots for a whole batch generation — so the budget is
        silently inert there."""
        from paddle_tpu.gen.scheduler import GenScheduler

        class Pred:
            num_slots, vocab_size, max_len = 2, 8, 16
            max_prompt_len, eos_id = 8, -1

            def prefill_cost(self, n):
                return 1.0

        s = GenScheduler(Pred(), admission="batch", prefill_budget=5.0)
        try:
            assert s.prefill_budget is None
        finally:
            s.close()
        s = GenScheduler(Pred(), prefill_budget=5.0)
        try:
            assert s.prefill_budget == 5.0
        finally:
            s.close()


class TestPipelineBalancing:
    def test_stage_weights_ride_the_shared_cost_model(self):
        from paddle_tpu.parallel.pipeline_transpiler import _op_cost
        main, _ = _matmul_program(4, 8, 16)
        block = main.global_block()
        mm = next(op for op in block.ops if op.type == "matmul")
        assert _op_cost(mm, block) == \
            1 + cost.op_flops(mm, block, default=0)
        assert _op_cost(mm, block) > 1  # really priced, not the old 1

    def test_quantile_cuts_balance_flops(self):
        # two matmuls of equal cost + cheap glue: a 2-stage split must
        # put one matmul on each side
        from paddle_tpu.parallel.pipeline_transpiler import \
            split_program
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = layers.data("x", shape=[8, 8], dtype="float32",
                            append_batch_size=False)
            w = layers.data("w", shape=[8, 8], dtype="float32",
                            append_batch_size=False)
            a = fluid.layers.matmul(x, w)
            b = fluid.layers.relu(a)
            c = fluid.layers.matmul(b, w)
            d = fluid.layers.relu(c)
        _, stage_ops, _, _ = split_program(
            main, 2, ["x", "w"], [d.name])
        types0 = [op.type for op in stage_ops[0]]
        types1 = [op.type for op in stage_ops[1]]
        assert types0.count("matmul") == 1
        assert types1.count("matmul") == 1
