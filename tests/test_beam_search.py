"""Beam search: op-level semantics + an MT inference decode of a trained
toy seq2seq (reference ``beam_search_op.cc``, ``beam_search_decode_op.cc``,
``tests/book/test_machine_translation.py`` decode path)."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers


# ---------------------------------------------------------------------------
# op-level
# ---------------------------------------------------------------------------

def _run_beam_search(pre_ids, pre_scores, ids, scores, K, end_id):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p_i = layers.data(name="p_i", shape=list(pre_ids.shape),
                          dtype="int64", append_batch_size=False)
        p_s = layers.data(name="p_s", shape=list(pre_scores.shape),
                          dtype="float32", append_batch_size=False)
        c_i = layers.data(name="c_i", shape=list(ids.shape),
                          dtype="int64", append_batch_size=False)
        c_s = layers.data(name="c_s", shape=list(scores.shape),
                          dtype="float32", append_batch_size=False)
        s_i, s_s, par = layers.beam_search(p_i, p_s, c_i, c_s, K, end_id)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed={"p_i": pre_ids, "p_s": pre_scores,
                               "c_i": ids, "c_s": scores},
                   fetch_list=[s_i, s_s, par])


class TestBeamSearchOp:
    def test_topk_across_beams(self):
        # B=1, K=2, C=2: all live; candidates with accumulated scores
        pre_ids = np.array([[5, 7]], "int64")
        pre_scores = np.array([[-1.0, -2.0]], "float32")
        ids = np.array([[[10, 11], [12, 13]]], "int64")
        scores = np.array([[[-1.5, -3.0], [-2.1, -9.0]]], "float32")
        s_i, s_s, par = _run_beam_search(pre_ids, pre_scores, ids, scores,
                                         2, end_id=0)
        # best two accumulated: -1.5 (beam0 tok10), -2.1 (beam1 tok12)
        np.testing.assert_array_equal(s_i, [[10, 12]])
        np.testing.assert_allclose(s_s, [[-1.5, -2.1]], atol=1e-6)
        np.testing.assert_array_equal(par, [[0, 1]])

    def test_finished_beam_keeps_score_and_end_id(self):
        end = 0
        pre_ids = np.array([[end, 7]], "int64")      # beam 0 finished
        pre_scores = np.array([[-0.5, -2.0]], "float32")
        ids = np.array([[[10, 11], [12, 13]]], "int64")
        scores = np.array([[[-0.1, -0.2], [-2.5, -9.0]]], "float32")
        s_i, s_s, par = _run_beam_search(pre_ids, pre_scores, ids, scores,
                                         2, end_id=end)
        # finished beam contributes ONLY (end, -0.5); its candidate scores
        # (-0.1, better than anything) must be ignored
        np.testing.assert_array_equal(s_i, [[end, 12]])
        np.testing.assert_allclose(s_s, [[-0.5, -2.5]], atol=1e-6)
        np.testing.assert_array_equal(par, [[0, 1]])


class TestBeamSearchDecodeOp:
    def test_backtrack(self):
        # B=1, K=2, T=3; hand-built parent chains
        main, startup = fluid.Program(), fluid.Program()
        steps_ids = [np.array([[4, 5]], "int64"),
                     np.array([[6, 7]], "int64"),
                     np.array([[8, 9]], "int64")]
        # step parents: t=0 trivial; t=1: beam0<-1, beam1<-0;
        # t=2: beam0<-0, beam1<-1
        steps_par = [np.array([[0, 1]], "int64"),
                     np.array([[1, 0]], "int64"),
                     np.array([[0, 1]], "int64")]
        with fluid.program_guard(main, startup):
            i0 = layers.zeros(shape=[1], dtype="int64")
            ids_arr = layers.array_write(
                layers.assign(steps_ids[0]), i=i0)
            par_arr = layers.array_write(
                layers.assign(steps_par[0]), i=i0)
            for t in (1, 2):
                it = layers.fill_constant(shape=[1], dtype="int64", value=t)
                layers.array_write(layers.assign(steps_ids[t]), i=it,
                                   array=ids_arr)
                layers.array_write(layers.assign(steps_par[t]), i=it,
                                   array=par_arr)
            final_scores = layers.assign(
                np.array([[-1.0, -2.0]], "float32"))
            sent, sscores = layers.beam_search_decode(
                ids_arr, par_arr, final_scores, max_len=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        seq, sc = exe.run(main, fetch_list=[sent, sscores])
        # final beam 0: t2 tok 8 parent 0 -> t1 beam0 tok 6 parent 1 ->
        # t0 beam1 tok 5
        np.testing.assert_array_equal(seq[0, 0], [5, 6, 8])
        # final beam 1: t2 tok 9 parent 1 -> t1 tok 7 parent 0 -> t0 tok 4
        np.testing.assert_array_equal(seq[0, 1], [4, 7, 9])
        np.testing.assert_allclose(sc, [[-1.0, -2.0]], atol=1e-6)


# ---------------------------------------------------------------------------
# end-to-end: train a toy seq2seq, beam-decode actual token ids
# ---------------------------------------------------------------------------

DICT, EMB, HID = 64, 16, 32
B, K, SRC_LEN, TRG_LEN = 4, 3, 6, 5
START = 1


def _build_train():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data(name="src", shape=[-1, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        trg = layers.data(name="trg", shape=[-1, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        label = layers.data(name="label", shape=[-1, 1], dtype="int64",
                            append_batch_size=False, lod_level=1)
        src_emb = layers.embedding(input=src, size=[DICT, EMB],
                                   param_attr=fluid.ParamAttr("src_emb_w"))
        enc_proj = layers.fc(input=src_emb, size=HID * 3,
                             param_attr=fluid.ParamAttr("enc_proj_w"),
                             bias_attr=False)
        # reversed encoder: the t=0 state has consumed the whole source
        # ending at src[0] (the chain seed), so sequence_first_step carries
        # the seed directly into the decoder init
        enc = layers.dynamic_gru(input=enc_proj, size=HID, is_reverse=True,
                                 param_attr=fluid.ParamAttr("enc_gru_w"),
                                 bias_attr=fluid.ParamAttr("enc_gru_b"))
        enc_last = layers.sequence_first_step(enc)
        trg_emb = layers.embedding(input=trg, size=[DICT, EMB],
                                   param_attr=fluid.ParamAttr("trg_emb_w"))
        drnn = layers.DynamicRNN()
        with drnn.block():
            cur = drnn.step_input(trg_emb)
            mem = drnn.memory(init=enc_last)
            dec_h = layers.fc(input=[cur, mem], size=HID, act="tanh",
                              param_attr=[fluid.ParamAttr("dec_fc_w_x"),
                                          fluid.ParamAttr("dec_fc_w_h")],
                              bias_attr=fluid.ParamAttr("dec_fc_b"))
            drnn.update_memory(mem, dec_h)
            out = layers.fc(input=dec_h, size=DICT, act="softmax",
                            param_attr=fluid.ParamAttr("dec_out_w"),
                            bias_attr=fluid.ParamAttr("dec_out_b"))
            drnn.output(out)
        predictions = drnn()
        cost = layers.cross_entropy(input=predictions, label=label)
        avg_cost = layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
    return main, startup, avg_cost


def _build_decode():
    """Unrolled beam decode re-using the TRAINED parameter names."""
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        src = layers.data(name="src", shape=[-1, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        src_emb = layers.embedding(input=src, size=[DICT, EMB],
                                   param_attr=fluid.ParamAttr("src_emb_w"))
        enc_proj = layers.fc(input=src_emb, size=HID * 3,
                             param_attr=fluid.ParamAttr("enc_proj_w"),
                             bias_attr=False)
        # reversed encoder: the t=0 state has consumed the whole source
        # ending at src[0] (the chain seed), so sequence_first_step carries
        # the seed directly into the decoder init
        enc = layers.dynamic_gru(input=enc_proj, size=HID, is_reverse=True,
                                 param_attr=fluid.ParamAttr("enc_gru_w"),
                                 bias_attr=fluid.ParamAttr("enc_gru_b"))
        enc_last = layers.sequence_first_step(enc)          # [B, HID]

        # tile the encoder state over the beam axis: [B*K, HID]
        mem = layers.reshape(
            layers.expand(layers.reshape(enc_last, shape=[B, 1, HID]),
                          expand_times=[1, K, 1]),
            shape=[B * K, HID])

        pre_ids = layers.assign(np.full((B, K), START, "int64"))
        pre_scores = layers.assign(
            np.tile(np.array([[0.0] + [-1e9] * (K - 1)], "float32"),
                    (B, 1)))
        beam_offset = layers.assign(
            (np.arange(B, dtype="int64")[:, None] * K).repeat(K, 1))

        i0 = layers.zeros(shape=[1], dtype="int64")
        ids_arr = None
        par_arr = None
        for t in range(TRG_LEN):
            cur = layers.embedding(
                input=layers.reshape(pre_ids, shape=[B * K, 1]),
                size=[DICT, EMB], param_attr=fluid.ParamAttr("trg_emb_w"))
            dec_h = layers.fc(input=[cur, mem], size=HID, act="tanh",
                              param_attr=[fluid.ParamAttr("dec_fc_w_x"),
                                          fluid.ParamAttr("dec_fc_w_h")],
                              bias_attr=fluid.ParamAttr("dec_fc_b"))
            out = layers.fc(input=dec_h, size=DICT, act="softmax",
                            param_attr=fluid.ParamAttr("dec_out_w"),
                            bias_attr=fluid.ParamAttr("dec_out_b"))
            probs = layers.reshape(out, shape=[B, K, DICT])
            topk_scores, topk_idx = layers.topk(probs, k=K)   # [B, K, K]
            acc = layers.ops.log(topk_scores) + layers.reshape(
                pre_scores, shape=[B, K, 1])
            sel_ids, sel_scores, parent = layers.beam_search(
                pre_ids, pre_scores, topk_idx, acc, K, end_id=0)
            # reorder decoder memories by parent beam
            flat_parent = layers.reshape(parent + beam_offset,
                                         shape=[B * K])
            mem = layers.gather(dec_h, flat_parent)
            it = layers.fill_constant(shape=[1], dtype="int64", value=t)
            if ids_arr is None:
                ids_arr = layers.array_write(sel_ids, i=it)
                par_arr = layers.array_write(parent, i=it)
            else:
                layers.array_write(sel_ids, i=it, array=ids_arr)
                layers.array_write(parent, i=it, array=par_arr)
            pre_ids, pre_scores = sel_ids, sel_scores

        sent, sscores = layers.beam_search_decode(
            ids_arr, par_arr, pre_scores, max_len=TRG_LEN)
    return prog, startup, sent, sscores


def test_mt_beam_decode_nondegenerate():
    from tests.test_book_machine_translation import _batches

    train, startup, avg_cost = _build_train()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for src_f, src_lod, trg_f, trg_lod, lab in _batches(200):
            (lv,) = exe.run(
                train,
                feed={"src": (src_f, src_lod), "trg": (trg_f, trg_lod),
                      "label": (lab, trg_lod)},
                fetch_list=[avg_cost])
        assert float(np.asarray(lv).reshape(())) < 1.5

        decode, dec_startup, sent, sscores = _build_decode()
        # do NOT run dec_startup: every decode parameter is named and
        # already trained; re-running init ops would clobber them (same
        # behavior as the reference executor)
        rng = np.random.RandomState(7)
        src = rng.randint(2, DICT, size=(B, SRC_LEN)).astype("int64")
        src_lod = [list(range(0, B * SRC_LEN + 1, SRC_LEN))]
        seqs, scores = exe.run(
            decode, feed={"src": (src.reshape(-1, 1), src_lod)},
            fetch_list=[sent, sscores])

    assert seqs.shape == (B, K, TRG_LEN)
    # non-degenerate: top beams differ across examples and aren't constant
    top = seqs[:, 0, :]
    assert len({tuple(r) for r in top}) > 1
    assert not np.all(top == top[:, :1])
    # the task is deterministic (next = 3*prev+1 seeded by src[:,0]); a
    # trained model's top beam should match most target positions
    want = np.empty((B, TRG_LEN), "int64")
    want[:, 0] = (src[:, 0] * 3 + 1) % DICT
    for t in range(1, TRG_LEN):
        want[:, t] = (want[:, t - 1] * 3 + 1) % DICT
    acc = (top == want).mean()
    assert acc > 0.6, (acc, top[:2], want[:2])
    # beams come back best-first
    assert np.all(np.diff(scores, axis=1) <= 1e-5)


class TestCrossEntropyOverBeam:
    """Training criterion over beam expansions (reference
    CrossEntropyOverBeam.cpp:1-393)."""

    def _run_cost(self, feeds, n_expansions, lod_levels, fetch_grads=()):
        import paddle_tpu.trainer_config_helpers as tch
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            beams = []
            for i in range(n_expansions):
                sc = layers.data(f"sc{i}", shape=[-1, 1], dtype="float32",
                                 append_batch_size=False,
                                 lod_level=lod_levels[i])
                sc.stop_gradient = False
                ids = layers.data(f"ids{i}", shape=[-1, -1], dtype="int64",
                                  append_batch_size=False)
                gold = layers.data(f"g{i}", shape=[-1], dtype="int64",
                                   append_batch_size=False)
                beams.append(tch.BeamInput(sc, ids, gold))
            cost = tch.cross_entropy_over_beam(beams)
            loss = layers.reduce_sum(cost)
            fluid.append_backward(loss)
        exe = fluid.Executor()
        exe.run(startup)
        outs = exe.run(main, feed=feeds,
                       fetch_list=[cost.name] + list(fetch_grads))
        return [np.asarray(o) for o in outs]

    def test_single_expansion_is_softmax_ce_over_candidates(self):
        # one sequence, 4 candidates, beam picks ids [2, 0]; gold id 2.
        # paths = the selected candidates; cost = -log softmax over their
        # scores at gold's slot
        sc = np.array([[0.1], [0.9], [0.4], [0.3]], "float32")
        feeds = {"sc0": (sc, [[0, 4]]),
                 "ids0": np.array([[2, 0]], "int64"),
                 "g0": np.array([2], "int64")}
        (cost,) = self._run_cost(feeds, 1, [1])
        z = np.array([0.4, 0.1])  # scores of selected ids 2, 0
        want = -np.log(np.exp(z[0]) / np.exp(z).sum())
        np.testing.assert_allclose(cost.reshape(()), want, rtol=1e-5)

    def test_gold_off_beam_becomes_extra_path(self):
        # gold id 3 NOT among selected [2, 0] -> appended as extra path
        sc = np.array([[0.1], [0.9], [0.4], [0.3]], "float32")
        feeds = {"sc0": (sc, [[0, 4]]),
                 "ids0": np.array([[2, 0]], "int64"),
                 "g0": np.array([3], "int64")}
        (cost,) = self._run_cost(feeds, 1, [1])
        z = np.array([0.4, 0.1, 0.3])  # selected + appended gold
        want = -np.log(np.exp(z[2]) / np.exp(z).sum())
        np.testing.assert_allclose(cost.reshape(()), want, rtol=1e-5)

    def test_two_expansions_path_scores(self):
        # seq with 3 first-step candidates, beam_size 2 selects [1, 0];
        # expansion 1: one sub-seq per selected candidate (2 sub-seqs,
        # 2 candidates each); second beam selects [0, 1] from gold row.
        # gold path: step0 id 1 (row select), step1 id 0.
        sc0 = np.array([[0.5], [1.0], [0.2]], "float32")
        ids0 = np.array([[1, 0]], "int64")
        g0 = np.array([1], "int64")
        # 2 sub-seqs, rows: [a0 a1 | b0 b1]
        sc1 = np.array([[0.3], [0.7], [0.9], [0.1]], "float32")
        ids1 = np.array([[0, 1], [1, -1]], "int64")  # per sub-seq picks
        g1 = np.array([0], "int64")
        feeds = {"sc0": (sc0, [[0, 3]]),
                 "ids0": ids0, "g0": g0,
                 "sc1": (sc1, [[0, 2], [0, 2, 4]]),
                 "ids1": ids1, "g1": g1}
        (cost,) = self._run_cost(feeds, 2, [1, 2])
        # paths (slots of ids1 row-major): (row0,id0)=1.0+0.3,
        # (row0,id1)=1.0+0.7, (row1,id1)=0.5+0.1; gold = first
        z = np.array([1.3, 1.7, 0.6])
        want = -np.log(np.exp(z[0]) / np.exp(z).sum())
        np.testing.assert_allclose(cost.reshape(()), want, rtol=1e-5)

    def test_padded_row_maps_through_nonpad_slots(self):
        # the documented padding contract (the reference's
        # TODO(caoying) case): ids0 has a -1 pad BEFORE the gold pick,
        # so gold's sub-sequence in expansion 1 is the count of
        # non-(-1) slots before it (here 1), NOT its raw slot index
        # (here 2 — one past the last sub-sequence that exists)
        sc0 = np.array([[0.5], [1.0], [0.2]], "float32")
        ids0 = np.array([[2, -1, 1]], "int64")  # slot 1 under-filled
        g0 = np.array([1], "int64")             # picked at slot 2
        # 2 sub-seqs — one per non-pad slot of ids0 (ids 2, then 1)
        sc1 = np.array([[0.3], [0.7], [0.9], [0.1]], "float32")
        ids1 = np.array([[0, -1, -1], [1, 0, -1]], "int64")
        g1 = np.array([1], "int64")
        feeds = {"sc0": (sc0, [[0, 3]]),
                 "ids0": ids0, "g0": g0,
                 "sc1": (sc1, [[0, 2], [0, 2, 4]]),
                 "ids1": ids1, "g1": g1}
        (cost,) = self._run_cost(feeds, 2, [1, 2])
        # paths (non-pad slots of ids1, row-major): (0,0) parent id 2,
        # (1,0) and (1,1) parent id 1 — gold's row is sub-seq 1, so
        # gold's path is (1,0): score 1.0 + 0.1
        z = np.array([0.2 + 0.3, 1.0 + 0.1, 1.0 + 0.9])
        want = -np.log(np.exp(z[1]) / np.exp(z).sum())
        np.testing.assert_allclose(cost.reshape(()), want, rtol=1e-5)

    def test_gradients_numeric(self):
        # central differences on every candidate score, single expansion
        sc = np.array([[0.1], [0.9], [0.4], [0.3]], "float32")
        feeds = {"sc0": (sc, [[0, 4]]),
                 "ids0": np.array([[2, 0, 1]], "int64"),
                 "g0": np.array([0], "int64")}
        cost, grad = self._run_cost(feeds, 1, [1],
                                    fetch_grads=["sc0@GRAD"])
        eps = 1e-3
        for r in range(4):
            up, dn = sc.copy(), sc.copy()
            up[r, 0] += eps
            dn[r, 0] -= eps
            cu = self._run_cost({**feeds, "sc0": (up, [[0, 4]])},
                                1, [1])[0]
            cd = self._run_cost({**feeds, "sc0": (dn, [[0, 4]])},
                                1, [1])[0]
            num = (cu.sum() - cd.sum()) / (2 * eps)
            np.testing.assert_allclose(grad[r, 0], num, atol=1e-3)

    def test_trains_through_kmax_selection(self):
        """A legacy-DSL config: network scores -> kmax_seq_score beam ->
        cross_entropy_over_beam; the gold candidate's score must rise."""
        import paddle_tpu.trainer_config_helpers as tch
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[-1, 4], dtype="float32",
                            append_batch_size=False, lod_level=1)
            h = layers.fc(x, 1, bias_attr=False,
                          param_attr=fluid.ParamAttr("ceob_w"))
            h.lod_level = 1
            sel = tch.kmax_seq_score_layer(h, beam_size=3)
            gold = layers.data("gold", shape=[-1], dtype="int64",
                               append_batch_size=False)
            cost = tch.cross_entropy_over_beam(
                tch.BeamInput(candidate_scores=h,
                              selected_candidates=sel, gold=gold))
            loss = layers.reduce_sum(cost)
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        xv = rng.rand(6, 4).astype("f")
        lod = [[0, 3, 6]]
        gv = np.array([1, 2], "int64")
        losses = []
        for _ in range(25):
            (lv,) = exe.run(main, feed={"x": (xv, lod), "gold": gv},
                            fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(())))
        assert losses[-1] < losses[0] * 0.7, losses
