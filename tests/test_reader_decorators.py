"""Reader-decorator semantics (paddle_tpu/reader/decorator.py).

Covers the full decorator surface the reference exposes in
``python/paddle/reader/decorator.py`` (map/shuffle/chain/compose/
buffered/firstn/cache/xmap), including the threaded paths and the
ordered-xmap re-sequencing.
"""

import numpy as np

from paddle_tpu.reader import decorator as dec


def r10():
    return lambda: iter(range(10))


class TestPureDecorators:
    def test_map_readers(self):
        got = list(dec.map_readers(lambda a, b: a + b, r10(), r10())())
        assert got == [2 * i for i in range(10)]

    def test_shuffle_preserves_multiset(self):
        out = list(dec.shuffle(r10(), 4)())
        assert sorted(out) == list(range(10))

    def test_chain(self):
        assert list(dec.chain(r10(), r10())()) == list(range(10)) * 2

    def test_compose_aligned(self):
        got = list(dec.compose(r10(), r10())())
        assert got[0] == (0, 0) and len(got) == 10

    def test_compose_misaligned_raises(self):
        short = lambda: iter(range(5))
        try:
            list(dec.compose(r10(), short)())
            raise AssertionError("expected ComposeNotAligned")
        except dec.ComposeNotAligned:
            pass
        # unchecked mode: shortest stream wins
        got = list(dec.compose(r10(), short, check_alignment=False)())
        assert len(got) == 5

    def test_firstn(self):
        assert list(dec.firstn(r10(), 3)()) == [0, 1, 2]

    def test_cache_partial_pass_not_cached(self):
        calls = [0]

        def counting():
            calls[0] += 1
            return iter(range(5))

        c = dec.cache(counting)
        next(c())  # abandon midway -> must NOT poison the cache
        assert list(c()) == list(range(5))
        assert calls[0] == 2
        assert list(c()) == list(range(5))
        assert calls[0] == 2  # served from memory


class TestThreadedDecorators:
    def test_buffered(self):
        assert list(dec.buffered(r10(), 3)()) == list(range(10))

    def test_xmap_unordered_multiset(self):
        out = sorted(dec.xmap_readers(lambda x: x * 2, r10(), 3, 4)())
        assert out == [2 * i for i in range(10)]

    def test_xmap_ordered_exact_order(self):
        out = list(dec.xmap_readers(lambda x: x * 2, r10(), 3, 4,
                                    order=True)())
        assert out == [2 * i for i in range(10)]

    def test_xmap_ordered_numpy_payloads(self):
        # the re-sequencing heap must key on position only — numpy
        # payloads are not comparable
        arr_reader = lambda: (np.full((3,), i) for i in range(20))
        out = list(dec.xmap_readers(lambda x: x + 1, arr_reader, 4, 2,
                                    order=True)())
        assert all((o == i + 1).all() for i, o in enumerate(out))


class TestV2Plot:
    def test_ploter_accumulates_and_saves(self, tmp_path):
        from paddle_tpu.v2.plot import Ploter
        p = Ploter("train_cost", "test_cost")
        for i in range(5):
            p.append("train_cost", i, 1.0 / (i + 1))
        p.append("test_cost", 0, 0.9)
        assert p.curves["train_cost"].step == [0, 1, 2, 3, 4]
        out = tmp_path / "curve.png"
        p.plot(path=str(out))
        if p._plt is not None:
            assert out.exists() and out.stat().st_size > 0
        p.reset()
        assert p.curves["train_cost"].step == []

    def test_ploter_disabled_is_noop(self, monkeypatch):
        monkeypatch.setenv("DISABLE_PLOT", "True")
        from paddle_tpu.v2.plot.plot import Ploter
        p = Ploter("c")
        p.append("c", 0, 1.0)
        p.plot()  # must not raise without matplotlib state


class TestAbandonedConsumerThreadCleanup:
    """An abandoned iteration (break/close/GC) used to leave the pump
    and xmap worker threads blocked forever on full queues."""

    def _wait_threads(self, baseline, timeout=5.0):
        import threading
        import time
        deadline = time.time() + timeout
        while threading.active_count() > baseline and \
                time.time() < deadline:
            time.sleep(0.01)
        return threading.active_count()

    def test_buffered_abandon_releases_pump_thread(self):
        import threading
        baseline = threading.active_count()

        def endless():
            i = 0
            while True:
                yield i
                i += 1

        it = dec.buffered(lambda: endless(), 2)()
        assert next(it) == 0
        it.close()  # abandon with the queue full and the pump blocked
        assert self._wait_threads(baseline) <= baseline

    def test_xmap_abandon_releases_feeder_and_workers(self):
        import threading
        baseline = threading.active_count()

        def endless():
            i = 0
            while True:
                yield i
                i += 1

        for order in (False, True):
            it = dec.xmap_readers(lambda x: x * 2, lambda: endless(),
                                  3, 2, order=order)()
            assert next(it) is not None
            it.close()
            assert self._wait_threads(baseline) <= baseline, order

    def test_buffered_still_completes_normally_after_fix(self):
        assert list(dec.buffered(r10(), 2)()) == list(range(10))


class TestThreadedErrorPropagation:
    def test_buffered_reraises_producer_exception(self):
        def bad():
            yield 1
            raise IOError("truncated stream")

        it = dec.buffered(lambda: bad(), 4)()
        assert next(it) == 1
        try:
            list(it)
            raise AssertionError("expected IOError")
        except IOError:
            pass

    def test_xmap_reraises_mapper_exception(self):
        def mapper(x):
            if x == 5:
                raise ValueError("boom")
            return x

        try:
            list(dec.xmap_readers(mapper, lambda: iter(range(10)),
                                  2, 2)())
            raise AssertionError("expected ValueError")
        except ValueError:
            pass

    def test_xmap_ordered_reraises_and_does_not_hang(self):
        def mapper(x):
            if x == 3:
                raise ValueError("boom")
            return x

        try:
            list(dec.xmap_readers(mapper, lambda: iter(range(10)),
                                  3, 2, order=True)())
            raise AssertionError("expected ValueError")
        except ValueError:
            pass

    def test_xmap_ordered_long_stream_window(self):
        # the in-flight window must keep a long ordered stream moving
        out = list(dec.xmap_readers(lambda x: x * 2,
                                    lambda: iter(range(500)), 4, 4,
                                    order=True)())
        assert out == [i * 2 for i in range(500)]
