"""Elastic distributed training: ZeRO shard checkpoints, mesh-elastic
restore, and the kill→shrink→resume acceptance drill
(docs/fault_tolerance.md "Elastic resume").

In-process tests (shard-format round trip, topology verification,
dp4→dp2→dp8 resharding, the ckpt.shard.write / ckpt.reshard failpoint
semantics, datapipe repositioning, the ckpt CLI) are tier-1; the
subprocess kill drills are additionally marked slow."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
import paddle_tpu.layers as layers
from paddle_tpu.datapipe.core import PipelineStateError
from paddle_tpu.fault import (CheckpointManager, CorruptCheckpoint,
                              FaultInjected, ReshardError, chaos,
                              verify_checkpoint)
from paddle_tpu.fault import shard_ckpt
from paddle_tpu.framework import unique_name_scope
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import make_mesh


@pytest.fixture(autouse=True)
def _clean_failpoints():
    chaos.clear()
    yield
    chaos.clear()


BATCH = 16


def _build(batch=BATCH):
    """Deterministic adam model; unique_name_scope('') makes rebuilds
    produce IDENTICAL var names (the fresh-process restore pattern)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with unique_name_scope(""), fluid.program_guard(main, startup):
        img = layers.data(name="img", shape=[batch, 32],
                          append_batch_size=False)
        label = layers.data(name="label", shape=[batch, 1], dtype="int64",
                            append_batch_size=False)
        hidden = layers.fc(input=img, size=64, act="relu")
        pred = layers.fc(input=hidden, size=8, act="softmax")
        loss = layers.mean(layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def _feed(batch=BATCH, seed=0):
    rng = np.random.RandomState(seed)
    return {"img": rng.rand(batch, 32).astype("float32"),
            "label": rng.randint(0, 8, (batch, 1)).astype("int64")}


def _dp_mesh(n):
    return make_mesh((n,), ("data",), devices=jax.devices()[:n])


def _train_and_save(tmp_path, dp_degree=4, steps=3, save_step=None,
                    async_save=False):
    """Run ``steps`` ZeRO dp steps and shard-save the final state.
    Returns (manager, pexe, scope, loss_var, reference state dict)."""
    main, startup, loss = _build()
    mesh = _dp_mesh(dp_degree)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        pexe = ParallelExecutor(loss_name=loss.name, main_program=main,
                                mesh=mesh, zero=True)
        for _ in range(steps):
            pexe.run(feed=_feed(), fetch_list=[loss])
        mgr = CheckpointManager(
            str(tmp_path), executor=pexe, main_program=main, scope=scope,
            mesh=mesh, shard_specs=pexe.zero_plan.checkpoint_specs())
        step = steps if save_step is None else save_step
        if async_save:
            mgr.save_async(step).result()
        else:
            mgr.save(step)
        topo = shard_ckpt.read_manifest(mgr.path(step))["topology"]
        ref = {n: np.asarray(scope.find_var(n)).copy()
               for n in topo["shards"]}
    return mgr, pexe, scope, loss, ref


class TestShardCheckpoint:
    def test_roundtrip_same_mesh(self, tmp_path):
        mgr, _, _, _, ref = _train_and_save(tmp_path)
        verify_checkpoint(mgr.path(3))
        main2, startup2, _ = _build()
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe = fluid.Executor()
            exe.run(startup2)
            mgr2 = CheckpointManager(str(tmp_path), executor=exe,
                                     main_program=main2, scope=s2)
            assert mgr2.restore_latest(mesh=_dp_mesh(4)) == 3
            for n, want in ref.items():
                np.testing.assert_array_equal(
                    np.asarray(s2.find_var(n)), want)

    def test_topology_record_and_shard_files(self, tmp_path):
        mgr, pexe, _, _, _ = _train_and_save(tmp_path)
        manifest = shard_ckpt.read_manifest(mgr.path(3))
        topo = manifest["topology"]
        assert topo["mesh_shape"] == [4]
        assert topo["axis_names"] == ["data"]
        assert shard_ckpt.validate_topology(manifest) == []
        # every ZeRO-sharded accumulator writes one file per dp rank,
        # each individually checksummed in the manifest
        for name in pexe.zero_plan.placements:
            rec = topo["shards"][name]
            assert rec["num_shards"] == 4
            assert rec["shard_ranks"] == [0, 1, 2, 3]
            for k in range(4):
                rel = shard_ckpt.shard_relpath(name, k, 4)
                assert rel in manifest["files"]
                assert os.path.exists(os.path.join(mgr.path(3), rel))
        # params stay replicated: one shard
        assert any(rec["num_shards"] == 1
                   for rec in topo["shards"].values())

    def test_verify_detects_missing_shard_and_tampered_topology(
            self, tmp_path):
        mgr, pexe, _, _, _ = _train_and_save(tmp_path)
        path = mgr.path(3)
        name = next(iter(pexe.zero_plan.placements))
        victim = os.path.join(path, shard_ckpt.shard_relpath(name, 2, 4))
        os.remove(victim)
        with pytest.raises(CorruptCheckpoint, match="missing file"):
            verify_checkpoint(path)
        # second checkpoint: tamper the GEOMETRY instead — per-file
        # hashes still pass, the topology cross-check must fail it
        mgr2, _, _, _, _ = _train_and_save(tmp_path / "b")
        manifest2 = shard_ckpt.read_manifest(mgr2.path(3))
        manifest2["topology"]["shards"][name]["num_shards"] = 8
        with open(os.path.join(mgr2.path(3), "MANIFEST.json"), "w") as f:
            json.dump(manifest2, f)
        with pytest.raises(CorruptCheckpoint, match="topology"):
            verify_checkpoint(mgr2.path(3))

    def test_save_async_snapshots_at_call_time(self, tmp_path):
        """save_async captures the state ON the call (the step path);
        mutations after it return must not leak into the commit."""
        main, startup, loss = _build()
        mesh = _dp_mesh(4)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pexe = ParallelExecutor(loss_name=loss.name,
                                    main_program=main, mesh=mesh,
                                    zero=True)
            pexe.run(feed=_feed(), fetch_list=[loss])
            mgr = CheckpointManager(
                str(tmp_path), executor=pexe, main_program=main,
                scope=scope, mesh=mesh,
                shard_specs=pexe.zero_plan.checkpoint_specs())
            pname = main.global_block().all_parameters()[0].name
            want = np.asarray(scope.find_var(pname)).copy()
            fut = mgr.save_async(1)
            # the training loop keeps stepping while the writer commits
            pexe.run(feed=_feed(seed=9), fetch_list=[loss])
            assert fut.result().endswith("ckpt-1")
            assert mgr.last_committed_step == 1
        main2, startup2, _ = _build()
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe = fluid.Executor()
            exe.run(startup2)
            mgr2 = CheckpointManager(str(tmp_path), executor=exe,
                                     main_program=main2, scope=s2)
            assert mgr2.restore_latest() == 1
            np.testing.assert_array_equal(np.asarray(s2.find_var(pname)),
                                          want)

    def test_mark_good_drains_pending_async_save(self, tmp_path):
        """mark_good immediately after save_async must wait for the
        commit instead of silently refusing the not-yet-renamed dir
        (the natural sentinel pattern: save_async -> mark_good)."""
        main, startup, loss = _build()
        mesh = _dp_mesh(4)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pexe = ParallelExecutor(loss_name=loss.name,
                                    main_program=main, mesh=mesh,
                                    zero=True)
            pexe.run(feed=_feed(), fetch_list=[loss])
            mgr = CheckpointManager(
                str(tmp_path), executor=pexe, main_program=main,
                scope=scope, mesh=mesh,
                shard_specs=pexe.zero_plan.checkpoint_specs())
            mgr.save_async(1)
            assert mgr.mark_good(1) == 1     # drained, then promoted
            assert mgr.last_good_step() == 1

    def test_shard_write_fault_leaves_previous_restorable(self,
                                                          tmp_path):
        """ckpt.shard.write firing mid-save: the commit must not land —
        the previous checkpoint stays the restore target, and the torn
        temp dir is swept by the next save's GC."""
        mgr, pexe, scope, loss, ref = _train_and_save(tmp_path,
                                                      steps=2,
                                                      save_step=1)
        with fluid.scope_guard(scope):
            chaos.inject("ckpt.shard.write", after=3)
            with pytest.raises(FaultInjected):
                mgr.save(2)
            chaos.clear()
            assert mgr.steps() == [1]
            assert [n for n in os.listdir(str(tmp_path))
                    if n.startswith(".tmp-")]
            mgr.save(2)          # retry commits and sweeps the debris
            assert mgr.steps() == [1, 2]
            assert not [n for n in os.listdir(str(tmp_path))
                        if n.startswith(".tmp-")]
            verify_checkpoint(mgr.path(2))


class TestElasticRestore:
    def _restore_onto(self, tmp_path, dp_degree, expect_step=3):
        main2, startup2, loss2 = _build()
        mesh = _dp_mesh(dp_degree)
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe = fluid.Executor()
            exe.run(startup2)
            mgr = CheckpointManager(str(tmp_path), executor=exe,
                                    main_program=main2, scope=s2)
            got = mgr.restore_last_good(mesh=mesh)
            if got is None:
                got = mgr.restore_latest(mesh=mesh)
            assert got == expect_step
        return main2, loss2, s2, mesh

    @pytest.mark.parametrize("new_dp", [2, 8])
    def test_dp4_checkpoint_restores_on_other_degree(self, tmp_path,
                                                     new_dp):
        mgr, pexe, scope, loss, ref = _train_and_save(tmp_path)
        with fluid.scope_guard(scope):
            (lv_ref,) = pexe.run(feed=_feed(seed=5), fetch_list=[loss])
        main2, loss2, s2, mesh = self._restore_onto(tmp_path, new_dp)
        with fluid.scope_guard(s2):
            for n, want in ref.items():
                np.testing.assert_array_equal(
                    np.asarray(s2.find_var(n)), want)
            # re-sliced state lives sharded on the NEW degree
            mname = next(iter(pexe.zero_plan.placements))
            arr = s2.find_var(mname)
            assert tuple(arr.sharding.mesh.devices.shape) == (new_dp,)
            assert arr.addressable_shards[0].data.shape[0] * new_dp == \
                arr.shape[0]
            # and the next training step matches the saved-mesh run
            pexe2 = ParallelExecutor(loss_name=loss2.name,
                                     main_program=main2, mesh=mesh,
                                     zero=True)
            (lv,) = pexe2.run(feed=_feed(seed=5), fetch_list=[loss2])
        np.testing.assert_allclose(
            float(np.asarray(lv).reshape(())),
            float(np.asarray(lv_ref).reshape(())), rtol=1e-5)

    def test_unprovable_plan_raises_before_touching_scope(self,
                                                          tmp_path):
        _train_and_save(tmp_path)
        main2, startup2, _ = _build()
        s3 = fluid.Scope()
        with fluid.scope_guard(s3):
            exe = fluid.Executor()
            mgr = CheckpointManager(str(tmp_path), executor=exe,
                                    main_program=main2, scope=s3)
            before = {n: id(v) for n, v in s3.items()}
            with pytest.raises(ReshardError) as ei:
                mgr.restore_latest(mesh=_dp_mesh(3))
            assert ei.value.retryable
            assert {n: id(v) for n, v in s3.items()} == before
            # the valid checkpoint was NOT quarantined by the failure
            assert mgr.steps() == [3] and not mgr.quarantined()
            # a provable mesh immediately succeeds on retry
            assert mgr.restore_latest(mesh=_dp_mesh(2)) == 3

    def test_reshard_failpoint_is_clean_and_retryable(self, tmp_path):
        """ckpt.reshard fires at the head of restore replanning: an
        armed error must surface BEFORE any scope mutation, and a
        retry with the failpoint cleared succeeds."""
        _train_and_save(tmp_path)
        main2, startup2, _ = _build()
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe = fluid.Executor()
            mgr = CheckpointManager(str(tmp_path), executor=exe,
                                    main_program=main2, scope=s2)
            chaos.inject("ckpt.reshard")
            before = {n: id(v) for n, v in s2.items()}
            with pytest.raises(FaultInjected):
                mgr.restore_latest(mesh=_dp_mesh(2))
            assert {n: id(v) for n, v in s2.items()} == before
            chaos.clear()
            assert mgr.restore_latest(mesh=_dp_mesh(2)) == 3

    def test_restore_plan_verified_statically(self, tmp_path):
        """plan_restore rejects an impossible mapping without reading a
        single shard (the static-proof contract)."""
        mgr, _, _, _, _ = _train_and_save(tmp_path)
        topo = shard_ckpt.read_manifest(mgr.path(3))["topology"]
        with pytest.raises(ReshardError) as ei:
            shard_ckpt.plan_restore(topo, _dp_mesh(3))
        assert "not divisible" in str(ei.value)
        # a good mesh yields a full plan keyed by every saved var
        plan = shard_ckpt.plan_restore(topo, _dp_mesh(2))
        assert set(plan) == set(topo["shards"])


class TestDatapipeElasticResume:
    def test_dp4_save_dp2_restore_exactly_once(self):
        """The satellite regression: a stride-sharded source saved at
        dp4 repositions onto dp2 with no gaps and no replays."""
        data = list(range(40))
        states = []
        consumed = []
        for i in range(4):
            src = dp.InMemorySource(data, num_shards=4, shard_index=i)
            it = iter(src)
            consumed.extend(next(it) for _ in range(5))
            it.close()
            states.append(src.state_dict())
        assert sorted(consumed) == list(range(20))
        remainder = []
        for i in range(2):
            src = dp.InMemorySource(data, num_shards=2, shard_index=i)
            src.load_state_dict(states[0])   # rank-0 sidecar fallback
            remainder.extend(iter(src))
        assert sorted(remainder) == list(range(20, 40))

    def test_grow_dp2_to_dp4(self):
        data = list(range(48))
        src = dp.InMemorySource(data, num_shards=2, shard_index=0)
        it = iter(src)
        for _ in range(6):
            next(it)
        it.close()
        state = src.state_dict()
        got = []
        for i in range(4):
            s = dp.InMemorySource(data, num_shards=4, shard_index=i)
            s.load_state_dict(state)
            got.extend(iter(s))
        assert sorted(got) == list(range(12, 48))

    def test_misaligned_reposition_fails_loudly(self):
        src = dp.InMemorySource(list(range(40)), num_shards=4)
        it = iter(src)
        for _ in range(5):
            next(it)
        it.close()
        state = src.state_dict()
        bad = dp.InMemorySource(list(range(40)), num_shards=3)
        with pytest.raises(PipelineStateError, match="reposition"):
            bad.load_state_dict(state)

    def test_same_degree_reload_is_exact(self):
        """No topology change: the remap must be a no-op (regression
        guard for the state-schema change)."""
        src = dp.InMemorySource(list(range(10)), num_shards=2,
                                shard_index=1)
        it = iter(src)
        next(it), next(it)
        it.close()
        clone = dp.InMemorySource(list(range(10)), num_shards=2,
                                  shard_index=1)
        clone.load_state_dict(src.state_dict())
        assert list(iter(clone)) == [5, 7, 9]


class TestCkptCLI:
    def test_inspect_and_verify(self, tmp_path, capsys):
        from paddle_tpu.cli import main as cli_main
        mgr, _, _, _, _ = _train_and_save(tmp_path)
        mgr.mark_good(3)
        assert cli_main(["ckpt", "inspect", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "ckpt-3" in out and "[sharded]" in out
        assert "mesh=[4]['data']" in out
        assert "last_good: 3" in out
        assert cli_main(["ckpt", "verify", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "verified" in out and "PASS" in out

    def test_verify_exit_codes_on_corruption(self, tmp_path, capsys):
        from conftest import corrupt_largest_file
        from paddle_tpu.cli import main as cli_main
        mgr, _, _, _, _ = _train_and_save(tmp_path)
        corrupt_largest_file(mgr.path(3))
        assert cli_main(["ckpt", "verify", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "CORRUPT" in out
        # inspect (shallow) still surveys; size mismatch caught too
        assert cli_main(["ckpt", "inspect", str(tmp_path)]) == 1

    def test_missing_dir_is_usage_error(self, tmp_path):
        from paddle_tpu.cli import main as cli_main
        assert cli_main(["ckpt", "verify",
                         str(tmp_path / "nope")]) == 2

    def test_json_report(self, tmp_path, capsys):
        from paddle_tpu.cli import main as cli_main
        mgr, _, _, _, _ = _train_and_save(tmp_path)
        assert cli_main(["ckpt", "inspect", str(tmp_path),
                         "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["steps"][0]["topology"]["mesh_shape"] == [4]
        assert report["steps"][0]["shards"]["sharded_vars"] > 0


# ---------------------------------------------------------------------------
# the acceptance drill: kill a dp4 run mid-step, resume on dp2
# ---------------------------------------------------------------------------

ELASTIC_TRAINER = r'''
"""ZeRO dp trainer for the kill-shrink-resume drill: shard-format
checkpoints (async commit) every step, promoted to known-good, resumed
via restore_last_good onto THIS run's mesh — which may be a different
size than the mesh that saved."""
import argparse
import json
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
from paddle_tpu import layers
from paddle_tpu.fault import CheckpointManager, chaos
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import make_mesh

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", required=True)
ap.add_argument("--dp", type=int, required=True)
ap.add_argument("--out", required=True)
args = ap.parse_args()

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[8], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = layers.fc(x, 16, act="relu", param_attr="w1", bias_attr="b1")
    pred = layers.fc(h, 1, param_attr="w2", bias_attr="b2")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

rng = np.random.RandomState(7)
w_true = np.arange(1.0, 9.0, dtype="float32").reshape(8, 1) * 0.2
xs = rng.rand(160, 6 + 2).astype("float32")
samples = [{"x": xs[i], "y": (xs[i:i + 1] @ w_true)[0].astype("float32")}
           for i in range(160)]
pipe = dp.InMemorySource(samples).batch(16, drop_last=True)

mesh = make_mesh((args.dp,), ("data",), devices=jax.devices()[:args.dp])
exe = fluid.Executor()
exe.run(startup)
pexe = ParallelExecutor(loss_name=loss.name, main_program=main,
                        mesh=mesh, zero=True)
assert pexe.zero_plan.placements        # the plan really shards state
mgr = CheckpointManager(args.ckpt, keep=5, executor=pexe,
                        main_program=main, datapipe=pipe, mesh=mesh,
                        shard_specs=pexe.zero_plan.checkpoint_specs())
resumed = mgr.restore_last_good()       # mesh defaults to THIS mesh
step = resumed or 0

losses = []
for batch in pipe:                       # resumes mid-stream
    step += 1
    chaos.fire("train.step", step=step)
    (lv,) = pexe.run(feed=batch, fetch_list=[loss.name])
    losses.append(float(np.asarray(lv).reshape(-1)[0]))
    mgr.save_async(step)                 # commit off the step path
    mgr.mark_good(step)                  # drains the pending commit

with open(args.out, "w") as f:
    json.dump({"final_loss": losses[-1], "resumed_from": resumed,
               "steps": len(losses), "dp": args.dp}, f)
'''


@pytest.mark.chaos
@pytest.mark.slow   # subprocess boots; the in-process shard/reshard
                    # failpoint tests above are the tier-1 smoke subset
class TestKillShrinkResume:
    def _runner(self, tmp_path):
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_CHAOS", None)
        trainer = tmp_path / "trainer.py"
        trainer.write_text(ELASTIC_TRAINER)

        def run(ckpt, out, dp_degree, chaos_spec=None, expect_rc=0):
            e = dict(env)
            if chaos_spec:
                e["PADDLE_TPU_CHAOS"] = chaos_spec
            r = subprocess.run(
                [sys.executable, str(trainer), "--ckpt", str(ckpt),
                 "--dp", str(dp_degree), "--out", str(out)],
                cwd=repo_root, env=e, capture_output=True, text=True,
                timeout=600)
            assert r.returncode == expect_rc, \
                (r.returncode, r.stderr[-2000:])
            return r

        return run

    def test_dp4_killed_resumes_on_dp2_to_same_loss(self, tmp_path):
        """THE acceptance drill: hard-kill a dp4 ZeRO run mid-step,
        restart on a dp2 mesh from the last-good shard checkpoint
        (restore plan statically verified), converge to the final loss
        of an uninterrupted run."""
        run = self._runner(tmp_path)
        # uninterrupted dp4 reference: 160 samples / batch 16 = 10 steps
        ref_out = tmp_path / "ref.json"
        run(tmp_path / "ref_ckpt", ref_out, 4)
        ref = json.loads(ref_out.read_text())
        assert ref["resumed_from"] is None and ref["steps"] == 10

        # chaos run on dp4: hard-killed at step 6 (steps 1-5 committed)
        ckpt = tmp_path / "ckpt"
        out = tmp_path / "got.json"
        run(ckpt, out, 4, chaos_spec="train.step=kill@5",
            expect_rc=chaos.KILL_EXIT_CODE)
        assert not out.exists()          # it really died mid-stream

        # the surviving checkpoints are shard-format and verifiable
        from paddle_tpu.cli import main as cli_main
        assert cli_main(["ckpt", "verify", str(ckpt)]) == 0

        # resume on HALF the mesh: dp2
        run(ckpt, out, 2)
        got = json.loads(out.read_text())
        assert got["resumed_from"] == 5
        assert got["steps"] == 5         # batches 6..10 exactly once
        np.testing.assert_allclose(got["final_loss"],
                                   ref["final_loss"], rtol=1e-4)

    def test_kill_mid_shard_write_leaves_previous_restorable(
            self, tmp_path):
        """ckpt.shard.write=kill mid-save: the commit never lands, the
        prior checkpoint stays restorable, and a shrink-resume from it
        still reaches the reference loss."""
        run = self._runner(tmp_path)
        ref_out = tmp_path / "ref.json"
        run(tmp_path / "ref_ckpt", ref_out, 4)
        ref = json.loads(ref_out.read_text())

        ckpt = tmp_path / "ckpt"
        out = tmp_path / "got.json"
        # let ~3 full saves land, then die inside a later shard write
        run(ckpt, out, 4, chaos_spec="ckpt.shard.write=kill@40",
            expect_rc=chaos.KILL_EXIT_CODE)
        assert not out.exists()

        from paddle_tpu.cli import main as cli_main
        assert cli_main(["ckpt", "verify", str(ckpt)]) == 0
        steps = sorted(int(n[len("ckpt-"):])
                       for n in os.listdir(ckpt)
                       if n.startswith("ckpt-")
                       and n[len("ckpt-"):].isdigit())
        assert steps                     # prior commits survived whole

        run(ckpt, out, 2)
        got = json.loads(out.read_text())
        assert got["resumed_from"] == steps[-1]
        np.testing.assert_allclose(got["final_loss"],
                                   ref["final_loss"], rtol=1e-4)
