"""Book test: CIFAR-10 image classification with resnet_cifar10 and a
small VGG (reference
``python/paddle/fluid/tests/book/test_image_classification.py``)."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.models.resnet import resnet_cifar10


def _vgg_tiny(input):
    conv = fluid.nets.img_conv_group(
        input=input, pool_size=2, pool_stride=2, conv_num_filter=[16, 16],
        conv_filter_size=3, conv_act="relu", conv_with_batchnorm=True,
        conv_batchnorm_drop_rate=[0.0, 0.0], pool_type="max")
    fc1 = layers.fc(input=conv, size=64, act=None)
    bn = layers.batch_norm(input=fc1, act="relu")
    return layers.fc(input=bn, size=64, act=None)


@pytest.mark.parametrize("net", ["resnet", "vgg"])
def test_image_classification(net):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        images = layers.data(name="pixel", shape=[3, 32, 32],
                             dtype="float32")
        label = layers.data(name="label", shape=[1], dtype="int64")
        if net == "resnet":
            predict = resnet_cifar10(images, 10, depth=8)
        else:
            body = _vgg_tiny(images)
            predict = layers.fc(input=body, size=10, act="softmax")
        cost = layers.cross_entropy(input=predict, label=label)
        avg_cost = layers.mean(cost)
        acc = layers.accuracy(input=predict, label=label)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    reader = fluid.dataset.cifar.train10()
    batch, accs, steps = [], [], 0
    for sample in reader():
        batch.append(sample)
        if len(batch) < 32:
            continue
        imgs = np.stack([b[0].reshape(3, 32, 32) for b in batch]) \
            .astype("float32")
        labels = np.asarray([[b[1]] for b in batch], dtype="int64")
        batch = []
        _, a = exe.run(main, feed={"pixel": imgs, "label": labels},
                       fetch_list=[avg_cost, acc])
        accs.append(float(np.asarray(a).reshape(())))
        steps += 1
        if steps >= 40:
            break
    assert np.mean(accs[-8:]) > 0.5, np.mean(accs[-8:])
