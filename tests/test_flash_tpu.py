"""Compiled Pallas flash-attention on real TPU hardware (VERDICT r2
item 4: the CPU suite only exercises interpret mode).  Skipped unless a
TPU backend is reachable — run manually on the bench chip with
``PADDLE_TPU_TEST_TPU=1 python -m pytest tests/test_flash_tpu.py``
(conftest pins the suite to the CPU platform otherwise)."""

import os
import subprocess
import sys

import numpy as np
import pytest

_DRIVER = r"""
import numpy as np, jax, jax.numpy as jnp
from paddle_tpu.ops.attention_ops import (fused_attention,
                                          _reference_attention, _HAS_PALLAS)
assert any(d.platform != "cpu" for d in jax.devices()), "no TPU"
assert _HAS_PALLAS
B, H, S, D = 4, 8, 1024, 64
key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)
k = jax.random.normal(jax.random.PRNGKey(1), q.shape, jnp.bfloat16)
v = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.bfloat16)
mask = jnp.ones((B, S), jnp.bfloat16)
scale = D ** -0.5

def loss(use_pallas, q, k, v):
    out = fused_attention(q, k, v, mask, True, scale, use_pallas)
    return jnp.sum(out.astype(jnp.float32) ** 2)

flash = jax.jit(lambda q, k, v: loss(True, q, k, v))
ref = jax.jit(lambda q, k, v: loss(False, q, k, v))
np.testing.assert_allclose(float(flash(q, k, v)), float(ref(q, k, v)),
                           rtol=2e-2)
gf = jax.jit(jax.grad(lambda q, k, v: loss(True, q, k, v),
                      argnums=(0, 1, 2)))(q, k, v)
gr = jax.jit(jax.grad(lambda q, k, v: loss(False, q, k, v),
                      argnums=(0, 1, 2)))(q, k, v)
for a, b in zip(gf, gr):
    a = np.asarray(a, np.float32); b = np.asarray(b, np.float32)
    # bf16 accumulation-order noise: a handful of elements can differ by
    # ~1 ulp of the grad scale; bound the tail instead of elementwise
    scale_g = np.abs(b).max()
    np.testing.assert_allclose(a, b, rtol=1e-1, atol=0.1 * scale_g)
    frac_off = np.mean(np.abs(a - b) > 0.02 * scale_g)
    assert frac_off < 1e-3, frac_off
print("FLASH_TPU_OK")
"""


@pytest.mark.skipif(not os.environ.get("PADDLE_TPU_TEST_TPU"),
                    reason="TPU-only: set PADDLE_TPU_TEST_TPU=1 on a "
                           "machine with a TPU backend")
def test_compiled_flash_matches_xla_on_tpu():
    # subprocess: the suite's conftest pinned THIS process to the CPU
    # platform before jax initialized; the child gets the real backend
    env = dict(os.environ)
    # conftest pinned the suite to cpu; "" lets the child auto-select the
    # real backend (axon/tpu) again
    env["JAX_PLATFORMS"] = ""
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", _DRIVER],
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))),
                          env=env, capture_output=True, text=True,
                          timeout=560)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "FLASH_TPU_OK" in proc.stdout
