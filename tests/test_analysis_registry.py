"""Registry cross-check for analyzer diagnostic codes (same idiom as
the failpoint/metric registries): every ``PTA***`` code must be (1)
documented in docs/static_analysis.md's diagnostic table, and (2)
covered by a negative test in tests/test_analysis.py that triggers it
on a deliberately broken program.  The scanner also walks the analysis
sources so a pass emitting an undeclared code (or a declared code no
pass can emit) fails here, not in an incident."""

import os
import re

import paddle_tpu
from paddle_tpu.analysis.diagnostics import DIAGNOSTIC_CODES

from tests.test_analysis import NEGATIVE_CASES as SINGLE_PROGRAM_CASES
from tests.test_analysis_distributed import \
    NEGATIVE_CASES as CROSS_PROGRAM_CASES

# single-program codes live in tests/test_analysis.py, cross-program
# (distributed verifier) codes in tests/test_analysis_distributed.py;
# together they must cover the declared table exactly
NEGATIVE_CASES = {**SINGLE_PROGRAM_CASES, **CROSS_PROGRAM_CASES}

SRC_ROOT = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
ANALYSIS_DIR = os.path.join(SRC_ROOT, "analysis")
DOC = os.path.join(os.path.dirname(SRC_ROOT), "docs", "static_analysis.md")

_CODE = re.compile(r"\bPTA\d{3}\b")


def _emitted_codes():
    """Codes that appear in the analysis passes' sources (excluding the
    declaration table itself)."""
    codes = set()
    for name in sorted(os.listdir(ANALYSIS_DIR)):
        if not name.endswith(".py") or name == "diagnostics.py":
            continue
        with open(os.path.join(ANALYSIS_DIR, name)) as f:
            codes.update(_CODE.findall(f.read()))
    return codes


def documented_codes():
    with open(DOC) as f:
        doc = f.read()
    # table rows are "| `PTA001` | severity | ... |"
    return set(re.findall(r"^\|\s*`(PTA\d{3})`\s*\|", doc, flags=re.M))


class TestDiagnosticRegistry:
    def test_scanner_finds_known_emit_sites(self):
        """An over-tight scanner regex silently passing the doc check
        would be worse than a missing doc row."""
        emitted = _emitted_codes()
        assert {"PTA001", "PTA005", "PTA007", "PTA010"} <= emitted

    def test_every_emitted_code_is_declared(self):
        undeclared = sorted(_emitted_codes() - set(DIAGNOSTIC_CODES))
        assert not undeclared, (
            f"analysis passes emit codes missing from "
            f"DIAGNOSTIC_CODES: {undeclared}")

    def test_every_declared_code_is_emitted_somewhere(self):
        dead = sorted(set(DIAGNOSTIC_CODES) - _emitted_codes())
        assert not dead, (
            f"DIAGNOSTIC_CODES declares codes no pass can emit "
            f"(codes are append-only — a retired check should keep a "
            f"tombstone row in the docs, not a dead registry entry): "
            f"{dead}")

    def test_every_code_is_documented(self):
        documented = documented_codes()
        assert documented, f"no diagnostic table parsed from {DOC}"
        missing = sorted(set(DIAGNOSTIC_CODES) - documented)
        assert not missing, (
            f"diagnostic codes missing from the docs/static_analysis.md "
            f"table: {missing}")
        stale = sorted(documented - set(DIAGNOSTIC_CODES))
        assert not stale, (
            f"docs/static_analysis.md documents unknown codes: {stale}")

    def test_every_code_has_a_negative_test(self):
        missing = sorted(set(DIAGNOSTIC_CODES) - set(NEGATIVE_CASES))
        assert not missing, (
            f"codes without a negative case in tests/test_analysis.py "
            f"or tests/test_analysis_distributed.py NEGATIVE_CASES "
            f"(each code needs a deliberately broken program/family "
            f"that triggers it): {missing}")
        stale = sorted(set(NEGATIVE_CASES) - set(DIAGNOSTIC_CODES))
        assert not stale, f"negative cases for unknown codes: {stale}"
        overlap = sorted(set(SINGLE_PROGRAM_CASES) &
                         set(CROSS_PROGRAM_CASES))
        assert not overlap, (
            f"codes registered in BOTH negative-case files (one owner "
            f"each): {overlap}")

    def test_doc_table_states_severity(self):
        with open(DOC) as f:
            doc = f.read()
        for code, (severity, _) in DIAGNOSTIC_CODES.items():
            row = re.search(rf"^\|\s*`{code}`\s*\|([^|]*)\|", doc,
                            flags=re.M)
            assert row and severity in row.group(1), (
                f"{code}'s doc row must state its severity "
                f"({severity!r})")
