"""Multi-PROCESS data-parallel training (VERDICT r3 item 7): two OS
processes form a jax.distributed cluster on the CPU backend (2 local
devices each -> a 4-device global mesh), run the REAL DP training step
through ParallelExecutor with cross-process gradient psum, and the loss
trajectory must equal a single-process run of the same program.

The reference exercises its multi-node path with forked pservers
(test_recv_op.py); the analog here is the multi-controller cluster that
replaces all four of its RPC stacks (SURVEY.md §5.8).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = '''
import argparse, os, sys

ap = argparse.ArgumentParser()
ap.add_argument("--rank", type=int, required=True)
ap.add_argument("--nproc", type=int, required=True)
ap.add_argument("--coordinator", required=True)
ap.add_argument("--out", required=True)
args = ap.parse_args()

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from paddle_tpu.parallel.distributed import init_parallel_env
init_parallel_env(coordinator_address=args.coordinator,
                  num_processes=args.nproc, process_id=args.rank)
assert jax.process_count() == args.nproc
assert len(jax.devices()) == 2 * args.nproc, len(jax.devices())

import numpy as np
import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.parallel_executor import ParallelExecutor

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 5
with fluid.program_guard(main, startup):
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(input=x, size=16, act="relu", param_attr="dp_w1")
    pred = layers.fc(input=h, size=1, param_attr="dp_w2")
    loss = layers.reduce_mean(layers.square(pred - y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

exe = fluid.Executor()
exe.run(startup)
mesh = make_mesh((2 * args.nproc,), ("data",))
pexe = ParallelExecutor(loss_name=loss.name, main_program=main, mesh=mesh)

rng = np.random.RandomState(7)
losses = []
for step in range(6):
    xv = rng.rand(16, 8).astype("f")
    yv = (xv.sum(axis=1, keepdims=True) * 0.3).astype("f")
    (lv,) = pexe.run(feed={"x": xv, "y": yv}, fetch_list=[loss.name])
    losses.append(float(np.asarray(lv).reshape(())))
w = np.asarray(fluid.global_scope().find_var("dp_w1"))
np.savez(args.out, losses=np.asarray(losses), w=w)
print("worker", args.rank, "done", losses[-1])
'''


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_dp_matches_single_process(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)

    procs = []
    outs = []
    try:
        for rank in range(2):
            out = tmp_path / f"rank{rank}.npz"
            outs.append(out)
            procs.append(subprocess.Popen(
                [sys.executable, str(worker), "--rank", str(rank),
                 "--nproc", "2", "--coordinator", coordinator,
                 "--out", str(out)],
                cwd=repo_root, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        for p in procs:
            stdout, stderr = p.communicate(timeout=600)
            assert p.returncode == 0, stderr[-2000:]
    finally:
        # a failed rank must not orphan its peer (it would sit on the
        # coordinator port waiting for distributed init)
        for q in procs:
            if q.poll() is None:
                q.kill()

    # single-process reference over the identical program + batches
    single = tmp_path / "single.npz"
    r = subprocess.run(
        [sys.executable, str(worker), "--rank", "0", "--nproc", "1",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         "--out", str(single)],
        cwd=repo_root, env=env, capture_output=True, text=True,
        timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]

    ref = np.load(single)
    for out in outs:
        got = np.load(out)
        np.testing.assert_allclose(got["losses"], ref["losses"],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got["w"], ref["w"], rtol=1e-5,
                                   atol=1e-6)
    assert ref["losses"][-1] < ref["losses"][0] * 0.5, ref["losses"]
