"""OpTest: per-op numeric test harness.

Port of the reference workhorse ``python/paddle/fluid/tests/unittests/
op_test.py:212``: build a single-op program from declared inputs/attrs, run
it through the real Executor (whole-block XLA lowering), compare outputs
against the test's numpy reference, and check analytic gradients (from IR
append_backward over the registered grad ops) against central-difference
numeric gradients (``get_numeric_gradient:97``).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import Program, program_guard, grad_var_name
from paddle_tpu.scope import Scope, scope_guard


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class OpTest:
    """Subclasses set: ``op_type``, ``inputs`` (slot -> ndarray or list of
    (name, ndarray)), ``outputs`` (slot -> expected ndarray or list),
    ``attrs`` (optional)."""

    op_type = None
    inputs = {}
    outputs = {}
    attrs = {}

    # -- helpers -----------------------------------------------------------
    def _input_items(self):
        for slot, val in self.inputs.items():
            if isinstance(val, list) and val and isinstance(val[0], tuple):
                for name, arr in val:
                    yield slot, name, arr
            else:
                yield slot, f"{slot}__var", val

    def _output_items(self):
        for slot, val in self.outputs.items():
            if isinstance(val, list) and val and isinstance(val[0], tuple):
                for name, arr in val:
                    yield slot, name, arr
            else:
                yield slot, f"{slot}__out", val

    def _build(self):
        program = Program()
        block = program.global_block()
        op_inputs = {}
        feed = {}
        for slot, name, arr in self._input_items():
            arr = np.asarray(arr)
            block.create_var(name=name, shape=arr.shape,
                             dtype=str(arr.dtype), is_data=True)
            op_inputs.setdefault(slot, []).append(name)
            feed[name] = arr
        op_outputs = {}
        for slot, name, _ in self._output_items():
            block.create_var(name=name)
            op_outputs.setdefault(slot, []).append(name)
        block.append_op(type=self.op_type, inputs=op_inputs,
                        outputs=op_outputs, attrs=dict(self.attrs))
        # testing a host op IS the point here — don't warn about the cliff
        from paddle_tpu.ops import registry as _registry
        opdef = _registry.lookup(self.op_type)
        if opdef is not None and opdef.host:
            program.expect_host_ops = True
        return program, feed

    # -- forward check -----------------------------------------------------
    # On-TPU tolerance handling (dual-place discipline; reference
    # op_test.py passes a larger atol for the CUDA place): TPU
    # transcendentals (exp/log) differ from the host libm at the ~4e-5
    # level.  The per-test DECLARED tolerance is scaled by this factor
    # but the scaling is CAPPED at the old global 1e-4 floor — so a
    # test declaring 1e-6 precision now fails at 1e-5 on TPU (a chip
    # regression beyond its own contract, which the old flat floor
    # silently passed; ADVICE r5), while a test that deliberately
    # declared a loose >= 1e-4 tolerance keeps exactly its declared
    # value instead of being loosened 10x further.
    TPU_TOL_SCALE = 10.0
    TPU_TOL_CAP = 1e-4

    def _tpu_tol(self, declared):
        return max(declared,
                   min(declared * self.TPU_TOL_SCALE, self.TPU_TOL_CAP))

    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=()):
        from paddle_tpu.place import is_tpu_available
        if is_tpu_available():
            atol = self._tpu_tol(atol)
            rtol = self._tpu_tol(rtol)
        program, feed = self._build()
        fetch_names = []
        expected = []
        for slot, name, arr in self._output_items():
            if arr is None or slot in no_check_set:
                continue
            fetch_names.append(name)
            expected.append(np.asarray(arr))
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            outs = exe.run(program, feed=feed, fetch_list=fetch_names)
        for name, got, want in zip(fetch_names, outs, expected):
            np.testing.assert_allclose(
                np.asarray(got, dtype=np.float64),
                np.asarray(want, dtype=np.float64),
                atol=atol, rtol=rtol,
                err_msg=f"{self.op_type} output {name} mismatch")

    # -- gradient check ----------------------------------------------------
    def check_grad(self, inputs_to_check, output_name, max_relative_error=
                   0.005, no_grad_set=None, numeric_grad_delta=0.005):
        program, feed = self._build()
        block = program.global_block()
        out_var = block.var(self._resolve_output(output_name))

        # scalarize: loss = mean(out)
        block.create_var(name="__loss__")
        block.append_op(type="mean", inputs={"X": [out_var.name]},
                        outputs={"Out": ["__loss__"]})
        loss = block.var("__loss__")
        loss.shape = (1,)
        loss.dtype = out_var.dtype

        with program_guard(program):
            fluid.append_backward(loss, no_grad_set=no_grad_set,
                                  parameter_list=[])

        check_names = [self._resolve_input(n) for n in
                       _as_list(inputs_to_check)]
        grad_names = [grad_var_name(n) for n in check_names]
        exe = fluid.Executor(fluid.CPUPlace())
        with scope_guard(Scope()):
            analytic = exe.run(program, feed=feed, fetch_list=grad_names)

        for name, g_analytic in zip(check_names, analytic):
            g_numeric = self._numeric_grad(name, output_name, feed,
                                           numeric_grad_delta)
            abs_a = np.abs(np.asarray(g_analytic, np.float64)).ravel()
            abs_n = np.abs(g_numeric).ravel()
            diff = np.abs(np.asarray(g_analytic, np.float64).ravel() -
                          g_numeric.ravel())
            denom = np.maximum(np.maximum(abs_a, abs_n), 1e-3)
            max_diff = (diff / denom).max() if diff.size else 0.0
            assert max_diff <= max_relative_error, (
                f"{self.op_type} grad wrt {name}: max relative error "
                f"{max_diff:.6f} > {max_relative_error}")

    def _resolve_input(self, name_or_slot):
        for slot, name, arr in self._input_items():
            if name_or_slot in (slot, name):
                return name
        raise KeyError(name_or_slot)

    def _resolve_output(self, name_or_slot):
        for slot, name, arr in self._output_items():
            if name_or_slot in (slot, name):
                return name
        raise KeyError(name_or_slot)

    def _numeric_grad(self, wrt_name, output_name, feed, delta):
        """Central differences of mean(out) wrt feed[wrt_name]
        (reference ``op_test.py get_numeric_gradient:97``)."""
        program, _ = self._build()
        block = program.global_block()
        out_name = self._resolve_output(output_name)
        block.create_var(name="__loss__")
        block.append_op(type="mean", inputs={"X": [out_name]},
                        outputs={"Out": ["__loss__"]})
        exe = fluid.Executor(fluid.CPUPlace())

        def loss_at(feed_dict):
            with scope_guard(Scope()):
                out, = exe.run(program, feed=feed_dict,
                               fetch_list=["__loss__"])
            return float(np.asarray(out).reshape(-1)[0])

        base = {k: np.array(v) for k, v in feed.items()}
        x = base[wrt_name].astype(np.float64)
        grad = np.zeros_like(x, dtype=np.float64)
        flat = x.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + delta
            base[wrt_name] = x.astype(base[wrt_name].dtype)
            hi = loss_at(base)
            flat[i] = orig - delta
            base[wrt_name] = x.astype(base[wrt_name].dtype)
            lo = loss_at(base)
            flat[i] = orig
            base[wrt_name] = x.astype(base[wrt_name].dtype)
            gflat[i] = (hi - lo) / (2.0 * delta)
        return grad
