"""trainer_config_helpers DSL + config schema tests (reference
trainer_config_helpers/tests + test config round-trips through
config_parser; here the schema is proto_config.TrainerConfig)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import proto_config
from paddle_tpu.trainer_config_helpers import (
    data_layer, fc_layer, classification_cost, settings, AdamOptimizer,
    SoftmaxActivation, ReluActivation)
from paddle_tpu.v2 import data_type as dt


def _mnist_config():
    settings(batch_size=16, learning_rate=0.01,
             learning_method=AdamOptimizer())
    img = data_layer(name="pixel", size=64)
    hidden = fc_layer(input=img, size=32, act=ReluActivation())
    pred = fc_layer(input=hidden, size=10, act=SoftmaxActivation())
    lbl = data_layer(name="label", size=10, type=dt.integer_value(10))
    cost = classification_cost(input=pred, label=lbl)
    return cost


class TestLegacyDSL:
    def test_builds_and_trains(self):
        rng = np.random.RandomState(0)
        cfg = proto_config.parse_config(_mnist_config)
        assert cfg.settings["learning_method"]["type"] == "adam"
        assert cfg.settings["batch_size"] == 16
        assert len(cfg.outputs) == 1

        main, startup, (cost,) = proto_config.build_programs(cfg)
        with fluid.program_guard(main, startup):
            fluid.optimizer.Adam(
                learning_rate=cfg.settings["learning_rate"]).minimize(cost)
        exe = fluid.Executor()
        exe.run(startup)
        xs = rng.rand(16, 64).astype("float32")
        ys = rng.randint(0, 10, (16, 1)).astype("int64")
        losses = []
        for _ in range(15):
            (lv,) = exe.run(main, feed={"pixel": xs, "label": ys},
                            fetch_list=[cost])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.8, losses


class TestConfigRoundTrip:
    def test_json_roundtrip(self, tmp_path):
        cfg = proto_config.parse_config(_mnist_config)
        p = str(tmp_path / "trainer.json")
        cfg.to_json(path=p, indent=1)
        cfg2 = proto_config.TrainerConfig.from_json(p)
        assert cfg2.settings == cfg.settings
        assert cfg2.outputs == cfg.outputs

        # reconstructed program computes the same forward
        rng = np.random.RandomState(1)
        xs = rng.rand(4, 64).astype("float32")
        ys = rng.randint(0, 10, (4, 1)).astype("int64")
        vals = []
        for c in (cfg, cfg2):
            main, startup, (cost,) = proto_config.build_programs(c)
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor()
                main.random_seed = startup.random_seed = 7
                exe.run(startup)
                (lv,) = exe.run(main, feed={"pixel": xs, "label": ys},
                                fetch_list=[cost])
            vals.append(float(np.asarray(lv).reshape(-1)[0]))
        np.testing.assert_allclose(vals[0], vals[1], rtol=1e-6)
