"""Fault-injection suite: failpoints in reader, RPC, and checkpoint
paths, retry/backoff semantics, stale-lease and heartbeat handling,
graceful shutdown, and the kill-and-resume training drill
(docs/fault_tolerance.md).  All chaos-marked tests run on the CPU
platform with bounded timeouts — tier-1-safe by construction."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.fault import (CheckpointManager, CorruptCheckpoint,
                              FaultInjected, GracefulShutdown, RetryError,
                              RetryPolicy, chaos)
from paddle_tpu.fault.checkpoint import MANIFEST_NAME, verify_checkpoint
from paddle_tpu.parallel.master import (MasterClient, MasterServer,
                                        MasterService, Task,
                                        partition_files)
from paddle_tpu.reader import decorator as rdr


@pytest.fixture(autouse=True)
def _clean_failpoints():
    chaos.clear()
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# chaos primitives
# ---------------------------------------------------------------------------

class TestFailpoints:
    def test_disarmed_is_noop(self):
        chaos.fire("nothing.armed")  # no raise

    def test_error_after_and_times(self):
        chaos.inject("fp", after=2, times=2)
        outcomes = []
        for _ in range(6):
            try:
                chaos.fire("fp")
                outcomes.append("ok")
            except FaultInjected:
                outcomes.append("boom")
        assert outcomes == ["ok", "ok", "boom", "boom", "ok", "ok"]

    def test_custom_exception_class_and_instance(self):
        chaos.inject("fp", error=ConnectionResetError)
        with pytest.raises(ConnectionResetError):
            chaos.fire("fp")
        chaos.inject("fp", error=ValueError("bad"))
        with pytest.raises(ValueError, match="bad"):
            chaos.fire("fp")

    def test_delay_only_does_not_raise(self):
        chaos.inject("fp", delay=0.01)
        t0 = time.monotonic()
        chaos.fire("fp")
        assert time.monotonic() - t0 >= 0.01

    def test_scoped_disarms(self):
        with chaos.scoped("fp"):
            assert chaos.armed("fp")
            with pytest.raises(FaultInjected):
                chaos.fire("fp")
        assert not chaos.armed("fp")

    def test_env_grammar(self):
        names = chaos.arm_from_env(
            "train.step=kill@4;master.rpc=error*2,reader.pump=delay:0.25")
        assert set(names) == {"train.step", "master.rpc", "reader.pump"}
        fired = chaos.failpoints()
        assert set(fired) >= set(names)
        with pytest.raises(ValueError):
            chaos.arm_from_env("x=explode")

    def test_env_grammar_modifiers_compose_in_either_order(self):
        for spec in ("fp=error*2@1", "fp=error@1*2"):
            chaos.clear()
            chaos.arm_from_env(spec)
            outcomes = []
            for _ in range(5):
                try:
                    chaos.fire("fp")
                    outcomes.append("ok")
                except FaultInjected:
                    outcomes.append("boom")
            assert outcomes == ["ok", "boom", "boom", "ok", "ok"], spec

    def test_kill_action_in_subprocess(self, tmp_path):
        code = ("from paddle_tpu.fault import chaos\n"
                "chaos.fire('die.here')\n"
                "print('survived')\n")
        env = dict(os.environ)
        env["PADDLE_TPU_CHAOS"] = "die.here=kill"
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == chaos.KILL_EXIT_CODE
        assert "survived" not in r.stdout


class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        p = RetryPolicy(max_attempts=4, base_delay=0.001, jitter=0)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")
            return "ok"

        assert p.call(flaky) == "ok"
        assert len(calls) == 3

    def test_exhausted_raises_retry_error_with_cause(self):
        p = RetryPolicy(max_attempts=2, base_delay=0.001, jitter=0)

        def always():
            raise TimeoutError("down")

        with pytest.raises(RetryError) as ei:
            p.call(always)
        assert isinstance(ei.value.last, TimeoutError)

    def test_non_retryable_propagates_immediately(self):
        p = RetryPolicy(max_attempts=5, base_delay=0.001)
        calls = []

        def bad():
            calls.append(1)
            raise KeyError("logic bug")

        with pytest.raises(KeyError):
            p.call(bad)
        assert len(calls) == 1

    def test_deadline(self):
        p = RetryPolicy(max_attempts=100, base_delay=0.2, jitter=0,
                        deadline=0.1)
        with pytest.raises(RetryError, match="deadline"):
            p.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))

    def test_backoff_growth_capped(self):
        p = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.3,
                        jitter=0)
        assert [p.backoff(n) for n in (1, 2, 3, 4)] == \
            [0.1, 0.2, 0.3, 0.3]

    def test_full_jitter_bounds(self):
        """jitter="full" (AWS full jitter): every delay lands in
        [0, cap] and actually varies — the decorrelation that spreads a
        thundering herd."""
        p = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=0.4,
                        jitter="full")
        for attempt, cap in ((1, 0.1), (2, 0.2), (3, 0.4), (4, 0.4)):
            delays = [p.backoff(attempt) for _ in range(200)]
            assert all(0.0 <= d <= cap for d in delays)
            assert max(delays) - min(delays) > cap * 0.1  # not constant

    def test_bad_jitter_rejected(self):
        with pytest.raises(ValueError):
            RetryPolicy(jitter=2.0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=-0.1)
        with pytest.raises(ValueError):
            RetryPolicy(jitter="bogus")
        # None = jitter off, the pre-existing falsy convention
        assert RetryPolicy(jitter=None).backoff(1) == \
            RetryPolicy(jitter=0).backoff(1)
        # numeric strings coerce at construction, not crash in backoff
        assert 0.0 <= RetryPolicy(jitter="0.5").backoff(1) <= 0.1

    def test_deadline_raises_immediately_not_after_sleeping(self):
        """When the remaining budget is smaller than the next backoff,
        the policy must raise NOW — not sleep through (or past) the
        deadline first."""
        p = RetryPolicy(max_attempts=100, base_delay=5.0, jitter=0,
                        deadline=0.05)
        t0 = time.monotonic()
        with pytest.raises(RetryError, match="deadline"):
            p.call(lambda: (_ for _ in ()).throw(ConnectionError("x")))
        assert time.monotonic() - t0 < 1.0  # never slept the 5s backoff

    def test_per_call_deadline_overrides_policy(self):
        p = RetryPolicy(max_attempts=100, base_delay=0.2, jitter=0,
                        deadline=None)   # policy itself would retry long
        calls = []

        def always():
            calls.append(1)
            raise ConnectionError("down")

        t0 = time.monotonic()
        with pytest.raises(RetryError, match="deadline"):
            p.call(always, deadline=0.05)
        assert time.monotonic() - t0 < 1.0
        assert len(calls) >= 1
        # and a generous per-call deadline still allows retries
        calls.clear()
        with pytest.raises(RetryError):
            RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0) \
                .call(always, deadline=30.0)
        assert len(calls) == 3


# ---------------------------------------------------------------------------
# reader resilience: worker/producer exceptions reach the consumer
# ---------------------------------------------------------------------------

class TestReaderFaults:
    def _ints(self, n=20):
        def reader():
            yield from range(n)
        return reader

    def test_buffered_producer_fault_propagates(self):
        chaos.inject("reader.pump", after=5)
        out = []
        with pytest.raises(FaultInjected):
            for x in rdr.buffered(self._ints(), size=4)():
                out.append(x)
        assert out == [0, 1, 2, 3, 4]  # partial progress, then the fault

    def test_xmap_worker_fault_propagates(self):
        chaos.inject("reader.worker", after=3)
        with pytest.raises(FaultInjected):
            list(rdr.xmap_readers(lambda x: x + 1, self._ints(),
                                  process_num=2, buffer_size=4)())

    def test_xmap_mapper_exception_propagates(self):
        def mapper(x):
            if x == 7:
                raise ValueError("bad sample")
            return x

        with pytest.raises(ValueError, match="bad sample"):
            list(rdr.xmap_readers(mapper, self._ints(),
                                  process_num=2, buffer_size=4,
                                  order=True)())

    def test_clean_stream_unaffected(self):
        got = sorted(rdr.xmap_readers(lambda x: x * 2, self._ints(10),
                                      process_num=3, buffer_size=4)())
        assert got == [2 * i for i in range(10)]


# ---------------------------------------------------------------------------
# master: stale leases, heartbeats, RPC retry
# ---------------------------------------------------------------------------

class TestStaleLeases:
    def test_stale_task_failed_ignored(self):
        m = MasterService(partition_files(["a"]), timeout=60)
        t_old = m.get_task()
        e_old = t_old.epoch  # in-process service aliases Task objects:
        old_id = t_old.id    # snapshot what the dead holder knew
        # evict the holder: force its lease to expire, then re-lease
        m.pending[old_id] = (m.pending[old_id][0], 0.0)
        t_new = m.get_task()
        assert t_new is not None and t_new.epoch != e_old
        e_new = t_new.epoch
        # dead holder reports failure with its stale epoch: must be
        # IGNORED — the new lease stays pending, no duplicate in todo
        assert m.task_failed(old_id, epoch=e_old) is False
        st = m.stats()
        assert st["pending"] == 1 and st["todo"] == 0
        assert m.task_finished(t_new.id, e_new) is True

    def test_requeue_bumps_epoch_rejecting_late_finish(self):
        m = MasterService([Task(0, ["a"])], timeout=0.01, failure_max=10)
        t = m.get_task()
        e_leased = t.epoch   # epoch the (about to be evicted) holder saw
        time.sleep(0.03)
        m.all_done()  # triggers _requeue_timeouts; task back in todo
        st = m.stats()
        assert st["todo"] == 1 and st["pending"] == 0
        # late finish from the evicted holder: rejected (not in pending)
        assert m.task_finished(0, epoch=e_leased) is False
        # even the requeued task's epoch moved past the evicted lease
        assert m.todo[0].epoch > e_leased

    def test_heartbeat_reclaims_dead_trainer_leases(self):
        m = MasterService(partition_files(["a", "b"]), timeout=60,
                          heartbeat_timeout=0.05)
        ta = m.get_task(trainer_id="A")
        assert ta is not None
        e_a = ta.epoch
        m.heartbeat("A")     # A opts into heartbeat eviction...
        time.sleep(0.1)      # ...then goes silent past the window
        m.heartbeat("B")
        # A's lease was reclaimed well before the 60s lease timeout
        st = m.stats()
        assert st["pending"] == 0 and st["todo"] == 2
        tb = m.get_task(trainer_id="B")
        assert tb is not None
        # A's late report is rejected by the epoch bump
        assert m.task_finished(ta.id, epoch=e_a) is False

    def test_no_heartbeat_opt_in_means_no_heartbeat_eviction(self):
        """A trainer that only leases (never heartbeats) must not be
        declared dead for working longer than the heartbeat window —
        its lease is governed by the lease timeout alone."""
        m = MasterService(partition_files(["a"]), timeout=60,
                          heartbeat_timeout=0.05)
        t = m.get_task(trainer_id="slowpoke")
        time.sleep(0.1)      # longer than heartbeat_timeout
        m.heartbeat("other")
        assert m.stats()["pending"] == 1          # lease intact
        assert m.task_finished(t.id, t.epoch) is True


class TestMasterRPCRetry:
    def _serve(self, tasks):
        svc = MasterService(tasks, timeout=60)
        server = MasterServer(svc, port=0)
        server.start_background()
        return svc, server, f"{server.addr[0]}:{server.addr[1]}"

    def test_injected_rpc_faults_are_retried(self):
        svc, server, addr = self._serve(partition_files(["a", "b"]))
        try:
            client = MasterClient(
                addr, retry=RetryPolicy(max_attempts=5, base_delay=0.001,
                                        jitter=0,
                                        retryable=(ConnectionError,
                                                   TimeoutError, OSError,
                                                   FaultInjected)))
            chaos.inject("master.rpc", times=2)  # two transient faults
            t = client.get_task()
            assert t is not None
            assert client.task_finished(t.id, t.epoch) is True
            client.close()
        finally:
            server.shutdown()

    def test_client_survives_master_restart(self):
        svc, server, addr = self._serve(partition_files(["a", "b"]))
        host, port = server.addr
        client = MasterClient(
            addr, retry=RetryPolicy(max_attempts=8, base_delay=0.05,
                                    jitter=0))
        t1 = client.get_task()
        assert t1 is not None
        # master dies and comes back on the same port (state survives:
        # same in-process service, fresh server)
        server.shutdown()
        server2 = MasterServer(svc, host=host, port=port)
        server2.start_background()
        try:
            # the client's socket is dead; _call must reconnect + retry
            assert client.task_finished(t1.id, t1.epoch) is True
            t2 = client.get_task()
            assert t2 is not None and t2.id != t1.id
            client.close()
        finally:
            server2.shutdown()

    def test_exhausted_retries_surface(self):
        svc, server, addr = self._serve(partition_files(["a"]))
        server.shutdown()  # nobody listening anymore
        # construction is lazy (restart-safe); the RPC itself exhausts
        # its retries and surfaces a RetryError
        client = MasterClient(
            addr, retry=RetryPolicy(max_attempts=2, base_delay=0.001,
                                    jitter=0))
        with pytest.raises(RetryError):
            client.get_task()

    def test_construction_while_master_down(self):
        """The client dials lazily: constructing it while the master is
        briefly down (trainer resume during a master restart) works."""
        svc = MasterService(partition_files(["a"]), timeout=60)
        server = MasterServer(svc, port=0)
        host, port = server.addr
        server.start_background()
        server.shutdown()            # master not up yet
        client = MasterClient(
            (host, port), retry=RetryPolicy(max_attempts=10,
                                            base_delay=0.05, jitter=0))
        server2 = MasterServer(svc, host=host, port=port)
        server2.start_background()
        try:
            t = client.get_task()
            assert t is not None
            client.close()
        finally:
            server2.shutdown()

    def test_background_heartbeats_keep_lease_alive(self):
        svc = MasterService(partition_files(["a"]), timeout=60,
                            heartbeat_timeout=0.2)
        server = MasterServer(svc, port=0)
        server.start_background()
        try:
            client = MasterClient(
                f"{server.addr[0]}:{server.addr[1]}", trainer_id="hb")
            client.start_heartbeats(interval=0.05)
            t = client.get_task()
            time.sleep(0.5)          # >> heartbeat window
            assert svc.stats()["pending"] == 1   # lease kept alive
            assert client.task_finished(t.id, t.epoch) is True
            client.close()
        finally:
            server.shutdown()

    def test_trainer_id_flows_through_rpc(self):
        svc, server, addr = self._serve(partition_files(["a"]))
        try:
            client = MasterClient(addr, trainer_id="t-0")
            assert client.heartbeat() is True
            t = client.get_task()
            assert t is not None
            assert svc.stats()["trainers"] == 1
            client.close()
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# checkpoint: torn writes, corruption quarantine, keep-N
# ---------------------------------------------------------------------------

def _tiny_model():
    x = layers.data(name="x", shape=[4, 8], append_batch_size=False)
    y = layers.data(name="y", shape=[4, 1], append_batch_size=False)
    pred = layers.fc(input=x, size=1, param_attr="ft_w")
    loss = layers.mean(layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _tiny_feed(step):
    rng = np.random.RandomState(step)
    xs = rng.rand(4, 8).astype("float32")
    return {"x": xs, "y": xs.sum(1, keepdims=True).astype("float32") * 0.1}


class TestCheckpointManager:
    def _train_and_save(self, tmp_path, steps, keep=10):
        loss = _tiny_model()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        mgr = CheckpointManager(str(tmp_path), keep=keep, executor=exe)
        for s in range(1, steps + 1):
            exe.run(fluid.default_main_program(), feed=_tiny_feed(s),
                    fetch_list=[loss])
            mgr.save(s)
        return mgr, exe, loss

    def test_manifest_written_and_verifies(self, tmp_path):
        mgr, _, _ = self._train_and_save(tmp_path, steps=1)
        manifest = verify_checkpoint(mgr.path(1))
        assert manifest["step"] == 1 and manifest["files"]
        assert os.path.exists(os.path.join(mgr.path(1), MANIFEST_NAME))

    def test_keep_n_gc(self, tmp_path):
        mgr, _, _ = self._train_and_save(tmp_path, steps=5, keep=2)
        assert mgr.steps() == [4, 5]

    def test_truncated_checkpoint_quarantined_and_fallback(self, tmp_path):
        from conftest import corrupt_largest_file
        mgr, exe, _ = self._train_and_save(tmp_path, steps=2)
        corrupt_largest_file(mgr.path(2))
        with pytest.raises(CorruptCheckpoint):
            mgr.verify(2)
        got = mgr.restore_latest()
        assert got == 1                      # fell back past the torn one
        assert mgr.steps() == [1]
        assert any("ckpt-2" in q for q in mgr.quarantined())
        # the latest pointer follows the restored step
        assert fluid.io.load_checkpoint(exe, str(tmp_path)) == 1

    def test_bitflip_detected_by_checksum(self, tmp_path):
        from conftest import corrupt_largest_file
        mgr, _, _ = self._train_and_save(tmp_path, steps=1)
        corrupt_largest_file(mgr.path(1), truncate_to_half=False)
        with pytest.raises(CorruptCheckpoint, match="checksum"):
            mgr.verify(1)

    def test_resave_same_step_overwrites_safely(self, tmp_path):
        """Re-committing an existing step (rollback + retrain) displaces
        the old dir by rename, never rmtree-before-rename."""
        mgr, exe, loss = self._train_and_save(tmp_path, steps=1)
        exe.run(fluid.default_main_program(), feed=_tiny_feed(9),
                fetch_list=[loss])
        mgr.save(1)  # overwrite the committed ckpt-1
        assert mgr.steps() == [1]
        assert mgr.restore_latest() == 1
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.startswith(".tmp-")]

    def test_legacy_checkpoint_without_manifest_still_restores(
            self, tmp_path):
        """Pre-manifest checkpoints (written before this runtime) are
        unverifiable but valid: restore_latest loads them and must NOT
        quarantine them."""
        mgr, exe, _ = self._train_and_save(tmp_path, steps=1)
        os.remove(os.path.join(mgr.path(1), MANIFEST_NAME))
        assert mgr.restore_latest() == 1
        assert mgr.quarantined() == []

    def test_restore_latest_empty_dir(self, tmp_path):
        exe = fluid.Executor()
        mgr = CheckpointManager(str(tmp_path), executor=exe)
        assert mgr.restore_latest() is None

    def test_gc_pins_newest_verified_when_newer_are_corrupt(self,
                                                            tmp_path):
        """keep-N rotation must never delete the only checkpoint that
        still verifies: with the newer ones torn on disk, the newest
        VERIFIED step is pinned regardless of rotation."""
        from conftest import corrupt_largest_file
        mgr, _, _ = self._train_and_save(tmp_path, steps=3, keep=10)
        corrupt_largest_file(mgr.path(2))
        corrupt_largest_file(mgr.path(3))
        mgr.keep = 1
        mgr._gc()          # rotation alone would keep only corrupt ckpt-3
        assert mgr.steps() == [1, 3]   # ckpt-1 pinned, ckpt-2 collected
        assert mgr.restore_latest() == 1

    def test_gc_trusts_the_step_it_just_committed(self, tmp_path):
        """The pin scan trusts the save's own fresh commit (hashed at
        write time) — a healthy directory pays no re-verify, and GC
        still rotates normally."""
        mgr, _, _ = self._train_and_save(tmp_path, steps=5, keep=2)
        assert mgr.steps() == [4, 5]

    def test_mark_good_and_restore_last_good(self, tmp_path):
        mgr, exe, _ = self._train_and_save(tmp_path, steps=3)
        assert mgr.mark_good(2) == 2
        assert mgr.last_good_step() == 2
        assert mgr.restore_last_good() == 2
        # the latest pointer follows the known-good restore
        assert fluid.io.load_checkpoint(exe, str(tmp_path)) == 2

    def test_gc_never_collects_known_good(self, tmp_path):
        mgr, exe, loss = self._train_and_save(tmp_path, steps=2, keep=2)
        mgr.mark_good(1)
        for s in (3, 4, 5):
            exe.run(fluid.default_main_program(), feed=_tiny_feed(s),
                    fetch_list=[loss])
            mgr.save(s)
        # rotation keeps the newest 2 AND the known-good anchor
        assert mgr.steps() == [1, 4, 5]
        assert mgr.last_good_step() == 1

    def test_resaving_the_anchor_step_drops_the_pointer(self, tmp_path):
        """Overwriting the known-good step (restart renumbering) must
        invalidate the pointer: the replacement has not earned its
        clean checks and must not inherit promoted status."""
        mgr, exe, loss = self._train_and_save(tmp_path, steps=2)
        mgr.mark_good(2)
        exe.run(fluid.default_main_program(), feed=_tiny_feed(9),
                fetch_list=[loss])
        mgr.save(2)                  # displaces the promoted ckpt-2
        assert mgr.last_good_step() is None
        assert mgr.restore_last_good() == 2   # falls back to latest

    def test_restore_reports_params_only_when_no_datapipe_state(
            self, tmp_path):
        """A known-good checkpoint saved before a pipeline was attached
        restores params only: last_restore_rewound must say so (the
        sentinel rollback branches on it instead of guessing)."""
        mgr, _, _ = self._train_and_save(tmp_path, steps=1)
        mgr.mark_good(1)

        class _Pipe:
            def load_state_dict(self, d):
                raise AssertionError("no state to load")

        mgr.datapipe = _Pipe()
        assert mgr.restore_last_good() == 1
        assert mgr.last_restore_rewound is False

    def test_mark_good_of_rotated_away_step_returns_none(self, tmp_path):
        """keep-N can delete a step before its promotion catches up
        (the clean-check lag): mark_good must refuse the phantom, not
        write a pointer to a nonexistent dir."""
        import shutil as _shutil
        mgr, _, _ = self._train_and_save(tmp_path, steps=2)
        _shutil.rmtree(mgr.path(1))
        assert mgr.mark_good(1) is None
        assert mgr.last_good_step() is None

    def test_gc_protects_fresh_commit_under_restart_renumbering(
            self, tmp_path):
        """A restart that renumbers from 0 into a directory holding
        higher steps must not let the save's own GC collect the
        checkpoint it just committed (the 'latest' pointer names it)."""
        mgr, exe, loss = self._train_and_save(tmp_path, steps=6, keep=3)
        exe.run(fluid.default_main_program(), feed=_tiny_feed(9),
                fetch_list=[loss])
        mgr.save(0)          # renumbered: sorts below every victim
        assert 0 in mgr.steps()
        assert fluid.io.load_checkpoint(exe, str(tmp_path)) == 0

    def test_mark_good_reverifies_foreign_checkpoints(self, tmp_path):
        """A manager that did not write the checkpoint itself (restart)
        must re-verify before promoting — a torn checkpoint can never
        become the rollback anchor."""
        from conftest import corrupt_largest_file
        mgr, exe, _ = self._train_and_save(tmp_path, steps=1)
        corrupt_largest_file(mgr.path(1))
        fresh = CheckpointManager(str(tmp_path), executor=exe)
        with pytest.raises(CorruptCheckpoint):
            fresh.mark_good(1)
        assert fresh.last_good_step() is None

    def test_restore_last_good_falls_back_when_good_is_corrupt(
            self, tmp_path):
        from conftest import corrupt_largest_file
        mgr, _, _ = self._train_and_save(tmp_path, steps=3)
        mgr.mark_good(2)
        corrupt_largest_file(mgr.path(2))
        got = mgr.restore_last_good()
        assert got == 3                      # newest verifiable wins
        assert any("ckpt-2" in q for q in mgr.quarantined())
        assert mgr.last_good_step() is None  # stale pointer dropped

    def test_kill_at_commit_leaves_previous_restorable(self, tmp_path):
        """A crash between the temp write and the atomic rename must not
        produce a partial ckpt-* dir; the previous step stays latest."""
        mgr, exe, loss = self._train_and_save(tmp_path, steps=1)
        chaos.inject("ckpt.commit", error=KeyboardInterrupt("preempted"))
        exe.run(fluid.default_main_program(), feed=_tiny_feed(2),
                fetch_list=[loss])
        with pytest.raises(KeyboardInterrupt):
            mgr.save(2)
        chaos.clear()
        assert mgr.steps() == [1]            # no partial ckpt-2
        leftovers = [n for n in os.listdir(str(tmp_path))
                     if n.startswith(".tmp-")]
        assert leftovers                      # torn temp dir left behind...
        assert mgr.restore_latest() == 1
        mgr.save(2)                           # ...and swept by the next GC
        assert not [n for n in os.listdir(str(tmp_path))
                    if n.startswith(".tmp-")]


# ---------------------------------------------------------------------------
# graceful shutdown
# ---------------------------------------------------------------------------

class TestGracefulShutdown:
    def test_sigterm_sets_flag_and_restores_handler(self):
        prev = signal.getsignal(signal.SIGTERM)
        with GracefulShutdown() as stop:
            assert not stop.should_stop()
            os.kill(os.getpid(), signal.SIGTERM)
            assert stop.wait(5.0)
            assert stop.received == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_preempted_loop_commits_final_checkpoint(self, tmp_path):
        loss = _tiny_model()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        mgr = CheckpointManager(str(tmp_path), executor=exe)
        done = 0
        with GracefulShutdown() as stop:
            for step in range(1, 100):
                if stop.should_stop():
                    break
                exe.run(fluid.default_main_program(), feed=_tiny_feed(step),
                        fetch_list=[loss])
                done = step
                if step == 3:  # "SIGTERM" arrives mid-run
                    os.kill(os.getpid(), signal.SIGTERM)
            mgr.save(done)  # the final commit a preemption must not lose
        assert done == 3 and mgr.restore_latest() == 3


# ---------------------------------------------------------------------------
# kill-and-resume drill (acceptance criterion)
# ---------------------------------------------------------------------------

TRAINER_SCRIPT = r'''
"""Deterministic trainer for the kill-and-resume drill: checkpoint every
step through CheckpointManager, resume from restore_latest(), fire the
train.step failpoint so PADDLE_TPU_CHAOS can kill it mid-epoch."""
import argparse
import json

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.fault import CheckpointManager, chaos

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", required=True)
ap.add_argument("--steps", type=int, required=True)
ap.add_argument("--out", required=True)
args = ap.parse_args()

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[6], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, 1, param_attr="w", bias_attr="b")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9) \
        .minimize(loss)

exe = fluid.Executor()
exe.run(startup)
mgr = CheckpointManager(args.ckpt, keep=3, executor=exe, main_program=main)
start = mgr.restore_latest() or 0

def feed_for(step):
    rng = np.random.RandomState(1000 + step)
    xs = rng.rand(16, 6).astype("float32")
    ys = (xs @ np.arange(1.0, 7.0, dtype="float32").reshape(6, 1)
          ).astype("float32")
    return {"x": xs, "y": ys}

final_loss = None
for step in range(start + 1, args.steps + 1):
    chaos.fire("train.step", step=step)
    (lv,) = exe.run(main, feed=feed_for(step), fetch_list=[loss.name])
    final_loss = float(np.asarray(lv).reshape(-1)[0])
    mgr.save(step)

with open(args.out, "w") as f:
    json.dump({"final_loss": final_loss, "resumed_from": start}, f)
'''


SENTINEL_TRAINER = r'''
"""Pipeline trainer for the crash-during-rollback drill: datapipe-driven
run_pipeline under a Sentinel guard, per-step checkpoints promoted to
known-good, resume via restore_last_good().  PADDLE_TPU_CHAOS arms
sentinel.nan (force the rollback) and ckpt.restore (kill mid-restore)."""
import argparse
import json

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
from paddle_tpu import layers
from paddle_tpu.fault import CheckpointManager, Sentinel

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", required=True)
ap.add_argument("--out", required=True)
args = ap.parse_args()

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[6], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, 1, param_attr="w", bias_attr="b")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9) \
        .minimize(loss)

rng = np.random.RandomState(7)
w_true = np.arange(1.0, 7.0, dtype="float32").reshape(6, 1)
xs = rng.rand(40, 6).astype("float32")
samples = [{"x": xs[i], "y": (xs[i:i + 1] @ w_true)[0].astype("float32")}
           for i in range(40)]
pipe = dp.InMemorySource(samples).shuffle(8, seed=3) \
    .batch(4, drop_last=True)

exe = fluid.Executor()
exe.run(startup)
mgr = CheckpointManager(args.ckpt, keep=4, executor=exe,
                        main_program=main, datapipe=pipe)
resumed = mgr.restore_last_good()
sentinel = Sentinel(manager=mgr, cadence=1, strikes=2, mark_good_after=1)

losses = []

def on_step(step, fetches):
    losses.append(float(np.asarray(fetches[0]).reshape(-1)[0]))
    mgr.save(step)
    sentinel.note_checkpoint(step)

exe.run_pipeline(main, pipe, fetch_list=[loss.name], sentinel=sentinel,
                 on_step=on_step)

with open(args.out, "w") as f:
    json.dump({"final_loss": losses[-1], "resumed_from": resumed,
               "steps": len(losses)}, f)
'''


@pytest.mark.chaos
@pytest.mark.slow  # full kill/resume drill: 5 subprocess boots; the
                   # in-process failpoint tests above are the tier-1
                   # smoke subset (ckpt.commit kill semantics included)
class TestKillAndResume:
    def test_killed_run_resumes_to_same_loss(self, tmp_path):
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_CHAOS", None)
        trainer = tmp_path / "trainer.py"
        trainer.write_text(TRAINER_SCRIPT)
        steps = 8

        def run(ckpt, out, chaos_spec=None, expect_rc=0):
            e = dict(env)
            if chaos_spec:
                e["PADDLE_TPU_CHAOS"] = chaos_spec
            r = subprocess.run(
                [sys.executable, str(trainer), "--ckpt", str(ckpt),
                 "--steps", str(steps), "--out", str(out)],
                cwd=repo_root, env=e, capture_output=True, text=True,
                timeout=300)
            assert r.returncode == expect_rc, \
                (r.returncode, r.stderr[-2000:])
            return r

        # uninterrupted reference run
        ref_out = tmp_path / "ref.json"
        run(tmp_path / "ref_ckpt", ref_out)
        ref = json.loads(ref_out.read_text())
        assert ref["resumed_from"] == 0

        # chaos run: killed hard at step 5 (steps 1-4 committed)
        ckpt = tmp_path / "ckpt"
        out = tmp_path / "got.json"
        run(ckpt, out, chaos_spec="train.step=kill@4",
            expect_rc=chaos.KILL_EXIT_CODE)
        assert not out.exists()              # it really died mid-epoch

        # resume: picks up from the newest committed checkpoint
        run(ckpt, out)
        got = json.loads(out.read_text())
        assert got["resumed_from"] == 4
        np.testing.assert_allclose(got["final_loss"], ref["final_loss"],
                                   rtol=1e-5)

    def test_crash_during_rollback_restarts_clean(self, tmp_path):
        """Chaos-kill the trainer mid-``restore_last_good()`` (the
        sentinel's rollback rung) and assert the subsequent restart
        still restores a verified checkpoint with MATCHING datapipe
        state: the resumed run must reach the same final loss as an
        uninterrupted reference run, because restores never mutate
        committed checkpoints."""
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_CHAOS", None)
        trainer = tmp_path / "trainer.py"
        trainer.write_text(SENTINEL_TRAINER)

        def run(ckpt, out, chaos_spec=None, expect_rc=0):
            e = dict(env)
            if chaos_spec:
                e["PADDLE_TPU_CHAOS"] = chaos_spec
            r = subprocess.run(
                [sys.executable, str(trainer), "--ckpt", str(ckpt),
                 "--out", str(out)],
                cwd=repo_root, env=e, capture_output=True, text=True,
                timeout=300)
            assert r.returncode == expect_rc, \
                (r.returncode, r.stderr[-2000:])
            return r

        # uninterrupted reference: 40 samples / batch 4 -> 10 steps
        ref_out = tmp_path / "ref.json"
        run(tmp_path / "ref_ckpt", ref_out)
        ref = json.loads(ref_out.read_text())
        assert ref["resumed_from"] is None and ref["steps"] == 10

        # chaos run: NaNs at steps 5-6 force a rollback, and the
        # rollback's restore itself is chaos-killed mid-read
        ckpt = tmp_path / "ckpt"
        out = tmp_path / "got.json"
        run(ckpt, out,
            chaos_spec="sentinel.nan=error@4*2;ckpt.restore=kill",
            expect_rc=chaos.KILL_EXIT_CODE)
        assert not out.exists()          # it really died mid-rollback

        # restart without chaos: restore_last_good() must verify and
        # load the known-good checkpoint (params + datapipe position)
        run(ckpt, out)
        got = json.loads(out.read_text())
        assert got["resumed_from"] == 2  # newest PROMOTED known-good
        assert got["steps"] == 7         # batches 3..9 replayed
        np.testing.assert_allclose(got["final_loss"], ref["final_loss"],
                                   rtol=1e-5)
        # the quarantine bundles from the poisoned steps survived too
        qdir = ckpt / "quarantine"
        assert qdir.is_dir() and len(list(qdir.glob("*.pkl"))) == 2

    def test_resume_skips_truncated_checkpoint(self, tmp_path):
        """Kill + corrupt the newest surviving checkpoint: recovery must
        checksum-detect it, quarantine, and resume from the one before."""
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_CHAOS", None)
        trainer = tmp_path / "trainer.py"
        trainer.write_text(TRAINER_SCRIPT)
        ckpt = tmp_path / "ckpt"
        out = tmp_path / "out.json"
        e = dict(env, PADDLE_TPU_CHAOS="train.step=kill@4")
        r = subprocess.run(
            [sys.executable, str(trainer), "--ckpt", str(ckpt),
             "--steps", "8", "--out", str(out)],
            cwd=repo_root, env=e, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == chaos.KILL_EXIT_CODE, r.stderr[-2000:]
        from conftest import corrupt_largest_file
        corrupt_largest_file(ckpt / "ckpt-4")
        r = subprocess.run(
            [sys.executable, str(trainer), "--ckpt", str(ckpt),
             "--steps", "8", "--out", str(out)],
            cwd=repo_root, env=env, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        got = json.loads(out.read_text())
        assert got["resumed_from"] == 3      # ckpt-4 skipped by checksum
        assert any(n.endswith(".corrupt") for n in os.listdir(ckpt))
