"""Fleet observability plane units: metrics federation
(obs.aggregate.FleetScraper), cross-process trace assembly with
clock-skew normalization, the SLO watchdog (obs.slo), and the bench
trajectory recorder/gate (obs.bench_history) + their CLI surfaces.
The end-to-end churn drill (kill a replica mid-scrape under a live
router) lives in tests/test_fleet.py next to the other chaos drills."""

import json
import os
import time
import warnings

import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu import cli, profiler
from paddle_tpu.obs import aggregate, bench_history, slo, trace
from paddle_tpu.profiler import RuntimeMetrics
from paddle_tpu.serving import InferenceServer

from tests.test_obs_prom import assert_conformant


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("obs_fleet") / "model")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[4])
        pred = layers.fc(input=x, size=2)
        exe = fluid.Executor()
        exe.run(startup)
        fluid.io.save_inference_model(d, ["x"], [pred], exe,
                                      main_program=main)
    return d


def _addr(server):
    return f"{server.addr[0]}:{server.addr[1]}"


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------

class TestFederation:
    def test_scrape_federate_and_stale_marking(self, model_dir):
        a = InferenceServer(model_dir, port=0)
        b = InferenceServer(model_dir, port=0)
        a.start_background()
        b.start_background()
        targets = [(_addr(a), "ra"), (_addr(b), "rb")]
        scraper = aggregate.FleetScraper(lambda: targets, timeout=5.0)
        try:
            profiler.runtime_metrics.inc("serving.requests_ok", 3)
            text, scrapes = scraper.federate()
            assert all(s["ok"] for s in scrapes)
            assert_conformant(text)
            # per-replica labels + liveness rows for both replicas
            for addr, rid in targets:
                assert (f'paddle_tpu_fleet_replica_up{{replica="{addr}"'
                        f',id="{rid}",stale="0"}} 1') in text
                assert f'replica="{addr}"' in text
            # first pass: totals but no rates yet
            assert "paddle_tpu_fleet_rps" not in text
            assert "paddle_tpu_fleet_replicas_scraped 2" in text
            assert "paddle_tpu_fleet_replicas_stale 0" in text

            # second pass computes rates from counter deltas
            profiler.runtime_metrics.inc("serving.requests_ok", 5)
            text, _ = scraper.federate()
            assert "paddle_tpu_fleet_rps " in text

            # kill one replica: the rollup must still render, with the
            # corpse marked stale instead of failing the scrape
            b.shutdown()
            errors0 = profiler.runtime_metrics.counter(
                "fleet.scrape.errors")
            text, scrapes = scraper.federate()
            assert_conformant(text)
            by_addr = {s["addr"]: s for s in scrapes}
            assert by_addr[_addr(a)]["ok"]
            assert not by_addr[_addr(b)]["ok"]
            assert by_addr[_addr(b)]["error"]
            assert (f'paddle_tpu_fleet_replica_up{{replica='
                    f'"{_addr(b)}",id="rb",stale="1"}} 0') in text
            assert "paddle_tpu_fleet_replicas_stale 1" in text
            # the live replica's samples still carry its label
            assert f'replica="{_addr(a)}"' in text
            assert f'total{{replica="{_addr(b)}"}}' not in text
            assert profiler.runtime_metrics.counter(
                "fleet.scrape.errors") > errors0
        finally:
            a.shutdown()
            try:
                b.shutdown()
            except Exception:
                pass

    def test_rates_survive_replica_death_between_scrapes(self):
        """Review regression: deltas are per-replica — a replica dying
        (its counters leaving the live sum) must not zero the
        survivors' reported fleet rate."""
        m = RuntimeMetrics()

        def scrape_of(addr, requests):
            return {"addr": addr, "id": addr, "ok": True,
                    "stats": {"counters":
                              {"serving.requests_ok": requests}}}

        scraper = aggregate.FleetScraper(lambda: [], metrics=m)
        rps, _ = scraper._rates([scrape_of("a", 10000),
                                 scrape_of("b", 10000)])
        assert rps is None                      # first pass: no window
        time.sleep(0.02)
        # b died; a served 50 more requests — the fleet rate is a's
        # delta, NOT max(0, 10050 - 20000) == 0
        rps, _ = scraper._rates([scrape_of("a", 10050)])
        assert rps is not None and rps > 0
        time.sleep(0.02)
        # b restarts with reset counters: clamped per-replica, a's
        # delta still counts
        rps, _ = scraper._rates([scrape_of("a", 10100),
                                 scrape_of("b", 3)])
        assert rps is not None and rps > 0

    def test_merged_quantile_is_count_weighted(self):
        def scrape(count, p99):
            return {"ok": True, "stats": {"series": {
                "gen.ttft_seconds": {"count": count, "p99": p99}}}}
        scrapes = [scrape(30, 0.1), scrape(10, 0.5),
                   {"ok": False, "stats": None}]
        got = aggregate.merged_quantile(scrapes, "gen.ttft_seconds",
                                        "p99")
        assert got == pytest.approx((30 * 0.1 + 10 * 0.5) / 40)
        assert aggregate.merged_quantile(scrapes, "nope") is None


# ---------------------------------------------------------------------------
# cross-process trace assembly
# ---------------------------------------------------------------------------

def _payload(pid, proc, spans, epoch_unix, now_unix):
    return {"pid": pid, "process_name": proc, "epoch_unix": epoch_unix,
            "now_unix": now_unix, "spans": spans}


class TestTraceAssembly:
    def _span(self, name, ts, span_id, pid, trace_id="rid-1"):
        return {"name": name, "trace_id": trace_id, "span_id": span_id,
                "parent_id": None, "ts": ts, "dur": 0.01, "tid": 1,
                "pid": pid, "proc": None, "attrs": {}}

    def test_skew_normalization_against_envelope(self):
        """A replica whose wall clock is 100s ahead still lands its
        spans where they belong on the assembler's timeline: the
        send/recv envelope pins the offset."""
        zero = 1000.0
        # assembler's own span at t=+1.0s
        local = _payload(10, "router",
                         [self._span("fleet.request", 1.0, 1, 10)],
                         epoch_unix=zero, now_unix=zero + 2.0)
        # the replica handled the same request ~1.05s in (its clock is
        # +100s skewed); the scrape happened at assembler time 2.0
        SKEW = 100.0
        remote = _payload(20, "replica:r0",
                          [self._span("serving.request", 0.05, 1, 20)],
                          epoch_unix=zero + 1.0 + SKEW,
                          now_unix=zero + 2.0 + SKEW)
        obj = aggregate.assemble_fleet_trace(
            [{"source": "router", "payload": local, "envelope": None},
             {"source": "r0", "payload": remote,
              "envelope": (zero + 1.99, zero + 2.01)}],
            zero_unix=zero)
        evs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        by_pid = {e["pid"]: e for e in evs}
        assert set(by_pid) == {10, 20}
        # local span at 1.0s; remote at ~1.05s on the SAME clock
        assert by_pid[10]["ts"] == pytest.approx(1.0 * 1e6)
        assert by_pid[20]["ts"] == pytest.approx(1.05 * 1e6, abs=0.1e6)
        offsets = {p["source"]: p["clock_offset_s"]
                   for p in obj["fleetAssembly"]["processes"]}
        assert offsets["r0"] == pytest.approx(SKEW, abs=0.1)
        # one process_name metadata row per pid
        meta = {e["pid"]: e["args"]["name"]
                for e in obj["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"}
        assert meta[20] == "replica:r0" and meta[10] == "router"

    def test_colliding_os_pids_stay_distinct_processes(self):
        """Review regression: containerized replicas all run as pid 1 —
        identity is (pid, process_name), so neither replica's spans are
        dropped and each keeps its own (remapped) timeline row."""
        zero = 0.0
        a = _payload(1, "replica:r0",
                     [self._span("serving.request", 1.0, 1, 1)],
                     zero, zero + 2.0)
        b = _payload(1, "replica:r1",
                     [self._span("serving.request", 1.1, 1, 1)],
                     zero, zero + 2.0)
        obj = aggregate.assemble_fleet_trace(
            [{"source": "r0", "payload": a,
              "envelope": (zero + 1.9, zero + 2.1)},
             {"source": "r1", "payload": b,
              "envelope": (zero + 1.9, zero + 2.1)}],
            zero_unix=zero)
        evs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        assert len(evs) == 2                    # nothing deduped away
        assert len({e["pid"] for e in evs}) == 2  # two distinct rows
        meta = {e["args"]["name"] for e in obj["traceEvents"]
                if e["ph"] == "M" and e["name"] == "process_name"}
        assert {"replica:r0", "replica:r1"} <= meta
        procs = obj["fleetAssembly"]["processes"]
        assert all(p["os_pid"] == 1 for p in procs)
        assert len({p["pid"] for p in procs}) == 2

    def test_dedupe_and_failures_reported(self):
        zero = 0.0
        spans = [self._span("a", 1.0, 7, 10)]
        p = _payload(10, "proc", spans, zero, zero + 1.5)
        obj = aggregate.assemble_fleet_trace(
            [{"source": "self", "payload": p, "envelope": None},
             # the same ring scraped twice (in-process fleet): deduped
             {"source": "again", "payload": p,
              "envelope": (zero + 1.4, zero + 1.6)},
             {"source": "corpse", "error": "ConnectionError: down"}],
            zero_unix=zero)
        evs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        assert len(evs) == 1
        assert obj["fleetAssembly"]["failures"] == [
            {"source": "corpse", "error": "ConnectionError: down"}]

    def test_live_servers_spans_endpoint_assembles(self, model_dir):
        """/spans end-to-end: scrape a real server's ring and merge it
        with the local one."""
        server = InferenceServer(model_dir, port=0)
        server.start_background()
        trace.enable(4096)
        try:
            with trace.trace_context("rid-spans-1"), \
                    trace.span("local.mark"):
                pass
            payload, envelope = aggregate.fetch_spans(_addr(server))
            assert payload["pid"] == os.getpid()  # in-process server
            assert envelope[0] <= envelope[1]
            obj = aggregate.assemble_fleet_trace(
                [{"source": "local",
                  "payload": trace.snapshot_payload(),
                  "envelope": None},
                 {"source": _addr(server), "payload": payload,
                  "envelope": envelope}])
            names = {e["name"] for e in obj["traceEvents"]}
            assert "local.mark" in names
        finally:
            server.shutdown()
            trace.disable()
            trace.clear()


# ---------------------------------------------------------------------------
# SLO watchdog
# ---------------------------------------------------------------------------

class TestSLOSpec:
    def test_example_spec_is_valid(self):
        assert slo.validate_spec(slo.EXAMPLE_SPEC) == []

    def test_validator_names_every_problem(self):
        problems = slo.validate_spec({
            "version": 2,
            "sustained_breaches": 0,
            "objectives": [
                {"name": "a", "kind": "quantile", "series": "s",
                 "quantile": "p42", "max": -1},
                {"name": "a", "kind": "error_rate", "ok": [],
                 "errors": ["e"], "max_ratio": 2},
                {"name": "c", "kind": "warp_drive"},
                {"name": "d", "kind": "rate_floor", "counter": "t",
                 "min_rate": 1.0, "surprise": True},
            ]})
        text = "\n".join(problems)
        for needle in ("version", "sustained_breaches", "p42", "max",
                       "duplicate name 'a'", "ok", "max_ratio",
                       "warp_drive", "surprise"):
            assert needle in text, (needle, problems)

    def test_load_spec_raises_with_problem_list(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text('{"version": 1, "objectives": "nope"}')
        with pytest.raises(ValueError, match="objectives"):
            slo.load_spec(str(p))
        p.write_text("{not json")
        with pytest.raises(ValueError, match="not JSON"):
            slo.load_spec(str(p))

    def test_watchdog_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(slo.SLO_ENV, raising=False)
        assert slo.watchdog_from_env() is None
        good = tmp_path / "good.json"
        good.write_text(json.dumps(slo.EXAMPLE_SPEC))
        monkeypatch.setenv(slo.SLO_ENV, str(good))
        wd = slo.watchdog_from_env()
        assert wd is not None and len(wd.spec.objectives) == 4
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        monkeypatch.setenv(slo.SLO_ENV, str(bad))
        with pytest.warns(UserWarning, match="disarmed"):
            assert slo.watchdog_from_env() is None


def _spec(*objectives, sustained=3, interval=0.01):
    return {"version": 1, "interval_seconds": interval,
            "sustained_breaches": sustained,
            "objectives": list(objectives)}


class TestSLOWatchdog:
    def test_quantile_breach_and_recovery(self):
        m = RuntimeMetrics()
        wd = slo.SLOWatchdog(_spec(
            {"name": "lat", "kind": "quantile",
             "series": "serving.request_seconds", "quantile": "p99",
             "max": 0.2}), metrics=m)
        assert wd.evaluate() == []          # no samples: skip, no breach
        for _ in range(10):
            m.observe("serving.request_seconds", 0.5)
        (breach,) = wd.evaluate()
        assert breach["objective"] == "lat"
        assert breach["value"] == pytest.approx(0.5)
        assert breach["threshold"] == 0.2
        assert m.counter("slo.breach") == 1
        assert m.counter("slo.evaluations") == 2
        assert m.gauge("slo.breaching") == 1
        assert wd.breach_log and wd.state()["breaching"] == {"lat": 1}
        # recovery: flood the window with fast samples
        for _ in range(3000):
            m.observe("serving.request_seconds", 0.01)
        assert wd.evaluate() == []
        assert m.gauge("slo.breaching") == 0

    def test_error_rate_uses_counter_deltas(self):
        m = RuntimeMetrics()
        wd = slo.SLOWatchdog(_spec(
            {"name": "err", "kind": "error_rate",
             "ok": ["fleet.requests_ok"], "errors": ["fleet.shed"],
             "max_ratio": 0.1}), metrics=m)
        m.inc("fleet.shed", 100)            # PRE-existing errors
        assert wd.evaluate() == []          # first pass: no window yet
        m.inc("fleet.requests_ok", 99)
        m.inc("fleet.shed", 1)              # 1% this window: fine
        assert wd.evaluate() == []
        m.inc("fleet.requests_ok", 5)
        m.inc("fleet.shed", 5)              # 50% this window: breach
        (breach,) = wd.evaluate()
        assert breach["value"] == pytest.approx(0.5)

    def test_rate_floor_skips_idle_unless_told(self):
        m = RuntimeMetrics()
        wd = slo.SLOWatchdog(_spec(
            {"name": "tok", "kind": "rate_floor",
             "counter": "gen.tokens", "min_rate": 1e9}), metrics=m)
        assert wd.evaluate() == []          # no prev window
        assert wd.evaluate() == []          # idle: skipped by default
        m.inc("gen.tokens", 3)              # active but way under floor
        (breach,) = wd.evaluate()
        assert breach["objective"] == "tok"
        # liveness variant: idle_ok false breaches on silence
        wd2 = slo.SLOWatchdog(_spec(
            {"name": "alive", "kind": "rate_floor",
             "counter": "gen.tokens", "min_rate": 1.0,
             "idle_ok": False}), metrics=m)
        assert wd2.evaluate() == []         # first pass seeds
        time.sleep(0.01)
        (breach,) = wd2.evaluate()
        assert breach["objective"] == "alive"

    def test_sustained_breach_writes_one_postmortem_per_episode(
            self, tmp_path, monkeypatch):
        pm_dir = tmp_path / "pm"
        pm_dir.mkdir()
        monkeypatch.setenv("PADDLE_TPU_POSTMORTEM", str(pm_dir))
        m = RuntimeMetrics()
        wd = slo.SLOWatchdog(_spec(
            {"name": "lat", "kind": "quantile",
             "series": "s", "quantile": "p99", "max": 0.1},
            sustained=2), metrics=m)
        m.observe("s", 1.0)
        wd.evaluate()                       # breach 1: no post-mortem
        assert m.counter("slo.postmortems") == 0
        wd.evaluate()                       # breach 2: SUSTAINED
        assert m.counter("slo.postmortems") == 1
        wd.evaluate()                       # still breaching: no redump
        assert m.counter("slo.postmortems") == 1
        pm_file = pm_dir / f"postmortem-{os.getpid()}.json"
        body = json.loads(pm_file.read_text())
        assert "sustained SLO breach: lat" in body["reason"]
        assert body["extra"]["slo_breach"]["objective"] == "lat"
        assert body["extra"]["spec"]["objectives"]
        # recovery re-arms the episode: a NEW sustained breach redumps
        for _ in range(3000):
            m.observe("s", 0.001)
        assert wd.evaluate() == []
        for _ in range(3000):
            m.observe("s", 1.0)
        wd.evaluate()
        wd.evaluate()
        assert m.counter("slo.postmortems") == 2

    def test_maybe_evaluate_respects_interval(self):
        m = RuntimeMetrics()
        wd = slo.SLOWatchdog(_spec(
            {"name": "lat", "kind": "quantile", "series": "s",
             "quantile": "p99", "max": 1.0}, interval=3600.0),
            metrics=m)
        assert wd.maybe_evaluate() is not None    # first call runs
        assert wd.maybe_evaluate() is None        # not due for an hour
        assert wd.evaluations == 1

    def test_background_thread_evaluates(self):
        m = RuntimeMetrics()
        for _ in range(5):
            m.observe("s", 9.0)
        wd = slo.SLOWatchdog(_spec(
            {"name": "lat", "kind": "quantile", "series": "s",
             "quantile": "p99", "max": 0.1}, interval=0.02),
            metrics=m)
        wd.start(interval=0.02)
        try:
            deadline = time.time() + 5
            while m.counter("slo.breach") < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert m.counter("slo.breach") >= 2
        finally:
            wd.stop()

    def test_gen_scheduler_ticks_armed_watchdog(self, tmp_path,
                                                monkeypatch):
        """The GenScheduler wiring: an armed PADDLE_TPU_SLO is picked
        up at construction and evaluated from the decode loop."""
        from paddle_tpu.gen.scheduler import GenScheduler

        spec = tmp_path / "slo.json"
        spec.write_text(json.dumps(_spec(
            {"name": "lat", "kind": "quantile",
             "series": "gen.ttft_seconds", "quantile": "p99",
             "max": 10.0}, interval=0.001)))
        monkeypatch.setenv(slo.SLO_ENV, str(spec))

        class _StubPredictor:
            num_slots, vocab_size, max_prompt_len = 2, 8, 4
            max_len, eos_id = 8, 0

        sched = GenScheduler(_StubPredictor(), queue_size=2)
        try:
            assert sched.slo_watchdog is not None
            assert sched.slo_watchdog.spec.objectives[0]["name"] == "lat"
        finally:
            sched.close()


# ---------------------------------------------------------------------------
# bench trajectory
# ---------------------------------------------------------------------------

class TestBenchTrajectory:
    def test_record_check_roundtrip_and_degradation(self, tmp_path):
        path = str(tmp_path / "traj.json")
        metrics = {"tokens_per_sec": 200.0, "tokens_per_sec_ratio": 2.5,
                   "ttft_p99_ms": 250.0, "lost_requests": 0}
        bench_history.record("decode", metrics, path=path, baseline=True)
        bench_history.record("decode", dict(metrics), path=path)
        report = bench_history.check(path)
        assert report["ok"], report
        assert report["benches"]["decode"]["comparisons"]
        # a degraded newest run regresses past the band: check fails
        bench_history.record("decode",
                             dict(metrics, tokens_per_sec=50.0),
                             path=path)
        report = bench_history.check(path)
        assert not report["ok"]
        (reg,) = report["benches"]["decode"]["regressions"]
        assert reg["metric"] == "tokens_per_sec"
        # --dry ignores the regression but still gates the schema
        assert bench_history.check(path, dry=True)["ok"]

    def test_baseline_flag_wins_over_first_run(self, tmp_path):
        path = str(tmp_path / "traj.json")
        bench_history.record("decode", {"tokens_per_sec": 500.0},
                             path=path)      # old, unrealistic first run
        bench_history.record("decode", {"tokens_per_sec": 200.0},
                             path=path, baseline=True)
        bench_history.record("decode", {"tokens_per_sec": 190.0},
                             path=path)
        report = bench_history.check(path)
        # vs the FLAGGED baseline (200) this passes; vs the first run
        # (500) it would have failed
        assert report["ok"], report

    def test_schema_gate_catches_malformation(self, tmp_path):
        path = tmp_path / "traj.json"
        path.write_text(json.dumps(
            {"format": 1, "runs": [{"bench": "decode",
                                    "time_unix": "yesterday",
                                    "metrics": {"x": "fast"}}]}))
        report = bench_history.check(str(path))
        assert not report["ok"]
        text = "\n".join(report["problems"])
        assert "time_unix" in text and "'x'" in text
        path.write_text("[1, 2]")
        assert not bench_history.check(str(path), dry=True)["ok"]

    def test_extractions_match_repo_artifacts(self):
        """summary_metrics stays in lockstep with the real bench
        artifacts AND the shipped BENCH_TRAJECTORY.json passes the
        gate — the acceptance criterion's 'exit zero on the real one'."""
        root = os.path.dirname(bench_history.default_path())
        for bench, src in (("serving", "BENCH_SERVING.json"),
                           ("datapipe", "BENCH_DATAPIPE.json"),
                           ("fleet", "BENCH_FLEET.json"),
                           ("decode", "BENCH_DECODE.json")):
            with open(os.path.join(root, src)) as f:
                summary = json.load(f)
            metrics = bench_history.summary_metrics(bench, summary)
            assert metrics and all(
                isinstance(v, (int, float)) for v in metrics.values())
            judged = set(metrics) & set(
                bench_history.BENCH_METRICS[bench])
            assert judged, (bench, metrics)
        report = bench_history.check()       # the shipped trajectory
        assert report["ok"], report

    def test_cli_exit_codes(self, tmp_path, capsys):
        path = str(tmp_path / "traj.json")
        metrics = {"tokens_per_sec": 200.0}
        bench_history.record("decode", metrics, path=path,
                             baseline=True)
        assert cli.main(["bench", "check", "--trajectory", path]) == 0
        bench_history.record("decode", {"tokens_per_sec": 10.0},
                             path=path)
        assert cli.main(["bench", "check", "--trajectory", path]) == 1
        assert cli.main(["bench", "check", "--trajectory", path,
                         "--dry"]) == 0
        out = capsys.readouterr().out
        assert "FAIL" in out
        # record imports an artifact through the shared extractor
        root = os.path.dirname(bench_history.default_path())
        assert cli.main([
            "bench", "record", "--bench", "fleet", "--summary",
            os.path.join(root, "BENCH_FLEET.json"),
            "--trajectory", str(tmp_path / "t2.json"),
            "--baseline"]) == 0
        obj = bench_history.load_trajectory(str(tmp_path / "t2.json"))
        assert obj["runs"][0]["bench"] == "fleet"
        assert obj["runs"][0]["baseline"] is True


class TestFleetStatsCLI:
    def test_fleet_stats_static_replicas(self, model_dir, capsys):
        server = InferenceServer(model_dir, port=0)
        server.start_background()
        try:
            rc = cli.main(["fleet-stats", "--replicas", _addr(server)])
            assert rc == 0
            out = capsys.readouterr().out
            assert_conformant(out)
            assert f'replica="{_addr(server)}"' in out
            rc = cli.main(["fleet-stats", "--replicas", _addr(server),
                           "--json"])
            assert rc == 0
            report = json.loads(capsys.readouterr().out)
            assert report["replicas"][0]["ok"] is True
        finally:
            server.shutdown()

    def test_fleet_stats_needs_a_target(self, capsys):
        assert cli.main(["fleet-stats"]) == 2
