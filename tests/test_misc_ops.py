"""Tests for the formerly-stubbed op set: im2sequence, row_conv,
dynamic_lstmp, conv_shift, pool3d, unpool, spp, positive_negative_pair
(mirror reference test_im2sequence_op.py, test_row_conv_op.py,
test_lstmp_op.py, test_conv_shift_op.py, test_pool3d_op.py,
test_unpool_op.py, test_spp_op.py, test_positive_negative_pair_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers


def _run(feed, fetch_list, startup=True):
    exe = fluid.Executor()
    if startup:
        exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed,
                   fetch_list=fetch_list)


class TestIm2Sequence:
    def test_patches(self):
        x = np.arange(1 * 1 * 4 * 4, dtype="float32").reshape(1, 1, 4, 4)
        xv = layers.data(name="x", shape=[1, 1, 4, 4],
                         append_batch_size=False)
        out = layers.im2sequence(xv, filter_size=2, stride=2)
        (got,) = _run({"x": x}, [out], startup=False)
        assert got.shape == (4, 4)  # 2x2 grid of 1*2*2 patches
        np.testing.assert_allclose(got[0], [0, 1, 4, 5])
        np.testing.assert_allclose(got[3], [10, 11, 14, 15])


class TestRowConv:
    def test_lookahead(self):
        rng = np.random.RandomState(0)
        x = rng.rand(5, 3).astype("float32")
        lod = [[0, 3, 5]]
        xv = layers.data(name="x", shape=[5, 3], append_batch_size=False,
                         lod_level=1)
        out = layers.row_conv(xv, future_context_size=1,
                              param_attr="rc_w")
        (got,) = _run({"x": (x, lod)}, [out])
        w = np.asarray(fluid.global_scope().find_var("rc_w"))
        expect = np.zeros_like(x)
        for lo, hi in ((0, 3), (3, 5)):
            for t in range(lo, hi):
                for fw in range(2):
                    if t + fw < hi:
                        expect[t] += w[fw] * x[t + fw]
        np.testing.assert_allclose(got, expect, rtol=1e-5)


class TestDynamicLSTMP:
    def test_shapes_and_training(self):
        rng = np.random.RandomState(1)
        H, P = 4, 3
        x = rng.rand(6, 4 * H).astype("float32")
        lod = [[0, 4, 6]]
        xv = layers.data(name="x", shape=[6, 4 * H],
                         append_batch_size=False, lod_level=1)
        xv.stop_gradient = False
        proj, cell = layers.dynamic_lstmp(xv, size=4 * H, proj_size=P)
        loss = layers.reduce_mean(proj)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        losses = []
        for _ in range(5):
            p, c, lv = exe.run(fluid.default_main_program(),
                               feed={"x": (x, lod)},
                               fetch_list=[proj, cell, loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert p.shape == (6, P) and c.shape == (6, H)
        assert np.isfinite(losses).all()
        assert losses[-1] != losses[0]  # training moves the params


class TestConvShift:
    def test_circular(self):
        x = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
        y = np.array([[1.0, 0.0, 2.0]], np.float32)
        xv = layers.data(name="x", shape=[1, 4], append_batch_size=False)
        yv = layers.data(name="y", shape=[1, 3], append_batch_size=False)
        out = layers.conv_shift(xv, yv)
        (got,) = _run({"x": x, "y": y}, [out], startup=False)
        n, m = 4, 3
        expect = np.zeros((1, n), np.float32)
        for j in range(n):
            for k in range(m):
                expect[0, j] += x[0, (j + k - m // 2) % n] * y[0, k]
        np.testing.assert_allclose(got, expect, rtol=1e-6)


class TestPool3d:
    def test_max_avg(self):
        rng = np.random.RandomState(2)
        x = rng.rand(1, 2, 4, 4, 4).astype("float32")
        xv = layers.data(name="x", shape=[1, 2, 4, 4, 4],
                         append_batch_size=False)
        mx = layers.pool3d(xv, pool_size=2, pool_type="max", pool_stride=2)
        av = layers.pool3d(xv, pool_size=2, pool_type="avg", pool_stride=2)
        got_m, got_a = _run({"x": x}, [mx, av], startup=False)
        assert got_m.shape == (1, 2, 2, 2, 2)
        blk = x[0, 0, :2, :2, :2]
        np.testing.assert_allclose(got_m[0, 0, 0, 0, 0], blk.max(),
                                   rtol=1e-6)
        np.testing.assert_allclose(got_a[0, 0, 0, 0, 0], blk.mean(),
                                   rtol=1e-6)


class TestUnpool:
    def test_roundtrip_with_pool_indices(self):
        rng = np.random.RandomState(3)
        x = rng.rand(1, 1, 4, 4).astype("float32")
        xv = layers.data(name="x", shape=[1, 1, 4, 4],
                         append_batch_size=False)
        pooled, indices = layers.pool2d_with_index(xv, pool_size=2,
                                                   pool_stride=2)
        restored = layers.unpool(pooled, indices, unpool_size=2,
                                 unpool_stride=2)
        got_p, got_r = _run({"x": x}, [pooled, restored], startup=False)
        assert got_r.shape == (1, 1, 4, 4)
        # each max value returns to its original position, rest zeros
        assert np.count_nonzero(got_r) == 4
        for i in range(2):
            for j in range(2):
                blk = x[0, 0, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
                pos = np.unravel_index(blk.argmax(), blk.shape)
                np.testing.assert_allclose(
                    got_r[0, 0, 2 * i + pos[0], 2 * j + pos[1]], blk.max())


class TestSPP:
    def test_feature_sizes(self):
        rng = np.random.RandomState(4)
        x = rng.rand(2, 3, 7, 5).astype("float32")
        xv = layers.data(name="x", shape=[2, 3, 7, 5],
                         append_batch_size=False)
        out = layers.spp(xv, pyramid_height=3)
        (got,) = _run({"x": x}, [out], startup=False)
        assert got.shape == (2, 3 * (1 + 4 + 16))
        # level 0 = global max per channel
        np.testing.assert_allclose(got[:, :3],
                                   x.max(axis=(2, 3)), rtol=1e-6)


class TestPositiveNegativePair:
    def test_pairs(self):
        score = np.array([[0.9], [0.2], [0.5], [0.4]], np.float32)
        label = np.array([[1.0], [0.0], [1.0], [0.0]], np.float32)
        qid = np.array([[0], [0], [1], [1]], np.int64)
        sv = layers.data(name="s", shape=[4, 1], append_batch_size=False)
        lv = layers.data(name="l", shape=[4, 1], append_batch_size=False)
        qv = layers.data(name="q", shape=[4, 1], append_batch_size=False,
                         dtype="int64")
        helper = fluid.layer_helper.LayerHelper("positive_negative_pair")
        pos = helper.create_tmp_variable("float32")
        neg = helper.create_tmp_variable("float32")
        neu = helper.create_tmp_variable("float32")
        helper.append_op(
            type="positive_negative_pair",
            inputs={"Score": sv, "Label": lv, "QueryID": qv},
            outputs={"PositivePair": pos, "NegativePair": neg,
                     "NeutralPair": neu})
        got = _run({"s": score, "l": label, "q": qid}, [pos, neg, neu],
                   startup=False)
        # q0: (0.9 vs 0.2) correct; q1: (0.5 vs 0.4) correct
        np.testing.assert_allclose(np.asarray(got[0]).reshape(-1), [2.0])
        np.testing.assert_allclose(np.asarray(got[1]).reshape(-1), [0.0])
