"""Book test: MNIST MLP + convnet converge
(reference ``python/paddle/fluid/tests/book/test_recognize_digits.py``)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def _mlp(img, label):
    hidden = fluid.layers.fc(input=img, size=64, act="relu")
    hidden = fluid.layers.fc(input=hidden, size=64, act="relu")
    prediction = fluid.layers.fc(input=hidden, size=10, act="softmax")
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def _conv_net(img, label):
    img2d = fluid.layers.reshape(img, [-1, 1, 28, 28])
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=img2d, filter_size=5, num_filters=8, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=16, pool_size=2,
        pool_stride=2, act="relu")
    prediction = fluid.layers.fc(input=conv_pool_2, size=10, act="softmax")
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


@pytest.mark.parametrize("net", ["mlp", "conv"])
def test_recognize_digits(net):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        builder = _mlp if net == "mlp" else _conv_net
        prediction, avg_cost, acc = builder(img, label)
        opt = fluid.optimizer.Adam(learning_rate=1e-3)
        opt.minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    reader = fluid.dataset.mnist.train()
    batch = []
    accs = []
    steps = 0
    max_steps = 60 if net == "conv" else 150
    for epoch in range(4):
        for sample in reader():
            batch.append(sample)
            if len(batch) < 64:
                continue
            imgs = np.stack([b[0] for b in batch]).astype("float32")
            labels = np.asarray([[b[1]] for b in batch], dtype="int64")
            batch = []
            loss, a = exe.run(main, feed={"img": imgs, "label": labels},
                              fetch_list=[avg_cost, acc])
            accs.append(float(np.asarray(a)))
            steps += 1
            if steps >= max_steps:
                break
        if steps >= max_steps:
            break
    # synthetic digits are separable: expect strong accuracy by the end
    assert np.mean(accs[-10:]) > 0.85, np.mean(accs[-10:])
