"""Control-flow tests (mirror reference tests/unittests/test_while_op.py,
test_recurrent_op.py, test_dyn_rnn.py, test_array_read_write.py,
test_switch.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers


def _run(feed, fetch, main=None, startup=True):
    exe = fluid.Executor()
    if startup:
        exe.run(fluid.default_startup_program())
    return exe.run(main or fluid.default_main_program(), feed=feed,
                   fetch_list=fetch)


class TestArrayReadWrite:
    def test_read_write(self):
        x = layers.data(name="x", shape=[3, 4], append_batch_size=False)
        i = layers.zeros(shape=[1], dtype="int32")
        arr = layers.array_write(x, i)
        i2 = layers.fill_constant(shape=[1], dtype="int32", value=1)
        arr = layers.array_write(layers.scale(x, scale=2.0), i2, array=arr)
        a0 = layers.array_read(arr, i)
        a1 = layers.array_read(arr, i2)
        total = layers.sums(input=[a0, a1])
        n = layers.array_length(arr)

        xv = np.random.rand(3, 4).astype("float32")
        t, ln = _run({"x": xv}, [total, n], startup=False)
        np.testing.assert_allclose(t, xv * 3.0, rtol=1e-5)
        assert int(ln[0]) == 2


class TestWhile:
    def test_while_sum(self):
        # sum three data tensors accumulated through a while loop
        d0 = layers.data(name="d0", shape=[10], append_batch_size=False)
        d1 = layers.data(name="d1", shape=[10], append_batch_size=False)
        d2 = layers.data(name="d2", shape=[10], append_batch_size=False)
        i = layers.zeros(shape=[1], dtype="int32")
        i.stop_gradient = True
        init = layers.zeros(shape=[10], dtype="float32")
        mem_array = layers.array_write(x=init, i=i)
        data_array = layers.array_write(x=d0, i=i)
        i = layers.increment(i)
        layers.array_write(d1, i, array=data_array)
        i = layers.increment(i)
        layers.array_write(d2, i, array=data_array)
        i = layers.zeros(shape=[1], dtype="int32")
        i.stop_gradient = True
        array_len = layers.fill_constant(shape=[1], dtype="int32", value=3)
        array_len.stop_gradient = True
        cond = layers.less_than(x=i, y=array_len)

        w = layers.While(cond=cond)
        with w.block():
            d = layers.array_read(array=data_array, i=i)
            prev = layers.array_read(array=mem_array, i=i)
            result = layers.sums(input=[d, prev])
            i = layers.increment(x=i, in_place=True)
            layers.array_write(result, i=i, array=mem_array)
            layers.less_than(x=i, y=array_len, cond=cond)

        sum_result = layers.array_read(array=mem_array, i=array_len)

        d0v = np.random.rand(10).astype("float32")
        d1v = np.random.rand(10).astype("float32")
        d2v = np.random.rand(10).astype("float32")
        (out,) = _run({"d0": d0v, "d1": d1v, "d2": d2v}, [sum_result],
                      startup=False)
        np.testing.assert_allclose(out, d0v + d1v + d2v, rtol=1e-5)


class TestStaticRNN:
    def test_simple_accumulate(self):
        B, T, D = 4, 5, 3
        x = layers.data(name="x", shape=[B, T, D], append_batch_size=False)
        x.stop_gradient = False
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(shape=[D], batch_ref=x, init_value=0.0)
            s = layers.sums(input=[mem, xt])
            rnn.update_memory(mem, s)
            rnn.step_output(s)
        out = rnn()
        loss = layers.reduce_sum(out)

        xv = np.random.rand(B, T, D).astype("float32")
        outv, lossv = _run({"x": xv}, [out, loss], startup=False)
        expect = np.cumsum(xv, axis=1)
        np.testing.assert_allclose(outv, expect, rtol=1e-4)

    def test_static_rnn_grad(self):
        B, T, D, H = 2, 3, 4, 4
        x = layers.data(name="x", shape=[B, T, D], append_batch_size=False)
        x.stop_gradient = False
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(shape=[H], batch_ref=x, init_value=0.0)
            h = layers.fc(input=[xt, mem], size=H, act="tanh")
            rnn.update_memory(mem, h)
            rnn.step_output(h)
        out = rnn()
        loss = layers.reduce_mean(out)
        fluid.append_backward(loss)

        xv = np.random.rand(B, T, D).astype("float32")
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        lossv, gx = exe.run(
            fluid.default_main_program(), feed={"x": xv},
            fetch_list=[loss, "x@GRAD"])
        assert np.isfinite(lossv).all()
        assert gx.shape == (B, T, D)
        assert np.abs(gx).sum() > 0


class TestIfElse:
    def test_ifelse_merge(self):
        x = layers.data(name="x", shape=[6, 1], append_batch_size=False)
        zero = layers.fill_constant(shape=[6, 1], dtype="float32", value=0.0)
        cond = layers.less_than(x=x, y=zero)
        ie = layers.IfElse(cond)
        with ie.true_block():
            neg = ie.input(x)
            ie.output(layers.scale(neg, scale=-1.0))
        with ie.false_block():
            pos = ie.input(x)
            ie.output(pos)
        out = ie()

        xv = np.random.randn(6, 1).astype("float32")
        (res,) = _run({"x": xv}, [out], startup=False)
        np.testing.assert_allclose(res, np.abs(xv), rtol=1e-5)


class TestSwitch:
    def test_switch_scalar(self):
        lr = layers.create_global_var(shape=[1], value=0.0, dtype="float32",
                                      persistable=True, name="lr")
        one = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
        two = layers.fill_constant(shape=[1], dtype="float32", value=2.0)
        step = layers.data(name="step", shape=[1],
                           append_batch_size=False)
        sw = layers.Switch()
        with sw.block():
            with sw.case(layers.less_than(step, one)):
                layers.assign(input=one, output=lr)
            with sw.default():
                layers.assign(input=two, output=lr)

        (v,) = _run({"step": np.asarray([0.5], "float32")}, [lr])
        assert float(v.reshape(())) == 1.0
        (v,) = _run({"step": np.asarray([5.0], "float32")}, [lr],
                    startup=False)
        assert float(v.reshape(())) == 2.0


class TestDynamicRNN:
    def _sent_feed(self):
        # 3 sequences of lengths 3, 2, 4; embedding dim 2
        lod = [[0, 3, 5, 9]]
        data = np.arange(18).reshape(9, 2).astype("float32") / 10.0
        return data, lod

    def test_drnn_accumulate(self):
        data, lod = self._sent_feed()
        sent = layers.data(name="sent", shape=[9, 2],
                           append_batch_size=False, lod_level=1)
        sent.stop_gradient = False
        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sent)
            prev = drnn.memory(shape=[2], value=0.0)
            s = layers.sums(input=[word, prev])
            drnn.update_memory(prev, s)
            drnn.output(s)
        out = drnn()
        last = layers.sequence_last_step(out)

        exe = fluid.Executor()
        (lastv,) = exe.run(fluid.default_main_program(),
                           feed={"sent": (data, lod)}, fetch_list=[last])
        # expected: per-sequence sum of word vectors
        expect = np.stack([data[0:3].sum(0), data[3:5].sum(0),
                           data[5:9].sum(0)])
        np.testing.assert_allclose(lastv, expect, rtol=1e-4)

    def test_drnn_train_grad(self):
        data, lod = self._sent_feed()
        sent = layers.data(name="sent", shape=[9, 2],
                           append_batch_size=False, lod_level=1)
        sent.stop_gradient = False
        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(sent)
            prev = drnn.memory(shape=[4], value=0.0)
            h = layers.fc(input=[word, prev], size=4, act="tanh")
            drnn.update_memory(prev, h)
            drnn.output(h)
        out = drnn()
        last = layers.sequence_last_step(out)
        loss = layers.reduce_mean(last)
        params = fluid.append_backward(loss)
        assert params, "no param grads generated through DynamicRNN"

        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        fetches = [loss] + [g.name for _, g in params]
        res = exe.run(fluid.default_main_program(),
                      feed={"sent": (data, lod)}, fetch_list=fetches)
        assert np.isfinite(res[0]).all()
        grad_mag = sum(float(np.abs(g).sum()) for g in res[1:])
        assert grad_mag > 0


def test_reorder_lod_tensor_by_rank_ragged():
    """Regression (r4): reordering a RAGGED tensor by rank table must move
    whole sub-sequences, not index rows by sequence id."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data(name="x", shape=[-1, 1], dtype="float32",
                        append_batch_size=False, lod_level=1)
        y = layers.data(name="y", shape=[-1, 1], dtype="float32",
                        append_batch_size=False, lod_level=1)
        table = layers.lod_rank_table(y)
        out = layers.reorder_lod_tensor_by_rank(x, table)
    exe = fluid.Executor()
    exe.run(startup)
    # y lengths [1, 3, 2] -> rank order (desc length): seq1, seq2, seq0
    yv = np.zeros((6, 1), "f")
    y_lod = [[0, 1, 4, 6]]
    xv = np.arange(6, dtype="f").reshape(6, 1)  # same lod as y
    (ov,) = exe.run(main, feed={"x": (xv, y_lod), "y": (yv, y_lod)},
                    fetch_list=[out.name])
    np.testing.assert_allclose(np.asarray(ov).reshape(-1),
                               [1, 2, 3, 4, 5, 0])


class TestNestedBoundedWhile:
    def test_nested_loops_with_slack_bounds(self):
        """r5 regression: both loops lower to bounded scans with a trip
        bound LARGER than the real trip count (max_iters attr).  The
        outer loop's post-termination iterations run with a frozen
        carry, which keeps the INNER loop's condition True by design —
        that must gate TensorArray writes row-wise (no whole-buffer
        merge) and must NOT trip the inner loop's exhaustion check."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            i = layers.zeros(shape=[1], dtype="int32")
            i.stop_gradient = True
            n_outer = layers.fill_constant(shape=[1], dtype="int32",
                                           value=3)
            n_outer.stop_gradient = True
            acc = layers.zeros(shape=[4], dtype="float32")
            cond = layers.less_than(x=i, y=n_outer)
            w = layers.While(cond=cond)
            with w.block():
                j = layers.zeros(shape=[1], dtype="int32")
                j.stop_gradient = True
                n_inner = layers.fill_constant(shape=[1], dtype="int32",
                                               value=2)
                n_inner.stop_gradient = True
                icond = layers.less_than(x=j, y=n_inner)
                iw = layers.While(cond=icond)
                with iw.block():
                    acc2 = acc + 1.0
                    layers.assign(acc2, output=acc)
                    j2 = layers.increment(x=j, in_place=True)
                    layers.less_than(x=j2, y=n_inner, cond=icond)
                i2 = layers.increment(x=i, in_place=True)
                layers.less_than(x=i2, y=n_outer, cond=cond)
            out = layers.reduce_sum(acc)
        # slack bounds: both loops run as bounded scans past termination
        for blk in main.blocks:
            for op in blk.ops:
                if op.type == "while":
                    op.attrs["max_iters"] = 7
        exe = fluid.Executor()
        exe.run(startup)
        (o,) = exe.run(main, feed={}, fetch_list=[out.name])
        # 3 outer x 2 inner increments of a 4-vector summed: 3*2*4
        np.testing.assert_allclose(float(np.asarray(o).reshape(())),
                                   24.0, rtol=1e-6)
