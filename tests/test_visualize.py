"""Program-visualization tests (``paddle_tpu.analysis.visualize``):
whole-Program DOT rendering with sub-block clusters, donation and
creation-site annotations, the typo'd ``paddle_tpu.debuger`` shim, and
the ``paddle_tpu lint --dot`` CLI exposure."""

import os
import sys
import warnings

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import visualize


def _train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8], dtype="float32",
                        append_batch_size=False)
        h = layers.fc(x, 4, act="relu")
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, ["x"], [loss.name]


class TestProgramDot:
    def test_renders_ops_vars_and_grads(self, tmp_path):
        main, feeds, fetches = _train_program()
        path = str(tmp_path / "p.dot")
        dot = visualize.program_dot(main, path=path)
        assert dot.startswith("digraph Program {")
        assert dot.rstrip().endswith("}")
        assert "mul" in dot and "_AT_GRAD" in dot
        assert "fillcolor=orange" in dot          # gradient vars
        assert os.path.exists(path)
        # every op carries its creation site as a tooltip pointing at
        # the user code that appended it (this file)
        assert 'tooltip="' in dot
        assert "test_visualize.py" in dot

    def test_donation_plan_annotations(self):
        from paddle_tpu.memory_optimization_transpiler import \
            plan_donation
        main, feeds, fetches = _train_program()
        plan = plan_donation(main, feed_names=feeds,
                             fetch_names=fetches)
        dot = visualize.program_dot(main)
        d = plan.to_dict()
        assert d["inplace_updates"], "sgd should update params in place"
        assert "[in-place @ op" in dot
        assert "peripheries=2" in dot

    def test_sub_blocks_render_as_clusters(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[1], dtype="float32",
                            append_batch_size=False)
            limit = layers.fill_constant([1], "float32", 3.0)
            cond = layers.less_than(x, limit)
            w = layers.While(cond=cond)
            with w.block():
                nxt = layers.increment(x, in_place=True)
                layers.less_than(nxt, limit, cond=cond)
        dot = visualize.program_dot(main)
        assert "subgraph cluster_b1" in dot
        assert "style=dotted" in dot    # parent-op -> sub-block edge

    def test_highlights_and_block_graph(self, tmp_path):
        main, _, fetches = _train_program()
        dot = visualize.draw_block_graphviz(
            main.global_block(), highlights=fetches, path=None)
        assert dot.startswith("digraph G {")
        assert "fillcolor=red" in dot

    def test_pprint(self):
        main, _, _ = _train_program()
        code = visualize.pprint_program_codes(main)
        assert "# block 0" in code and "mul(" in code
        fwd = visualize.pprint_block_codes(main.global_block(),
                                           show_backward=False)
        assert "_grad" not in fwd


class TestDebugerShim:
    def test_shim_warns_and_reexports(self):
        sys.modules.pop("paddle_tpu.debuger", None)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            from paddle_tpu import debuger
        assert any(issubclass(w.category, DeprecationWarning)
                   for w in caught)
        assert debuger.draw_block_graphviz is \
            visualize.draw_block_graphviz
        assert debuger.pprint_program_codes is \
            visualize.pprint_program_codes

    def test_package_import_does_not_warn(self):
        # the lazy __getattr__ keeps `import paddle_tpu` silent; only
        # touching the deprecated name pays the warning
        import subprocess
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=repo_root + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        r = subprocess.run(
            [sys.executable, "-W", "error::DeprecationWarning",
             "-c", "import paddle_tpu"],
            cwd=repo_root, env=env, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]


class TestLintDotCLI:
    def test_lint_dot_writes_graph(self, tmp_path, capsys):
        from paddle_tpu.cli import main as cli_main
        out = str(tmp_path / "mnist.dot")
        rc = cli_main(["lint", "--zoo", "mnist", "--dot", out])
        assert rc == 0
        text = open(out).read()
        assert text.startswith("digraph Program {")
        assert "conv2d" in text

    def test_lint_dot_requires_single_main_program(self, tmp_path,
                                                   capsys):
        from paddle_tpu.cli import main as cli_main
        rc = cli_main(["lint", "--zoo", "all",
                       "--dot", str(tmp_path / "x.dot")])
        assert rc == 2
        assert "exactly one main program" in capsys.readouterr().err
