"""linear_chain_crf / crf_decoding / warpctc / edit_distance tests
(reference test_linear_chain_crf_op.py, test_warpctc_op.py,
test_edit_distance_op.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.layer_helper import LayerHelper


LOD = [[0, 3, 5, 9]]
N, K = 9, 4


def _crf_brute_force(emission, transition, labels, lod):
    """Enumerate all paths for tiny sequences."""
    import itertools
    start, stop, trans = transition[0], transition[1], transition[2:]
    nlls = []
    for s, e in zip(lod[0][:-1], lod[0][1:]):
        em = emission[s:e]
        lab = labels[s:e]
        T = e - s

        def score(path):
            sc = start[path[0]] + em[0, path[0]]
            for t in range(1, T):
                sc += trans[path[t - 1], path[t]] + em[t, path[t]]
            return sc + stop[path[-1]]

        z = np.logaddexp.reduce(
            [score(p) for p in itertools.product(range(K), repeat=T)])
        nlls.append(z - score(list(lab)))
    return np.asarray(nlls, "float32")


class TestLinearChainCRF:
    def test_nll_matches_brute_force(self):
        rng = np.random.RandomState(0)
        em = rng.randn(N, K).astype("float32")
        trans = rng.randn(K + 2, K).astype("float32") * 0.5
        lab = rng.randint(0, K, size=(N, 1)).astype("int64")

        x = layers.data(name="em", shape=[N, K], append_batch_size=False,
                        lod_level=1)
        t = layers.data(name="trans", shape=[K + 2, K],
                        append_batch_size=False)
        y = layers.data(name="lab", shape=[N, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
        helper = LayerHelper("linear_chain_crf")
        nll = helper.create_tmp_variable("float32")
        helper.append_op(
            type="linear_chain_crf",
            inputs={"Emission": [x], "Transition": [t], "Label": [y]},
            outputs={"LogLikelihood": [nll]})
        exe = fluid.Executor()
        (out,) = exe.run(feed={"em": (em, LOD), "trans": trans,
                               "lab": (lab, LOD)}, fetch_list=[nll])
        expect = _crf_brute_force(em, trans, lab.reshape(-1), LOD)
        np.testing.assert_allclose(out.reshape(-1), expect, rtol=1e-4)

    def test_viterbi_decode(self):
        rng = np.random.RandomState(1)
        em = rng.randn(N, K).astype("float32")
        trans = rng.randn(K + 2, K).astype("float32") * 0.5
        x = layers.data(name="em", shape=[N, K], append_batch_size=False,
                        lod_level=1)
        t = layers.data(name="trans", shape=[K + 2, K],
                        append_batch_size=False)
        helper = LayerHelper("crf_decoding")
        path = helper.create_tmp_variable("int32")
        helper.append_op(type="crf_decoding",
                         inputs={"Emission": [x], "Transition": [t]},
                         outputs={"ViterbiPath": [path]})
        exe = fluid.Executor()
        (out,) = exe.run(feed={"em": (em, LOD), "trans": trans},
                         fetch_list=[path])
        # brute-force best path per sequence
        import itertools
        start, stop, tr = trans[0], trans[1], trans[2:]
        best = []
        for s, e in zip(LOD[0][:-1], LOD[0][1:]):
            T = e - s
            scores = {}
            for p in itertools.product(range(K), repeat=T):
                sc = start[p[0]] + em[s, p[0]]
                for i in range(1, T):
                    sc += tr[p[i - 1], p[i]] + em[s + i, p[i]]
                scores[p] = sc + stop[p[-1]]
            best.extend(max(scores, key=scores.get))
        np.testing.assert_array_equal(out.reshape(-1), best)


class TestCTC:
    def test_warpctc_matches_brute_force(self):
        # T=4 frames, C=3 classes (blank=0), label "1 2"
        rng = np.random.RandomState(2)
        T, C = 4, 3
        logits = rng.randn(T, C).astype("float32")
        labels = np.asarray([[1], [2]], "int64")

        x = layers.data(name="logits", shape=[T, C],
                        append_batch_size=False, lod_level=1)
        y = layers.data(name="lab", shape=[2, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
        helper = LayerHelper("warpctc")
        loss = helper.create_tmp_variable("float32")
        helper.append_op(type="warpctc",
                         inputs={"Logits": [x], "Label": [y]},
                         outputs={"Loss": [loss]}, attrs={"blank": 0})
        exe = fluid.Executor()
        (out,) = exe.run(feed={"logits": (logits, [[0, T]]),
                               "lab": (labels, [[0, 2]])},
                         fetch_list=[loss])

        # brute force: sum over all alignments that collapse to [1,2]
        import itertools
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        total = -np.inf
        for path in itertools.product(range(C), repeat=T):
            merged = [v for i, v in enumerate(path)
                      if (i == 0 or v != path[i - 1]) and v != 0]
            if merged == [1, 2]:
                total = np.logaddexp(
                    total, sum(logp[t, path[t]] for t in range(T)))
        np.testing.assert_allclose(float(out.reshape(-1)[0]), -total,
                                   rtol=1e-4)

    def test_ctc_grads(self):
        T, C = 5, 4
        rng = np.random.RandomState(3)
        logits = rng.randn(T, C).astype("float32")
        x = layers.data(name="logits", shape=[T, C],
                        append_batch_size=False, lod_level=1)
        x.stop_gradient = False
        y = layers.data(name="lab", shape=[2, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
        helper = LayerHelper("warpctc")
        loss = helper.create_tmp_variable("float32")
        helper.append_op(type="warpctc",
                         inputs={"Logits": [x], "Label": [y]},
                         outputs={"Loss": [loss]}, attrs={"blank": 0})
        total = layers.reduce_sum(loss)
        fluid.append_backward(total)
        exe = fluid.Executor()
        (g,) = exe.run(feed={"logits": (logits, [[0, T]]),
                             "lab": (np.asarray([[1], [2]], "int64"),
                                     [[0, 2]])},
                       fetch_list=["logits@GRAD"])
        assert g.shape == (T, C)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestEditDistance:
    def test_distance(self):
        hyp = np.asarray([[1], [2], [3], [4], [5]], "int64")
        ref = np.asarray([[1], [3], [3], [9]], "int64")
        h_lod = [[0, 3, 5]]
        r_lod = [[0, 2, 4]]
        x = layers.data(name="h", shape=[5, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
        y = layers.data(name="r", shape=[4, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
        helper = LayerHelper("edit_distance")
        out = helper.create_tmp_variable("float32")
        seq_num = helper.create_tmp_variable("int32")
        helper.append_op(type="edit_distance",
                         inputs={"Hyps": [x], "Refs": [y]},
                         outputs={"Out": [out],
                                  "SequenceNum": [seq_num]})
        exe = fluid.Executor()
        (d,) = exe.run(feed={"h": (hyp, h_lod), "r": (ref, r_lod)},
                       fetch_list=[out])
        # [1,2,3] vs [1,3]: distance 2 (sub 2->3? actually del 2 -> [1,3]) = 1
        # [4,5] vs [3,9]: 2 substitutions = 2
        np.testing.assert_allclose(d.reshape(-1), [1.0, 2.0])
