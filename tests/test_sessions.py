"""Resumable generative sessions: exactly-once token delivery across
mid-stream replica death (router-side re-prefill + splice), drain-time
checkpoint migration at token boundaries, client-side resume for
router-less deployments, and the resume-protocol schema — the ISSUE-20
failover stack end to end."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddle_tpu import profiler
from paddle_tpu.fault import chaos
from paddle_tpu.fleet import FleetRouter, SessionTable, \
    validate_checkpoint, validate_stream_event
from paddle_tpu.gen import GenPredictor, GenScheduler, \
    SchedulerDraining, StreamMigrated
from paddle_tpu.models import gen_lm
from paddle_tpu.serving import InferenceServer, ServingClient

import numpy as np


@pytest.fixture(scope="module")
def bundle_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("genlm_sess") / "bundle")
    gen_lm.export_gen_model(d, gen_lm.GenConfig(), num_slots=4)
    return d


@pytest.fixture(scope="module")
def predictor(bundle_dir):
    p = GenPredictor(bundle_dir)
    p.warmup()
    return p


def _server(bundle_dir, **kw):
    kw.setdefault("warmup", True)
    kw.setdefault("request_timeout", 30.0)
    server = InferenceServer(bundle_dir, port=0, **kw)
    server.start_background()
    assert server.wait_until_ready(180)
    return server


def _addr(server):
    return f"{server.addr[0]}:{server.addr[1]}"


def _ref_greedy(predictor, prompt, n):
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits, _ = predictor.prefill(seq)
        t = int(np.argmax(logits))
        out.append(t)
        seq.append(t)
    return out


def _read_stream(host, port, payload, headers=None, timeout=60):
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", "/generate", json.dumps(payload).encode(), hdrs)
    resp = conn.getresponse()
    if resp.status != 200:
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body, []
    events, stamps = [], []
    while True:
        line = resp.readline()
        if not line:
            break
        events.append(json.loads(line))
        stamps.append(time.monotonic())
        if events[-1].get("done"):
            break
    conn.close()
    return 200, events, stamps


def _counter(name):
    return profiler.runtime_metrics.counter(name)


# ---------------------------------------------------------------------------
# session table + resume-protocol schema (no bundle needed)
# ---------------------------------------------------------------------------

class TestSessionTable:
    def test_lru_eviction_counts_orphans(self):
        t = SessionTable(capacity=3)
        for i in range(5):
            t.begin(f"s{i}", f"r{i}", [1, 2, 3], 8)
        assert len(t) == 3
        assert t.orphaned == 2
        # the two OLDEST were evicted; the youngest three survive
        assert t.owner("s0") is None and t.owner("s1") is None
        assert t.owner("s4") == "r4"

    def test_begin_retouches_lru_order(self):
        t = SessionTable(capacity=2)
        t.begin("a", "r1", [1], 4)
        t.begin("b", "r1", [1], 4)
        t.begin("a", "r2", [1], 4, delivered=3)   # resume: re-touch
        t.begin("c", "r1", [1], 4)                # evicts b, not a
        assert t.owner("a") == "r2"
        assert t.owner("b") is None
        assert t.lookup("a")["delivered"] == 3

    def test_finish_evicts_without_orphan(self):
        t = SessionTable(capacity=8)
        t.begin("a", "r1", [1], 4)
        entry = t.finish("a")
        assert entry["done"] is True
        assert len(t) == 0 and t.orphaned == 0
        assert t.finish("a") is None

    def test_note_updates_owner_and_delivered(self):
        t = SessionTable()
        t.begin("a", "r1", [1, 2], 8)
        t.note("a", replica="r2", delivered=5)
        e = t.lookup("a")
        assert e["replica"] == "r2" and e["delivered"] == 5
        assert t.note("missing") is None

    def test_snapshot_shape(self):
        t = SessionTable(capacity=4)
        t.begin("a", "r1", [1, 2], 8, delivered=2)
        snap = t.snapshot()
        assert snap["count"] == 1 and snap["capacity"] == 4
        assert snap["sessions"][0]["sid"] == "a"
        assert snap["sessions"][0]["delivered"] == 2


class TestResumeProtocolSchema:
    def test_token_and_terminal_shapes_validate(self):
        assert validate_stream_event({"token": 3, "index": 0}) == []
        assert validate_stream_event(
            {"done": True, "finish_reason": "eos", "tokens": 4,
             "token_index": 4}) == []
        assert validate_stream_event(
            {"migrate": {"resume_from": 2, "remaining_tokens": 6},
             "done": True, "token_index": 2, "retryable": True}) == []

    def test_legacy_error_tail_still_parses(self):
        """Satellite regression: the OLD terminal error tail — no
        token_index, no top-level retryable — must keep validating, and
        the new tail with both fields must too."""
        legacy = {"error": {"type": "upstream_died", "message": "x"},
                  "done": True}
        new = {"error": {"type": "upstream_died", "message": "x"},
               "done": True, "token_index": 7, "retryable": True}
        assert validate_stream_event(legacy) == []
        assert validate_stream_event(new) == []

    def test_malformed_events_fail(self):
        assert validate_stream_event({"token": 3})
        assert validate_stream_event({"token": 3, "index": True})
        assert validate_stream_event({"done": True})
        assert validate_stream_event(
            {"migrate": {"resume_from": 2}, "done": True})  # !retryable
        assert validate_stream_event(
            {"error": {"type": "x"}, "done": True,
             "retryable": "yes"})

    def test_checkpoint_schema(self):
        good = {"prompt": [1, 2], "tokens": [3], "remaining_tokens": 4,
                "eos_id": None, "reason": "draining"}
        assert validate_checkpoint(good) == []
        assert validate_checkpoint({"prompt": [], "tokens": [],
                                    "remaining_tokens": 0,
                                    "reason": "draining"})
        assert validate_checkpoint({"prompt": [1], "tokens": [],
                                    "remaining_tokens": -1,
                                    "reason": "draining"})

    def test_router_finish_stream_tail_carries_new_fields(self):
        """The router's terminal error tail now includes the
        ``token_index`` high-water mark and a top-level ``retryable``
        flag, and the result round-trips the schema."""
        import io

        class _FakeHandler:
            def __init__(self):
                self.wfile = io.BytesIO()
                self.close_connection = False

        router = FleetRouter(replicas=["127.0.0.1:1"])
        router.start_background()
        try:
            fake = _FakeHandler()
            router._finish_stream(fake, error="owner died",
                                  etype="upstream_died",
                                  token_index=5, retryable=True)
            line = fake.wfile.getvalue().split(b"\r\n")[1]
            tail = json.loads(line)
            assert tail["token_index"] == 5
            assert tail["retryable"] is True
            assert tail["error"]["type"] == "upstream_died"
            assert validate_stream_event(tail) == []
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# client-side resume protocol against a scripted (model-free) server
# ---------------------------------------------------------------------------

def _tok(i):
    return {"token": 100 + i, "index": i}


def _done(n, reason="length"):
    return {"done": True, "finish_reason": reason, "tokens": n,
            "token_index": n}


def _scripted_server(scripts):
    """One scripted reply per expected request: stream ``events`` as
    ndjson chunks, then either end the chunked body cleanly or (with
    ``cut``) sever the socket mid-stream."""
    received = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n))
            received.append(req)
            spec = scripts[min(len(received) - 1, len(scripts) - 1)]
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for ev in spec["events"]:
                line = (json.dumps(ev) + "\n").encode()
                self.wfile.write(b"%x\r\n" % len(line) + line + b"\r\n")
                self.wfile.flush()
            self.close_connection = True
            if spec.get("cut"):
                self.connection.close()
                return
            self.wfile.write(b"0\r\n\r\n")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, received


class TestClientResumeProtocol:
    def _run(self, scripts, **gen_kw):
        srv, received = _scripted_server(scripts)
        try:
            client = ServingClient(f"{srv.server_address[0]}:"
                                   f"{srv.server_address[1]}")
            gen_kw.setdefault("max_new_tokens", 5)
            events = list(client.generate([1, 2], **gen_kw))
        finally:
            srv.shutdown()
            srv.server_close()
        return events, received

    def test_socket_cut_resumes_sequence_identical(self):
        """Acceptance: the socket dying after k events yields a
        client-visible sequence identical to an unbroken stream."""
        base = _counter("gen.session.resumes")
        events, received = self._run([
            {"events": [_tok(0), _tok(1), _tok(2)], "cut": True},
            {"events": [_tok(3), _tok(4), _done(5)]},
        ])
        toks = [e["token"] for e in events if "token" in e]
        assert toks == [100, 101, 102, 103, 104]
        assert events[-1]["done"] and events[-1]["finish_reason"] == \
            "length"
        assert not any(e.get("error") for e in events)
        assert _counter("gen.session.resumes") == base + 1
        # the resume request re-prefills prompt + delivered tokens
        assert len(received) == 2
        assert received[1]["prompt"] == [1, 2, 100, 101, 102]
        assert received[1]["resume_from"] == 3
        assert received[1]["max_new_tokens"] == 2
        assert received[1]["session_id"] == received[0]["session_id"]

    def test_duplicate_indices_are_dropped(self):
        """Exactly-once: replayed token_index events never reach the
        caller."""
        base = _counter("gen.session.dedup_drops")
        events, _ = self._run([
            {"events": [_tok(0), _tok(1), _tok(2), _tok(1), _tok(2),
                        _tok(3), _tok(4), _done(5)]},
        ])
        toks = [e["token"] for e in events if "token" in e]
        assert toks == [100, 101, 102, 103, 104]
        assert _counter("gen.session.dedup_drops") == base + 2

    def test_retryable_error_tail_resumes(self):
        events, received = self._run([
            {"events": [_tok(0), _tok(1),
                        {"error": {"type": "batcher_crashed",
                                   "message": "aborted"},
                         "done": True, "token_index": 2,
                         "retryable": True}]},
            {"events": [_tok(2), _tok(3), _tok(4), _done(5)]},
        ])
        toks = [e["token"] for e in events if "token" in e]
        assert toks == [100, 101, 102, 103, 104]
        assert not any(e.get("error") for e in events)
        assert received[1]["resume_from"] == 2

    def test_migrate_tail_resumes(self):
        events, received = self._run([
            {"events": [_tok(0),
                        {"migrate": {"resume_from": 1,
                                     "remaining_tokens": 4},
                         "done": True, "token_index": 1,
                         "retryable": True}]},
            {"events": [_tok(1), _tok(2), _tok(3), _tok(4), _done(5)]},
        ])
        toks = [e["token"] for e in events if "token" in e]
        assert toks == [100, 101, 102, 103, 104]
        assert received[1]["resume_from"] == 1

    def test_non_retryable_error_tail_surfaces_terminal(self):
        """The documented contract survives: a non-retryable mid-stream
        failure is a terminal error EVENT, not a resume and not an
        exception."""
        events, received = self._run([
            {"events": [_tok(0),
                        {"error": {"type": "bad_feed",
                                   "message": "nope"},
                         "done": True, "token_index": 1,
                         "retryable": False}]},
        ])
        assert len(received) == 1          # no resume attempted
        assert events[-1]["error"]["type"] == "bad_feed"
        assert events[-1]["done"]

    def test_legacy_error_tail_without_retryable_surfaces(self):
        """Old replicas end failed streams with an error tail carrying
        NEITHER token_index nor retryable — the client must surface it
        unchanged, not guess at a resume."""
        events, received = self._run([
            {"events": [_tok(0),
                        {"error": {"type": "upstream_died",
                                   "message": "legacy"},
                         "done": True}]},
        ])
        assert len(received) == 1
        assert events[-1]["error"]["type"] == "upstream_died"

    def test_resume_disabled_preserves_legacy_eof_behavior(self):
        """With resume off, a severed stream ends exactly as it always
        did — the delivered prefix, no reconnect, no synthesized
        events."""
        events, received = self._run([
            {"events": [_tok(0), _tok(1)], "cut": True},
        ], resume=False)
        assert len(received) == 1
        toks = [e["token"] for e in events if "token" in e]
        assert toks == [100, 101]
        assert not any(e.get("done") for e in events)


# ---------------------------------------------------------------------------
# router-side mid-stream failover (real bundle, chaos failpoints)
# ---------------------------------------------------------------------------

class TestRouterMidStreamFailover:
    def _fleet(self, bundle_dir, n=2):
        servers = [_server(bundle_dir) for _ in range(n)]
        router = FleetRouter(replicas=[_addr(s) for s in servers])
        router.start_background()
        return servers, router

    def test_kill_owner_mid_stream_token_identical(self, bundle_dir,
                                                   predictor):
        """Tentpole acceptance: the owner dies after its 4th produced
        token; the stream completes on a survivor token-identical to an
        unkilled reference — zero lost, zero duplicated."""
        servers, router = self._fleet(bundle_dir)
        chaos.inject("gen.decode.stall", delay=0.02)
        chaos.inject("gen.session.kill_owner", error=True, times=1,
                     after=3)
        resumes = _counter("gen.session.resumes")
        spliced = _counter("gen.session.spliced_tokens")
        try:
            status, events, _ = _read_stream(
                router.addr[0], router.addr[1],
                {"prompt": [2, 9], "max_new_tokens": 10})
            assert status == 200
            toks = [e["token"] for e in events if "token" in e]
            idxs = [e["index"] for e in events if "token" in e]
            assert idxs == list(range(10)), "lost or duplicated tokens"
            assert toks == _ref_greedy(predictor, [2, 9], 10)
            tail = events[-1]
            assert tail["done"] and tail["finish_reason"] == "length"
            assert tail["token_index"] == 10
            assert _counter("gen.session.resumes") == resumes + 1
            assert _counter("gen.session.spliced_tokens") == spliced + 7
            # terminal delivery evicted the session
            assert len(router.sessions) == 0
        finally:
            chaos.clear()
            router.shutdown()
            for s in servers:
                s.shutdown()

    def test_truncated_stream_resumes(self, bundle_dir, predictor):
        """A torn transport (chunk boundary tear, no replica death)
        rides the same resume path."""
        servers, router = self._fleet(bundle_dir)
        chaos.inject("gen.decode.stall", delay=0.02)
        chaos.inject("gen.stream.truncate", error=True, times=1,
                     after=2)
        try:
            status, events, _ = _read_stream(
                router.addr[0], router.addr[1],
                {"prompt": [5, 9, 3], "max_new_tokens": 8})
            assert status == 200
            toks = [e["token"] for e in events if "token" in e]
            idxs = [e["index"] for e in events if "token" in e]
            assert idxs == list(range(8))
            assert toks == _ref_greedy(predictor, [5, 9, 3], 8)
            assert events[-1]["finish_reason"] == "length"
        finally:
            chaos.clear()
            router.shutdown()
            for s in servers:
                s.shutdown()

    def test_replica_hard_kill_severs_and_resumes(self, bundle_dir,
                                                  predictor):
        """An in-process hard-kill (InferenceServer.abort_streams — the
        scheduler-thread stream abort a SIGKILL implies) surfaces as a
        retryable tail the router converts into a survivor resume."""
        servers, router = self._fleet(bundle_dir)
        chaos.inject("gen.decode.stall", delay=0.04)
        got = {}

        def consume():
            got["result"] = _read_stream(
                router.addr[0], router.addr[1],
                {"prompt": [7, 1], "max_new_tokens": 10})

        t = threading.Thread(target=consume)
        try:
            t.start()
            # wait until the router has relayed a few tokens, then
            # hard-kill the owning replica's streams
            deadline = time.monotonic() + 20
            owner = None
            while time.monotonic() < deadline:
                snap = router.sessions.snapshot()
                if snap["sessions"] and \
                        snap["sessions"][0]["delivered"] >= 2:
                    owner = snap["sessions"][0]["replica"]
                    break
                time.sleep(0.01)
            assert owner is not None, "stream never started"
            victim = next(s for s in servers if _addr(s) == owner)
            victim.abort_streams()
            t.join(timeout=60)
            assert not t.is_alive()
            status, events, _ = got["result"]
            assert status == 200
            toks = [e["token"] for e in events if "token" in e]
            idxs = [e["index"] for e in events if "token" in e]
            assert idxs == list(range(10))
            assert toks == _ref_greedy(predictor, [7, 1], 10)
            assert events[-1]["finish_reason"] == "length"
        finally:
            chaos.clear()
            t.join(timeout=5)
            router.shutdown()
            for s in servers:
                s.shutdown()


# ---------------------------------------------------------------------------
# drain-time migration (scheduler, server, and through the router)
# ---------------------------------------------------------------------------

class TestDrainMigration:
    def test_drain_waits_for_fast_streams(self, predictor):
        sched = GenScheduler(predictor, queue_size=8)
        try:
            s = sched.submit([5], max_new_tokens=3)
            ckpts = sched.drain(deadline_s=30.0)
            assert ckpts == []
            assert len(list(s)) == 3
            assert s.finish_reason == "length"
        finally:
            sched.close()

    def test_drain_rejects_new_sessions(self, predictor):
        sched = GenScheduler(predictor, queue_size=8)
        try:
            sched.drain(deadline_s=1.0)
            with pytest.raises(SchedulerDraining):
                sched.submit([1], max_new_tokens=2)
        finally:
            sched.close()

    def test_drain_deadline_checkpoints_slow_stream(self, predictor):
        """Satellite regression: a deliberately slow stream cannot pin
        the drain — on deadline expiry it is checkpointed at a token
        boundary, and the checkpoint resumes token-identically on a
        fresh scheduler."""
        migrations = _counter("gen.session.migrations")
        sched = GenScheduler(predictor, queue_size=8)
        chaos.inject("gen.decode.stall", delay=0.05)
        try:
            s = sched.submit([3, 4], max_new_tokens=12)
            assert s.next_event(timeout=30)[0] == "token"
            t0 = time.monotonic()
            ckpts = sched.drain(deadline_s=0.25)
            # bounded: nowhere near the 12 * 0.05s full run + margin
            assert time.monotonic() - t0 < 10.0
            assert len(ckpts) == 1
            ckpt = ckpts[0]
            assert validate_checkpoint(ckpt) == []
            assert ckpt["prompt"] == [3, 4]
            assert len(ckpt["tokens"]) + ckpt["remaining_tokens"] == 12
            assert 1 <= len(ckpt["tokens"]) < 12
            assert _counter("gen.session.migrations") == migrations + 1
            # the stream's consumer sees the hand-back, not an error
            with pytest.raises(StreamMigrated) as ei:
                for _ in s:
                    pass
            assert ei.value.checkpoint["prompt"] == [3, 4]
        finally:
            chaos.clear()
            sched.close()
        # resume the checkpoint on a survivor: token-identical to an
        # undrained reference (greedy decode is deterministic)
        survivor = GenScheduler(predictor, queue_size=8)
        try:
            cont = survivor.submit(ckpt["prompt"] + ckpt["tokens"],
                                   max_new_tokens=ckpt
                                   ["remaining_tokens"])
            full = ckpt["tokens"] + list(cont)
            assert full == _ref_greedy(predictor, [3, 4], 12)
        finally:
            survivor.close()

    def test_rolling_restart_through_router_completes_stream(
            self, bundle_dir, predictor):
        """Tentpole acceptance: draining the owner mid-stream hands the
        session back (migrate tail) and the router re-places it on the
        surviving replica — the client sees one complete, error-free,
        token-identical stream."""
        servers = [_server(bundle_dir) for _ in range(2)]
        router = FleetRouter(replicas=[_addr(s) for s in servers])
        router.start_background()
        chaos.inject("gen.decode.stall", delay=0.04)
        got = {}

        def consume():
            got["result"] = _read_stream(
                router.addr[0], router.addr[1],
                {"prompt": [2, 9], "max_new_tokens": 10})

        t = threading.Thread(target=consume)
        try:
            t.start()
            deadline = time.monotonic() + 20
            owner = None
            while time.monotonic() < deadline:
                snap = router.sessions.snapshot()
                if snap["sessions"] and \
                        snap["sessions"][0]["delivered"] >= 2:
                    owner = snap["sessions"][0]["replica"]
                    break
                time.sleep(0.01)
            assert owner is not None, "stream never started"
            victim = next(s for s in servers if _addr(s) == owner)
            # rolling restart: bound the drain so the active stream is
            # checkpoint-migrated instead of awaited
            ckpts = victim.drain_sessions(deadline_s=0.05)
            assert len(ckpts) == 1
            assert validate_checkpoint(ckpts[0]) == []
            # a draining replica refuses NEW sessions retryably
            host, port = victim.addr
            status, body, _ = _read_stream(
                host, port, {"prompt": [1], "max_new_tokens": 2})
            assert status == 503
            assert body["error"]["type"] == "draining"
            assert body["retryable"] is True
            t.join(timeout=60)
            assert not t.is_alive()
            status, events, _ = got["result"]
            assert status == 200
            toks = [e["token"] for e in events if "token" in e]
            idxs = [e["index"] for e in events if "token" in e]
            assert not any(e.get("error") for e in events)
            assert idxs == list(range(10))
            assert toks == _ref_greedy(predictor, [2, 9], 10)
        finally:
            chaos.clear()
            t.join(timeout=5)
            router.shutdown()
            for s in servers:
                s.shutdown()


# ---------------------------------------------------------------------------
# client resume against a real replica (router-less deployment)
# ---------------------------------------------------------------------------

class TestClientResumeIntegration:
    def test_client_resumes_after_stream_abort(self, bundle_dir,
                                               predictor):
        """Router-less failover: the replica's streams are hard-aborted
        mid-decode; ServingClient.generate re-prefills and the caller
        sees the unbroken sequence."""
        server = _server(bundle_dir)
        chaos.inject("gen.decode.stall", delay=0.04)
        try:
            client = ServingClient(_addr(server))
            it = client.generate([2, 9], max_new_tokens=10)
            events = []
            for ev in it:
                events.append(ev)
                if len([e for e in events if "token" in e]) == 3:
                    server.abort_streams()
            toks = [e["token"] for e in events if "token" in e]
            idxs = [e["index"] for e in events if "token" in e]
            assert idxs == list(range(10))
            assert toks == _ref_greedy(predictor, [2, 9], 10)
            assert events[-1]["done"]
            assert not any(e.get("error") for e in events)
        finally:
            chaos.clear()
            server.shutdown()
