"""CSP tests.

Part 1 ports the reference channel semantics suite
(``paddle/fluid/framework/channel_test.cc``, ~1k LoC) to pytest against
``paddle_tpu.channel.Channel``.  Part 2 mirrors the IR-level
``python/paddle/fluid/tests/test_concurrency.py`` flows (Go routines,
select, fibonacci) through the real Executor.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.channel import Channel, ChannelClosedError


# ===========================================================================
# Part 1: channel semantics (channel_test.cc ports)
# ===========================================================================

class TestChannelSemantics:
    def test_capacity(self):
        assert Channel(capacity=10).cap() == 10
        assert Channel().cap() == 0

    def test_sufficient_buffer_doesnt_block(self):
        # channel_test.cc SufficientBufferSizeDoesntBlock
        ch = Channel(capacity=10)
        for i in range(10):
            ch.send(i)          # must not block
        for i in range(10):
            v, ok = ch.receive()
            assert ok and v == i

    def test_send_on_closed_buffered_panics(self):
        # channel_test.cc SendReceiveClosedBufferedChannelPanics
        ch = Channel(capacity=1)
        ch.send(1)
        ch.close()
        with pytest.raises(ChannelClosedError):
            ch.send(2)

    def test_send_on_closed_unbuffered_panics(self):
        ch = Channel()
        ch.close()
        with pytest.raises(ChannelClosedError):
            ch.send(1)

    def test_residual_values_after_close(self):
        # channel_test.cc ReceiveFromBufferedChannelReturnResidualValuesTest
        ch = Channel(capacity=10)
        for i in range(10):
            ch.send(i)
        ch.close()
        for i in range(10):
            v, ok = ch.receive()  # residuals drain with ok=True
            assert ok and v == i
        for _ in range(2):
            v, ok = ch.receive()  # then closed-and-drained
            assert not ok

    def test_unbuffered_order_matches_send_order(self):
        # channel_test.cc RecevingOrderEqualToSendingOrderWithUnBufferedChannel
        ch = Channel()
        got = []

        def sender():
            for i in range(20):
                ch.send(i)

        t = threading.Thread(target=sender)
        t.start()
        for _ in range(20):
            v, ok = ch.receive()
            assert ok
            got.append(v)
        t.join()
        assert got == list(range(20))

    def test_buffered_order_matches_send_order(self):
        ch = Channel(capacity=3)
        got = []

        def sender():
            for i in range(50):
                ch.send(i)

        t = threading.Thread(target=sender)
        t.start()
        for _ in range(50):
            v, ok = ch.receive()
            assert ok
            got.append(v)
        t.join()
        assert got == list(range(50))

    def test_close_unblocks_receivers(self):
        # channel_test.cc {Buffered,Unbuffered}ChannelCloseUnblocksReceiversTest
        for cap in (0, 3):
            ch = Channel(capacity=cap)
            ended = [False] * 4

            def recv(i):
                v, ok = ch.receive()
                assert not ok
                ended[i] = True

            threads = [threading.Thread(target=recv, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            assert not any(ended)
            ch.close()
            for t in threads:
                t.join(timeout=5)
            assert all(ended)

    def test_close_unblocks_senders(self):
        # channel_test.cc {Buffered,Unbuffered}ChannelCloseUnblocksSendersTest
        for cap in (0, 2):
            ch = Channel(capacity=cap)
            if cap:
                for i in range(cap):
                    ch.send(i)  # fill the buffer
            results = [None] * 4

            def send(i):
                try:
                    ch.send(i)
                    results[i] = "sent"
                except ChannelClosedError:
                    results[i] = "closed"

            threads = [threading.Thread(target=send, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            assert all(r is None for r in results)  # all blocked
            ch.close()
            for t in threads:
                t.join(timeout=5)
            assert all(r == "closed" for r in results)

    def test_unbuffered_less_receive_more_send(self):
        # channel_test.cc UnbufferedLessReceiveMoreSendTest
        ch = Channel()
        sent = []

        def sender():
            for i in range(4):
                try:
                    ch.send(i)
                    sent.append(i)
                except ChannelClosedError:
                    return

        t = threading.Thread(target=sender)
        t.start()
        for i in range(3):
            v, ok = ch.receive()
            assert ok and v == i
        time.sleep(0.05)
        assert sent == [0, 1, 2]  # 4th send still blocked
        ch.close()
        t.join(timeout=5)

    def test_concurrent_send_sufficient_buffer(self):
        ch = Channel(capacity=10)
        threads = [threading.Thread(target=ch.send, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5)
        got = sorted(ch.receive()[0] for _ in range(10))
        assert got == list(range(10))


# ===========================================================================
# Part 2: IR-level concurrency flows (test_concurrency.py ports)
# ===========================================================================

def _int_tensor(name_hint, value=0, dtype="int64"):
    from paddle_tpu.framework import unique_name, default_main_program
    block = default_main_program().current_block()
    var = block.create_var(name=unique_name(name_hint), dtype=dtype)
    return var


class TestRoutineOp:
    def test_simple_routine(self):
        ch = fluid.make_channel(dtype="float64")
        result = _int_tensor("return_value", dtype="float64")

        with fluid.Go():
            input_value = layers.fill_constant(shape=[1], dtype="float64",
                                               value=1234)
            fluid.channel_send(ch, input_value)

        result, status = fluid.channel_recv(ch, result)
        fluid.channel_close(ch)

        exe = fluid.Executor(fluid.CPUPlace())
        outs = exe.run(fluid.default_main_program(), fetch_list=[result])
        assert float(np.asarray(outs[0]).reshape(-1)[0]) == 1234

    def test_daisy_chain(self):
        """Go daisy chain (talks.golang.org/2012/concurrency.slide#39),
        scaled down to n=20."""
        n = 20
        leftmost = fluid.make_channel(dtype="int64")
        left = leftmost
        for _ in range(n):
            right = fluid.make_channel(dtype="int64")
            with fluid.Go():
                one = layers.fill_constant(shape=[1], dtype="int64", value=1)
                result = _int_tensor("return_value")
                result, _ = fluid.channel_recv(right, result)
                one_added = layers.elementwise_add(x=one, y=result)
                fluid.channel_send(left, one_added)
            left = right

        with fluid.Go():
            one = layers.fill_constant(shape=[1], dtype="int64", value=1)
            fluid.channel_send(right, one)

        leftmost_result = _int_tensor("return_value")
        leftmost_result, _ = fluid.channel_recv(leftmost, leftmost_result)

        exe = fluid.Executor(fluid.CPUPlace())
        out = exe.run(fluid.default_main_program(),
                      fetch_list=[leftmost_result])
        assert int(np.asarray(out[0]).reshape(-1)[0]) == n + 1

    def test_select_buffered_send(self):
        ch1 = fluid.make_channel(dtype="float64", capacity=1)
        result1 = _int_tensor("return_value", dtype="float64")
        input_value = layers.fill_constant(shape=[1], dtype="float64",
                                           value=10)
        with fluid.Select() as select:
            with select.case(fluid.channel_send, ch1, input_value):
                pass
            with select.default():
                pass
        result1, status = fluid.channel_recv(ch1, result1)
        fluid.channel_close(ch1)
        exe = fluid.Executor(fluid.CPUPlace())
        out = exe.run(fluid.default_main_program(), fetch_list=[result1])
        assert float(np.asarray(out[0]).reshape(-1)[0]) == 10

    def test_fibonacci(self):
        """Go Fibonacci select example (tour.golang.org/concurrency/5)."""
        from paddle_tpu.framework import default_main_program
        block = default_main_program().current_block()

        def persistable(name, dtype="int32"):
            from paddle_tpu.framework import unique_name
            v = block.create_var(name=unique_name(name), dtype=dtype)
            v.persistable = True
            return v

        quit_input = persistable("quit_ch_input")
        layers.fill_constant(shape=[1], dtype="int32", value=0,
                             out=quit_input)
        result = persistable("result")
        layers.fill_constant(shape=[1], dtype="int32", value=0, out=result)

        x = layers.fill_constant(shape=[1], dtype="int32", value=0)
        y = layers.fill_constant(shape=[1], dtype="int32", value=1)
        while_cond = layers.fill_constant(shape=[1], dtype="bool", value=True)
        while_false = layers.fill_constant(shape=[1], dtype="bool",
                                           value=False)
        x_tmp = layers.fill_constant(shape=[1], dtype="int32", value=0)

        ch1 = fluid.make_channel(dtype="int32")
        quit_ch = fluid.make_channel(dtype="int32")

        with fluid.Go():
            for _ in range(10):
                fluid.channel_recv(ch1, result)
            fluid.channel_send(quit_ch, quit_input)

        while_op = layers.While(cond=while_cond)
        with while_op.block():
            result2 = layers.fill_constant(shape=[1], dtype="int32", value=0)
            with fluid.Select() as select:
                with select.case(fluid.channel_send, ch1, x, is_copy=True):
                    layers.assign(x, output=x_tmp)
                    layers.assign(y, output=x)
                    layers.assign(layers.elementwise_add(x=x_tmp, y=y),
                                  output=y)
                with select.case(fluid.channel_recv, quit_ch, result2):
                    layers.assign(while_false, output=while_cond)

        fluid.channel_close(ch1)
        fluid.channel_close(quit_ch)

        exe = fluid.Executor(fluid.CPUPlace())
        out = exe.run(fluid.default_main_program(), fetch_list=[result])
        assert int(np.asarray(out[0]).reshape(-1)[0]) == 34
