"""Continuous-batching generation runtime: prefill/decode KV-cache
equivalence, iteration-level admission into a running batch, streamed
chunked /generate over keep-alive HTTP (directly and through the
FleetRouter), warm-replica zero-compile first /generate, the
MicroBatcher-contract deadline/queue semantics at token granularity,
and the client-disconnect slot-reclamation drill."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.fault import chaos
from paddle_tpu.fleet import FleetRouter
from paddle_tpu.gen import GenPredictor, GenScheduler, is_gen_bundle
from paddle_tpu.models import gen_lm
from paddle_tpu.serving import InferenceServer, ServingClient


@pytest.fixture(scope="module")
def bundle_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("genlm") / "bundle")
    gen_lm.export_gen_model(d, gen_lm.GenConfig(), num_slots=4)
    return d


@pytest.fixture(scope="module")
def predictor(bundle_dir):
    p = GenPredictor(bundle_dir)
    p.warmup()
    return p


@pytest.fixture()
def scheduler(predictor):
    s = GenScheduler(predictor, queue_size=8)
    yield s
    s.close()


def _server(bundle_dir, **kw):
    kw.setdefault("warmup", True)
    kw.setdefault("request_timeout", 30.0)
    server = InferenceServer(bundle_dir, port=0, **kw)
    server.start_background()
    assert server.wait_until_ready(180)
    return server


def _ref_greedy(predictor, prompt, n):
    """Reference decode: re-run the (cache-free) prefill over the
    growing sequence — what the KV-cached path must reproduce."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits, _ = predictor.prefill(seq)
        t = int(np.argmax(logits))
        out.append(t)
        seq.append(t)
    return out


class TestBundle:
    def test_bundle_detection(self, bundle_dir, tmp_path):
        assert is_gen_bundle(bundle_dir)
        assert not is_gen_bundle(str(tmp_path))

    def test_warmup_idempotent(self, predictor):
        # module fixture already warmed: everything must be cached
        assert predictor.warmup() == 0


class TestKVCacheEquivalence:
    def test_cached_decode_matches_reference(self, predictor, scheduler):
        """Greedy decode through the slot cache must produce EXACTLY the
        tokens the cache-free reference (full re-prefill per step)
        produces — the KV cache is an optimization, not a model."""
        prompt = [5, 9, 3, 17]
        stream = scheduler.submit(prompt, max_new_tokens=7)
        got = list(stream)
        assert stream.finish_reason == "length"
        assert got == _ref_greedy(predictor, prompt, 7)

    def test_interleaved_requests_do_not_corrupt_each_other(
            self, predictor, scheduler):
        """Two concurrent generations share the decode batch but not
        state: each must still match its own isolated reference."""
        pa, pb = [2, 11, 29], [40, 7]
        sa = scheduler.submit(pa, max_new_tokens=6)
        sb = scheduler.submit(pb, max_new_tokens=6)
        got_a, got_b = list(sa), list(sb)
        assert got_a == _ref_greedy(predictor, pa, 6)
        assert got_b == _ref_greedy(predictor, pb, 6)

    def test_slot_reuse_after_eviction_is_clean(self, predictor,
                                                scheduler):
        """A slot freed by a finished request must serve the next
        request without stale-cache bleed-through."""
        want = _ref_greedy(predictor, [8, 8, 8], 5)
        for _ in range(3):   # cycles through (and re-uses) slots
            s = scheduler.submit([8, 8, 8], max_new_tokens=5)
            assert list(s) == want

    def test_eos_override_stops_early_and_frees_slot(self, predictor,
                                                     scheduler):
        ref = _ref_greedy(predictor, [5, 9, 3], 6)
        evb = profiler.runtime_metrics.counter("gen.evictions")
        s = scheduler.submit([5, 9, 3], max_new_tokens=6, eos_id=ref[1])
        assert list(s) == ref[:2]
        assert s.finish_reason == "eos"
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and scheduler.active_slots:
            time.sleep(0.02)
        assert scheduler.active_slots == 0
        assert profiler.runtime_metrics.counter("gen.evictions") > evb


class TestIterationLevelScheduling:
    def test_admission_into_running_batch(self, scheduler):
        """The headline capability: a short request submitted while a
        long generation is mid-flight gets its first token IMMEDIATELY
        (admitted between decode steps), not after the long one ends."""
        chaos.inject("gen.decode.stall", delay=0.05)
        try:
            long_s = scheduler.submit([7, 8], max_new_tokens=40)
            assert long_s.next_event(timeout=30)[0] == "token"
            short_s = scheduler.submit([2, 4], max_new_tokens=2)
            ev = short_s.next_event(timeout=30)
            assert ev is not None and ev[0] == "token"
            # the long request is still decoding — we did not queue
            # behind it
            assert long_s.finish_reason is None
            list(short_s)
            assert short_s.finish_reason is not None
            assert long_s.finish_reason is None
        finally:
            chaos.clear()
            long_s.cancel()
            list(long_s)

    def test_batch_admission_queues_behind_running_batch(self,
                                                         predictor):
        """admission='batch' is the PR 2 request-level baseline: a new
        request waits for the WHOLE running batch to finish."""
        sched = GenScheduler(predictor, queue_size=8, admission="batch")
        chaos.inject("gen.decode.stall", delay=0.03)
        try:
            first = sched.submit([3, 3], max_new_tokens=10)
            assert first.next_event(timeout=30)[0] == "token"
            # a SECOND token means decode iterations began — the batch
            # assembly window is over, so the late arrival cannot ride
            # this batch
            assert first.next_event(timeout=30)[0] == "token"
            late = sched.submit([4, 4], max_new_tokens=2)
            ev = late.next_event(timeout=30)
            # by the time the late request produced its first token the
            # batch it had to wait for has fully finished
            assert ev is not None and ev[0] == "token"
            assert first.finish_reason is not None
            list(late)
        finally:
            chaos.clear()
            sched.close()

    def test_queue_full_sheds_503_class(self, predictor):
        from paddle_tpu.serving import QueueFull
        sched = GenScheduler(predictor, queue_size=1)
        chaos.inject("gen.decode.stall", delay=0.05)
        busy = []
        try:
            # 4 slots busy + 1 queued: the next submit must shed.
            # queue_size=1 admits one request per decode iteration, so
            # wait for each admission before submitting the next
            for i in range(4):
                busy.append(sched.submit([1 + i], max_new_tokens=50))
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and \
                        sched.active_slots < i + 1:
                    time.sleep(0.02)
            assert sched.active_slots == 4
            busy.append(sched.submit([5], max_new_tokens=50))
            rej = profiler.runtime_metrics.counter(
                "gen.queue_rejections")
            with pytest.raises(QueueFull):
                sched.submit([9], max_new_tokens=2)
            assert profiler.runtime_metrics.counter(
                "gen.queue_rejections") == rej + 1
        finally:
            chaos.clear()
            for b in busy:
                b.cancel()
            sched.close()

    def test_expired_deadline_while_queued_gets_immediate_504(
            self, predictor):
        """The MicroBatcher deadline contract at admission granularity
        (mirroring Predictor.run_many's batched-dispatch timeout): a
        request whose X-Deadline-Ms budget expires while still QUEUED
        fails with DeadlineExceeded — it never takes a KV slot — and
        gen.expired counts it."""
        from paddle_tpu.serving import DeadlineExceeded
        sched = GenScheduler(predictor, queue_size=8)
        chaos.inject("gen.decode.stall", delay=0.05)
        try:
            blockers = [sched.submit([1 + i], max_new_tokens=50)
                        for i in range(4)]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    sched.active_slots < 4:
                time.sleep(0.02)
            expired = profiler.runtime_metrics.counter("gen.expired")
            adm = profiler.runtime_metrics.counter("gen.admissions")
            q = sched.submit([9], max_new_tokens=5, deadline=0.05)
            ev = q.next_event(timeout=10)
            assert ev[0] == "error" and \
                isinstance(ev[1], DeadlineExceeded)
            assert profiler.runtime_metrics.counter(
                "gen.expired") == expired + 1
            # not admitted: no slot was ever taken for it
            assert profiler.runtime_metrics.counter(
                "gen.admissions") == adm
        finally:
            chaos.clear()
            for b in blockers:
                b.cancel()
            sched.close()


def _read_stream(host, port, payload, headers=None, timeout=60):
    """Stream /generate with http.client, returning the parsed events
    AND each event's arrival time (the incrementality evidence)."""
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    conn.request("POST", "/generate", json.dumps(payload).encode(), hdrs)
    resp = conn.getresponse()
    if resp.status != 200:
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body, []
    events, stamps = [], []
    while True:
        line = resp.readline()
        if not line:
            break
        events.append(json.loads(line))
        stamps.append(time.monotonic())
        if events[-1].get("done"):
            break
    conn.close()
    return 200, events, stamps


class TestServingGenerate:
    @pytest.fixture(scope="class")
    def server(self, bundle_dir):
        server = _server(bundle_dir)
        yield server
        server.shutdown()

    def test_warm_replica_first_generate_compiles_nothing(
            self, bundle_dir):
        """Acceptance: warmup declared BOTH signature families (every
        prefill bucket + the decode step) before /readyz — the first
        real /generate triggers no fresh lowering/compile."""
        server = _server(bundle_dir)
        try:
            host, port = server.addr
            misses = profiler.runtime_metrics.counter("jit_cache.misses")
            status, events, _ = _read_stream(
                host, port, {"prompt": [3, 5, 7], "max_new_tokens": 5})
            assert status == 200
            assert sum(1 for e in events if "token" in e) == 5
            assert profiler.runtime_metrics.counter(
                "jit_cache.misses") == misses, \
                "first /generate paid a cold compile on a warm replica"
        finally:
            server.shutdown()

    def test_stream_chunks_arrive_incrementally(self, server):
        """First chunk must land while the server is still decoding —
        chunked transfer, not a buffered body."""
        host, port = server.addr
        chaos.inject("gen.decode.stall", delay=0.06)
        try:
            t0 = time.monotonic()
            status, events, stamps = _read_stream(
                host, port, {"prompt": [2, 9], "max_new_tokens": 10})
        finally:
            chaos.clear()
        assert status == 200
        assert events[-1]["done"] and \
            events[-1]["finish_reason"] == "length"
        t_first, t_last = stamps[0] - t0, stamps[-1] - t0
        assert t_first < t_last / 2, (t_first, t_last)

    def test_generate_matches_scheduler_output(self, server, predictor):
        host, port = server.addr
        status, events, _ = _read_stream(
            host, port, {"prompt": [5, 9, 3, 17], "max_new_tokens": 6})
        assert status == 200
        toks = [e["token"] for e in events if "token" in e]
        assert toks == _ref_greedy(predictor, [5, 9, 3, 17], 6)

    def test_buffered_mode(self, server, predictor):
        host, port = server.addr
        status, events, _ = _read_stream(
            host, port, {"prompt": [5, 9, 3], "max_new_tokens": 4,
                         "stream": False})
        assert status == 200
        assert events[-1]["tokens"] == _ref_greedy(predictor,
                                                   [5, 9, 3], 4)

    def test_client_disconnect_reclaims_slot(self, server):
        """Satellite drill: a streaming client dropping mid-generation
        (gen.client.disconnect failpoint) frees its KV slot, stops its
        decode work, and must not crash the decode loop — the next
        request is served normally."""
        host, port = server.addr
        dis = profiler.runtime_metrics.counter("gen.disconnects")
        chaos.inject("gen.client.disconnect", error=True, after=1,
                     times=1)
        chaos.inject("gen.decode.stall", delay=0.02)
        try:
            status, events, _ = _read_stream(
                host, port, {"prompt": [4, 4], "max_new_tokens": 40})
        except Exception:
            pass   # a torn chunked body is a legal client-side outcome
        finally:
            chaos.clear()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                server._gen.active_slots > 0:
            time.sleep(0.05)
        assert server._gen.active_slots == 0, "KV slot leaked"
        # paged pool: eviction must also return the slot's pages to
        # the free list, or disconnects slowly strand the pool
        gp = server.gen_predictor
        assert gp.free_pages == gp.num_pages, "KV pages leaked"
        assert profiler.runtime_metrics.counter(
            "gen.disconnects") == dis + 1
        # decode loop survived the closed socket
        status, events, _ = _read_stream(
            host, port, {"prompt": [3, 5, 7], "max_new_tokens": 3})
        assert status == 200
        assert sum(1 for e in events if "token" in e) == 3

    def test_expired_deadline_on_arrival_504(self, server):
        host, port = server.addr
        expired = profiler.runtime_metrics.counter("gen.expired")
        status, body, _ = _read_stream(
            host, port, {"prompt": [1], "max_new_tokens": 2},
            headers={"X-Deadline-Ms": "0"})
        assert status == 504
        assert body["error"]["type"] == "deadline_exceeded"
        assert body["retryable"] is True
        assert profiler.runtime_metrics.counter(
            "gen.expired") == expired + 1

    def test_deadline_expires_while_queued_504_over_http(self, server):
        """X-Deadline-Ms end to end: slots pinned by long generations,
        a tiny-budget request 504s without ever being admitted."""
        host, port = server.addr
        chaos.inject("gen.decode.stall", delay=0.05)
        # pin every slot deterministically via the scheduler itself
        holds = [server._gen.submit([1 + i], max_new_tokens=80)
                 for i in range(4)]
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    server._gen.active_slots < 4:
                time.sleep(0.02)
            assert server._gen.active_slots == 4
            expired = profiler.runtime_metrics.counter("gen.expired")
            status, body, _ = _read_stream(
                host, port, {"prompt": [9], "max_new_tokens": 5},
                headers={"X-Deadline-Ms": "60"})
            assert status == 504, body
            assert profiler.runtime_metrics.counter(
                "gen.expired") == expired + 1
        finally:
            chaos.clear()
            for h in holds:
                h.cancel()
            for h in holds:
                list(h)

    def test_predict_on_gen_bundle_404(self, server):
        host, port = server.addr
        req = urllib.request.Request(
            f"http://{host}:{port}/predict",
            data=json.dumps({"feeds": {"x": [[1.0]]}}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 404

    def test_bad_request_400(self, server):
        host, port = server.addr
        status, body, _ = _read_stream(
            host, port, {"prompt": [], "max_new_tokens": 2})
        assert status == 400
        status, body, _ = _read_stream(
            host, port, {"prompt": [10 ** 6], "max_new_tokens": 2})
        assert status == 400

    def test_stats_and_meta_report_gen_state(self, server):
        host, port = server.addr
        with urllib.request.urlopen(
                f"http://{host}:{port}/stats", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["server"]["gen"]["num_slots"] == 4
        assert snap["server"]["gen"]["admission"] == "continuous"
        with urllib.request.urlopen(
                f"http://{host}:{port}/meta", timeout=10) as r:
            meta = json.loads(r.read())
        assert meta["generate"] is True
        assert meta["max_len"] == 64


class TestFleetStreaming:
    def test_chunks_flow_incrementally_through_router(self, bundle_dir,
                                                      predictor):
        """Acceptance: the router forwards /generate chunks AS the
        replica produces them — the first chunk reaches the client
        before the generation completes, so TTFT survives the hop."""
        server = _server(bundle_dir)
        router = FleetRouter(
            replicas=[f"{server.addr[0]}:{server.addr[1]}"])
        router.start_background()
        chaos.inject("gen.decode.stall", delay=0.06)
        try:
            host, port = router.addr
            t0 = time.monotonic()
            status, events, stamps = _read_stream(
                host, port, {"prompt": [2, 9], "max_new_tokens": 10})
            assert status == 200
            toks = [e["token"] for e in events if "token" in e]
            assert toks == _ref_greedy(predictor, [2, 9], 10)
            t_first, t_last = stamps[0] - t0, stamps[-1] - t0
            assert t_first < t_last / 2, \
                f"router buffered the stream (ttft {t_first:.3f}s of " \
                f"{t_last:.3f}s total)"
        finally:
            chaos.clear()
            router.shutdown()
            server.shutdown()

    def test_serving_client_generate_through_router(self, bundle_dir,
                                                    predictor):
        server = _server(bundle_dir)
        router = FleetRouter(
            replicas=[f"{server.addr[0]}:{server.addr[1]}"])
        router.start_background()
        try:
            client = ServingClient(router.addr)
            events = list(client.generate([5, 9, 3], max_new_tokens=4))
            toks = [e["token"] for e in events if "token" in e]
            assert toks == _ref_greedy(predictor, [5, 9, 3], 4)
            assert events[-1]["done"]
        finally:
            router.shutdown()
            server.shutdown()

    def test_router_sheds_when_replica_queue_full(self, bundle_dir):
        """A replica 503 (generation queue full) surfaces through the
        router as a retryable shed, not a hang."""
        server = _server(bundle_dir, gen_queue_size=1)
        router = FleetRouter(
            replicas=[f"{server.addr[0]}:{server.addr[1]}"],
            retry=None, default_deadline=1.0)
        router.start_background()
        chaos.inject("gen.decode.stall", delay=0.08)
        holds = []
        try:
            # pin every slot AND the (size-1) admission queue; with
            # queue_size=1 each hold must be admitted before the next
            # submit fits the queue
            for i in range(4):
                holds.append(server._gen.submit([1 + i],
                                                max_new_tokens=80))
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and \
                        server._gen.active_slots < i + 1:
                    time.sleep(0.02)
            assert server._gen.active_slots == 4
            holds.append(server._gen.submit([5], max_new_tokens=80))
            assert server._gen.queue_depth == 1
            status, body, _ = _read_stream(
                router.addr[0], router.addr[1],
                {"prompt": [9], "max_new_tokens": 2})
            assert status in (503, 504), body
            assert body["retryable"] is True
        finally:
            chaos.clear()
            for h in holds:
                h.cancel()
            for h in holds:
                list(h)
            router.shutdown()
            server.shutdown()


class TestCLI:
    def test_generate_command_streams_tokens(self, bundle_dir,
                                             predictor, capsys):
        from paddle_tpu.cli import main as cli_main
        server = _server(bundle_dir)
        try:
            host, port = server.addr
            rc = cli_main(["generate", "--addr", f"{host}:{port}",
                           "--prompt", "5 9 3", "--max-new", "4"])
            assert rc == 0
            out = capsys.readouterr().out.strip().splitlines()
            want = _ref_greedy(predictor, [5, 9, 3], 4)
            assert [int(x) for x in out[:-1]] == want
            assert out[-1].startswith("# done")
        finally:
            server.shutdown()


class TestPagedKV:
    """Paged KV pool: equivalence against the dense baseline (plain and
    under PADDLE_TPU_OPT=1), page-allocator lifecycle, page reuse
    without stale reads, bucketed zero-recompile decode, and
    occupancy-proportional decode bytes."""

    @pytest.fixture(scope="class")
    def dense(self, tmp_path_factory):
        d = str(tmp_path_factory.mktemp("genlm_dense") / "bundle")
        gen_lm.export_gen_model(d, gen_lm.GenConfig(), num_slots=4,
                                paged=False)
        p = GenPredictor(d)
        p.warmup()
        return p

    def test_default_export_is_paged(self, predictor):
        assert predictor.paged
        assert predictor.meta["page_len"] == 16
        assert predictor.page_buckets[-1] == predictor.pages_per_slot

    def test_paged_matches_dense_baseline(self, predictor, scheduler,
                                          dense):
        """Token-identical across the LAYOUT change, not just against
        the re-prefill reference: dense pool and paged pool are the
        same model."""
        ds = GenScheduler(dense, queue_size=8)
        try:
            for prompt in ([5, 9, 3, 17], [2] * 20, [7] * 37):
                got = list(scheduler.submit(prompt, max_new_tokens=6))
                assert got == list(ds.submit(prompt, max_new_tokens=6))
                assert got == _ref_greedy(predictor, prompt, 6)
        finally:
            ds.close()

    def test_paged_equivalence_under_opt(self, bundle_dir, predictor,
                                         monkeypatch):
        """The optimization pipeline must not reorder the paged op's
        stateful cache writes: greedy tokens stay identical under
        PADDLE_TPU_OPT=1."""
        monkeypatch.setenv("PADDLE_TPU_OPT", "1")
        p = GenPredictor(bundle_dir)
        s = GenScheduler(p, queue_size=8)
        try:
            for prompt in ([5, 9, 3, 17], [6] * 21):
                got = list(s.submit(prompt, max_new_tokens=6))
                assert got == _ref_greedy(predictor, prompt, 6)
        finally:
            s.close()

    def test_page_allocator_lifecycle(self, bundle_dir):
        p = GenPredictor(bundle_dir)
        total = p.num_pages
        n = p.pages_needed(20, 5)          # ceil(25 / 16) = 2 pages
        assert n == 2
        p.alloc_slot_pages(0, n)
        assert p.free_pages == total - n
        with pytest.raises(ValueError):    # double-alloc is a bug
            p.alloc_slot_pages(0, 1)
        assert p.free_slot_pages(0) == n
        assert p.free_pages == total
        assert p.free_slot_pages(0) == 0   # idempotent (evict paths)

    def test_page_pool_exhaustion_raises_then_recovers(self, tmp_path):
        d = str(tmp_path / "b")
        gen_lm.export_gen_model(d, gen_lm.GenConfig(), num_slots=4,
                                num_pages=8)
        p = GenPredictor(d)
        p.alloc_slot_pages(0, 4)
        p.alloc_slot_pages(1, 4)
        with pytest.raises(RuntimeError):
            p.alloc_slot_pages(2, 1)
        p.free_slot_pages(0)
        p.alloc_slot_pages(2, 4)           # freed pages are reusable

    def test_evicted_pages_are_reused_clean(self, predictor, scheduler):
        """admit -> decode -> evict -> re-admit cycles the SAME pages
        through different requests; a stale read would break the
        re-prefill reference on later iterations."""
        total = predictor.num_pages
        long, short = [9] * 40, [8, 8, 8]
        want_long = _ref_greedy(predictor, long, 5)
        want_short = _ref_greedy(predictor, short, 5)
        for _ in range(3):
            assert list(scheduler.submit(long, max_new_tokens=5)) \
                == want_long
            assert list(scheduler.submit(short, max_new_tokens=5)) \
                == want_short
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                predictor.free_pages < total:
            time.sleep(0.02)
        assert predictor.free_pages == total, "pages leaked"

    def test_mixed_page_buckets_no_fresh_compiles(self, predictor,
                                                  scheduler):
        """A warmed replica serving lengths that span EVERY declared
        page bucket must never compile: each live page count maps onto
        a warmed bucket signature."""
        prompts = [[7] * 5, [9] * 20, [3] * 40, [11] * 50]
        refs = [_ref_greedy(predictor, p, 4) for p in prompts]
        misses = profiler.runtime_metrics.counter("jit_cache.misses")
        for prompt, ref in zip(prompts, refs):
            assert list(scheduler.submit(prompt, max_new_tokens=4)) \
                == ref
        assert profiler.runtime_metrics.counter("jit_cache.misses") \
            == misses, "paged decode compiled outside warmup"

    def test_decode_bytes_scale_with_page_bucket(self, predictor,
                                                 dense):
        """The deterministic tier-1 form of the bench_paged.py bytes
        acceptance: XLA cost-analysis bytes of the warmed decode
        executables grow with the fed page bucket, and the smallest
        bucket (25% of the pool here) reads <= 0.5x the dense decode
        step."""
        import re as _re
        from paddle_tpu.obs import perf
        paged_by_bucket, dense_bytes = {}, None
        for r in perf.records():
            m = _re.search(r"gen_page_table:4x(\d+)", r["label"])
            if m and r["bytes_accessed"]:
                paged_by_bucket[int(m.group(1))] = r["bytes_accessed"]
            elif "gen_attn_mask" in r["label"] and r["bytes_accessed"]:
                dense_bytes = r["bytes_accessed"]
        if not paged_by_bucket or dense_bytes is None:
            pytest.skip("backend reported no cost analysis")
        assert set(predictor.page_buckets) <= set(paged_by_bucket)
        full = paged_by_bucket[max(paged_by_bucket)]
        small = paged_by_bucket[min(paged_by_bucket)]
        assert small < full, "decode bytes do not scale with pages"
        assert small <= 0.5 * dense_bytes, (small, dense_bytes)
