"""CI guard: disabled tracing must cost <5% of the step loop.

The bench environment has 2 noisy vCPUs, so the guard does NOT race two
sleep loops against each other (sleep scheduling jitter under load is
tens of microseconds per step — the same order as the bound being
checked).  Instead the step is sleep-MODELED: a production step is
taken as 1 ms of device dispatch, the per-step cost of the disabled
instrumentation shell (the spans + latency series Executor.run /
run_pipeline wrap every step in) is measured directly over many
iterations, and the guard asserts shell < 5% of the modeled step.
That is the same contract — "instrumented loop <= 1.05x plain loop" —
with the noise term removed instead of averaged over."""

import time

from paddle_tpu.obs import numerics, perf, slo, trace
from paddle_tpu.obs.ledger import RunLedger
from paddle_tpu.profiler import RuntimeMetrics, record_latency

# the modeled production step: 1 ms of compiled dispatch (the serving
# fixture's tiny model dispatches in this order of magnitude; real
# training steps are larger, making the bound only easier)
STEP_SECONDS = 0.001
MAX_OVERHEAD_FRACTION = 0.05


def _shell_once(metrics, i, watchdog=None, perf_record=None,
                ledger=None, health=None):
    """The per-step instrumentation shell of Executor.run_pipeline +
    run AND the fleet-plane hooks the hot loops now carry: one step
    span, three phase spans, one latency series, the SLO tick the
    GenScheduler loop makes (a None check unarmed; one clock read
    armed-but-not-due), and the device-perf hooks every Executor.run
    now pays — the MFU note (a None check without a compile record; a
    division + one gauge write with one) and the HBM census tick (a
    None check unarmed; one clock read armed-but-not-due).  Federation
    adds NO per-step hook — it is pull-based, so with no scrape active
    its steady-state cost is exactly zero — which this shell
    demonstrates by containing nothing for it.  The training-health
    plane adds the run-ledger note (a None check unarmed; one buffered
    row append + gauge snapshot armed) and the sentinel's health-gauge
    writes (a None check unarmed; three gauge writes armed — the norms
    themselves ride the sentinel's already-paid device sync)."""
    with trace.span("train.step", step=i):
        with record_latency("obs_overhead.step_seconds",
                            metrics=metrics):
            with trace.span("executor.feed"):
                pass
            with trace.span("executor.dispatch"):
                pass
            with trace.span("executor.fetch"):
                pass
    slo.tick(watchdog)
    perf.note_step(perf_record, STEP_SECONDS, metrics=metrics)
    perf.census_tick()
    if ledger is not None:
        ledger.note_step(fetch_names=_FETCH_NAMES, fetches=_FETCHES)
    if health is not None:
        numerics.set_health_gauges(metrics, health)


_FETCH_NAMES = ("mean_0.tmp_0",)
_FETCHES = ([0.125],)


def _per_step_shell_seconds(metrics, iters=2000, watchdog=None,
                            perf_record=None, ledger=None, health=None):
    t0 = time.perf_counter()
    for i in range(iters):
        _shell_once(metrics, i, watchdog, perf_record, ledger, health)
    return (time.perf_counter() - t0) / iters


class TestDisabledTracingOverhead:
    def test_disabled_span_is_shared_noop(self):
        trace.disable()
        assert trace.span("a", x=1) is trace.span("b")

    def test_step_loop_overhead_under_5_percent(self):
        trace.disable()
        m = RuntimeMetrics()
        # best-of-5: a contended 2-vCPU runner inflates some rounds;
        # the minimum is the shell's true cost
        shell = min(_per_step_shell_seconds(m) for _ in range(5))
        budget = STEP_SECONDS * MAX_OVERHEAD_FRACTION
        assert shell <= budget, (
            f"disabled instrumentation shell costs {shell * 1e6:.1f}us "
            f"per step — over {MAX_OVERHEAD_FRACTION:.0%} of a "
            f"{STEP_SECONDS * 1e3:.0f}ms step ({budget * 1e6:.0f}us)")
        # the latency series keeps recording while spans are disabled
        assert m.snapshot()["series"][
            "obs_overhead.step_seconds"]["count"] == 5 * 2000

    def test_armed_slo_watchdog_stays_under_5_percent(self):
        """Satellite: the SLO evaluator's hot-loop hook with a REAL
        armed watchdog (interval not yet due — the steady state between
        evaluations) still fits the disabled-shell budget; PADDLE_TPU_
        TRACE=0 and no scrape active, so this is the whole fleet-plane
        cost a decode iteration pays."""
        trace.disable()
        m = RuntimeMetrics()
        wd = slo.SLOWatchdog(
            {"version": 1, "interval_seconds": 3600.0,
             "objectives": [{"name": "lat", "kind": "quantile",
                             "series": "obs_overhead.step_seconds",
                             "quantile": "p99", "max": 10.0}]},
            metrics=m)
        wd.evaluate()   # seed _last_eval: steady state = not-due path
        shell = min(_per_step_shell_seconds(m, watchdog=wd)
                    for _ in range(5))
        budget = STEP_SECONDS * MAX_OVERHEAD_FRACTION
        assert shell <= budget, (
            f"armed-SLO instrumentation shell costs "
            f"{shell * 1e6:.1f}us per step — over "
            f"{MAX_OVERHEAD_FRACTION:.0%} of a "
            f"{STEP_SECONDS * 1e3:.0f}ms step ({budget * 1e6:.0f}us)")
        # the not-due path really did skip evaluation (1 seed pass)
        assert wd.evaluations == 1

    def test_armed_perf_hooks_stay_under_5_percent(self):
        """Satellite: the device-perf hooks in their ARMED steady state
        — a live compile record (so every step derives the MFU gauge:
        one division + one locked gauge write) and an armed-but-not-due
        HBM census cadence (one clock read) — still fit the
        disabled-shell budget."""
        trace.disable()
        m = RuntimeMetrics()
        record = {"flops": 1e12, "steps": 0, "last_step_seconds": None,
                  "mfu": None}
        before = m.counter("hbm.census_runs")
        perf.arm_census(3600.0)
        try:
            perf.census_tick()   # burn the fresh-arm due tick
            shell = min(_per_step_shell_seconds(m, perf_record=record)
                        for _ in range(5))
        finally:
            perf.arm_census(None)
        budget = STEP_SECONDS * MAX_OVERHEAD_FRACTION
        assert shell <= budget, (
            f"armed perf-hook shell costs {shell * 1e6:.1f}us per step "
            f"— over {MAX_OVERHEAD_FRACTION:.0%} of a "
            f"{STEP_SECONDS * 1e3:.0f}ms step ({budget * 1e6:.0f}us)")
        # the MFU note really ran per step, the census never tripped
        assert record["steps"] == 5 * 2000
        assert m.gauge("train.mfu") is not None
        assert m.counter("hbm.census_runs") == before

    def test_armed_ledger_and_health_stay_under_5_percent(self):
        """Satellite: the training-health plane in its ARMED steady
        state — a real RunLedger appending one buffered row per step
        (flush_every amortizes the write; no per-row fsync) plus the
        sentinel's three health-gauge writes — still fits the
        disabled-shell budget.  Disabled, both hooks are a single
        None check, covered by the base shell test."""
        import tempfile

        trace.disable()
        m = RuntimeMetrics()
        with tempfile.TemporaryDirectory() as d:
            led = RunLedger(d + "/ledger", rotate_rows=100_000,
                            flush_every=64, metrics=m, install=False)
            health = {"param_norm": 3.0, "grad_norm": 0.01,
                      "update_ratio": 0.0033}
            try:
                shell = min(
                    _per_step_shell_seconds(m, ledger=led, health=health)
                    for _ in range(5))
            finally:
                led.close()
            budget = STEP_SECONDS * MAX_OVERHEAD_FRACTION
            assert shell <= budget, (
                f"armed ledger+health shell costs {shell * 1e6:.1f}us "
                f"per step — over {MAX_OVERHEAD_FRACTION:.0%} of a "
                f"{STEP_SECONDS * 1e3:.0f}ms step "
                f"({budget * 1e6:.0f}us)")
            # every step really appended a row and wrote the gauges
            assert led.rows_total == 5 * 2000
            assert m.gauge("train.grad_norm") == 0.01

    def test_enabled_tracing_records_bounded_spans(self):
        trace.enable(ring_size=256)
        trace.clear()
        m = RuntimeMetrics()
        for i in range(100):
            _shell_once(m, i)
        spans = trace.snapshot_spans()
        assert len(spans) == 256          # ring bound respected (4/step)
        assert {"train.step", "executor.feed", "executor.dispatch",
                "executor.fetch"} <= {s["name"] for s in spans}
        trace.clear()
        trace.disable()
