"""Registry cross-check: every chaos failpoint the runtime fires must
be documented in docs/fault_tolerance.md's failpoint registry table.

The scanner (modeled on test_obs_metric_registry.py) walks
``paddle_tpu/`` source for ``chaos.fire("name")`` / ``_chaos.fire(...)``
sites and fails naming any fired failpoint the doc table misses — so a
PR adding a failure boundary without documenting how to drill it fails
here, not during an incident."""

import os
import re

import paddle_tpu

SRC_ROOT = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
DOC = os.path.join(os.path.dirname(SRC_ROOT), "docs", "fault_tolerance.md")

# fire sites: chaos.fire("a.b", ...) / _chaos.fire('a.b.c'); \s* spans
# the line breaks black-style wrapping adds.  The dotted-name
# requirement keeps prose like chaos.fire("name") in the chaos module's
# own docstring out of the registry.
_FIRE = re.compile(
    r"\b_?chaos\.fire\(\s*\n?\s*[\"']"
    r"([a-z0-9_]+(?:\.[a-z0-9_]+)+)[\"']")


def _iter_sources():
    for dirpath, _, names in os.walk(SRC_ROOT):
        for n in sorted(names):
            if n.endswith(".py"):
                with open(os.path.join(dirpath, n)) as f:
                    yield os.path.join(dirpath, n), f.read()


def fired_failpoint_names():
    names = set()
    for path, text in _iter_sources():
        if os.path.relpath(path, SRC_ROOT) == os.path.join("fault",
                                                           "chaos.py"):
            continue  # the framework itself, not a fire site
        names.update(_FIRE.findall(text))
    return names


def documented_failpoint_names():
    with open(DOC) as f:
        doc = f.read()
    # registry rows are "| `name` | where ... |" in the failpoint table
    return set(re.findall(r"^\|\s*`([a-z0-9_.]+)`\s*\|", doc, flags=re.M))


class TestFailpointRegistry:
    def test_scanner_finds_known_fire_sites(self):
        """The scanner must keep seeing the load-bearing names — an
        over-tight regex silently passing the doc check is worse than a
        missing doc row."""
        fired = fired_failpoint_names()
        assert {"master.rpc", "ckpt.commit", "ckpt.restore",
                "reader.pump", "datapipe.source", "serving.run",
                "serving.batcher.crash", "sentinel.nan",
                "train.step"} <= fired

    def test_every_fired_failpoint_is_documented(self):
        fired = fired_failpoint_names()
        documented = documented_failpoint_names()
        assert documented, f"no failpoint table parsed from {DOC}"
        missing = sorted(fired - documented)
        assert not missing, (
            f"failpoints fired by the runtime but missing from the "
            f"docs/fault_tolerance.md registry table: {missing}")
