"""ResNet-50 benchmark harness tests (reference ``benchmark/fluid/resnet.py``
+ ``run.sh``): the analytic FLOP walker and an AMP training smoke of the
bench's exact program shape (tiny config, CPU)."""

import os
import sys

import numpy as np
import pytest

# repo root (for the bench modules), independent of checkout location
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_program_matmul_flops_counts_conv_and_fc():
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from bench_resnet import program_matmul_flops

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 3, 8, 8], dtype="float32",
                        append_batch_size=False)
        y = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                          bias_attr=False)  # out (2,4,8,8)
        flat = layers.reshape(y, shape=[2, 4 * 8 * 8])
        out = layers.fc(flat, size=5)
    flops = program_matmul_flops(main.global_block())
    conv = 2 * 2 * 8 * 8 * 4 * 3 * 3 * 3       # 2*N*Ho*Wo*Co*Ci*kh*kw
    fc = 2 * 2 * (4 * 8 * 8) * 5               # 2*M*K*N
    assert flops == conv + fc, (flops, conv, fc)


def test_resnet_amp_train_step_runs_and_learns():
    # the exact bench program (resnet_train_program + Momentum + amp) at
    # the bench's own CPU smoke config; guards the conv AMP path whose
    # preferred_element_type transpose mismatch broke bf16 training
    import paddle_tpu as fluid
    from paddle_tpu.models import resnet as R

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        cost, acc, feeds = R.resnet_train_program(
            4, class_dim=10, depth=18, image_shape=(3, 32, 32))
        fluid.optimizer.Momentum(learning_rate=0.01,
                                 momentum=0.9).minimize(cost)
    main.amp = True  # bf16 compute path even on CPU
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"image": rng.rand(4, 3, 32, 32).astype("float32"),
            "label": rng.randint(0, 10, size=(4, 1)).astype("int64")}
    losses = []
    for _ in range(6):
        (l,) = exe.run(main, feed=feed, fetch_list=[cost.name])
        losses.append(float(np.asarray(l).reshape(())))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
