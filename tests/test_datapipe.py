"""datapipe subsystem: stage composition, sharding, determinism,
checkpointable iterators, metrics, chaos failpoints, and the mid-epoch
kill -> checkpoint -> resume drill (identical sample sequence).

docs/data_pipeline.md is the companion narrative; the chaos-marked
subprocess drill follows the test_fault_injection.py idiom (CPU
platform, bounded timeouts — tier-1-safe)."""

import json
import os
import pickle
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.datapipe as dp
from paddle_tpu.fault import chaos
from paddle_tpu import profiler


@pytest.fixture(autouse=True)
def _clean_failpoints():
    chaos.clear()
    yield
    chaos.clear()


def id_samples(n):
    return [{"x": np.full((3,), i, np.float32),
             "y": np.array([i], np.int64)} for i in range(n)]


def ids_of(batches):
    return [b["y"][:, 0].tolist() for b in batches]


def flat_ids(batches):
    return [i for b in ids_of(batches) for i in b]


def std_pipe(samples, workers=2, seed=3):
    return (dp.InMemorySource(samples)
              .shuffle(8, seed=seed)
              .map(lambda s: {"x": s["x"] * 2, "y": s["y"]},
                   workers=workers)
              .batch(4, drop_last=True)
              .prefetch(depth=2))


class TestSources:
    def test_in_memory_epochs_and_len(self):
        src = dp.InMemorySource(list(range(7)))
        assert len(src) == 7
        assert list(src) == list(range(7))
        assert src.epoch == 1
        assert list(src) == list(range(7))  # next epoch, same stream
        assert src.epoch == 2

    def test_sharding_partitions_disjoint_and_complete(self):
        data = list(range(23))
        shards = [list(dp.InMemorySource(data, num_shards=4, shard_index=i))
                  for i in range(4)]
        assert sorted(x for s in shards for x in s) == data
        assert all(len(set(s)) == len(s) for s in shards)
        with pytest.raises(ValueError):
            dp.InMemorySource(data, num_shards=2, shard_index=2)

    def test_file_source_lines_and_parse(self, tmp_path):
        (tmp_path / "a.txt").write_text("1\n2\n")
        (tmp_path / "b.txt").write_text("3\n")
        src = dp.FileSource(str(tmp_path / "*.txt"), parse=int)
        assert list(src) == [1, 2, 3]
        with pytest.raises(FileNotFoundError):
            list(dp.FileSource(str(tmp_path / "*.nope")))

    def test_recordio_source_roundtrip(self, tmp_path):
        from paddle_tpu.recordio_writer import (
            convert_reader_to_recordio_file)
        path = str(tmp_path / "data.recordio")
        n = convert_reader_to_recordio_file(
            path, lambda: iter(range(10)))
        assert n == 10
        src = dp.RecordIOSource(path)
        assert list(src) == list(range(10))
        # sharded over records
        got = [list(dp.RecordIOSource(path, num_shards=2, shard_index=i))
               for i in range(2)]
        assert sorted(got[0] + got[1]) == list(range(10))

    def test_source_resume_skips_to_offset(self):
        src = dp.InMemorySource(list(range(10)))
        it = iter(src)
        assert [next(it) for _ in range(4)] == [0, 1, 2, 3]
        it.close()
        state = src.state_dict()
        fresh = dp.InMemorySource(list(range(10)))
        fresh.load_state_dict(state)
        assert list(fresh) == [4, 5, 6, 7, 8, 9]


class TestStages:
    def test_shuffle_multiset_and_seed_determinism(self):
        data = list(range(40))
        a = list(dp.InMemorySource(data).shuffle(8, seed=5))
        b = list(dp.InMemorySource(data).shuffle(8, seed=5))
        c = list(dp.InMemorySource(data).shuffle(8, seed=6))
        assert sorted(a) == data and a == b
        assert a != c  # different seed, different permutation
        assert a != data  # it actually shuffles

    def test_shuffle_epochs_differ_but_replay_identically(self):
        pipe = dp.InMemorySource(list(range(20))).shuffle(4, seed=1)
        e0, e1 = list(pipe), list(pipe)
        assert sorted(e0) == sorted(e1) and e0 != e1
        again = dp.InMemorySource(list(range(20))).shuffle(4, seed=1)
        assert [list(again), list(again)] == [e0, e1]

    def test_parallel_map_ordered_and_exceptions(self):
        out = list(dp.InMemorySource(list(range(50)))
                   .map(lambda x: x * 2, workers=3))
        assert out == [2 * i for i in range(50)]

        def boom(x):
            if x == 7:
                raise ValueError("boom")
            return x

        with pytest.raises(ValueError, match="boom"):
            list(dp.InMemorySource(list(range(20))).map(boom, workers=3))

    def test_map_workers_zero_is_synchronous(self):
        out = list(dp.InMemorySource(list(range(10))).map(lambda x: -x))
        assert out == [-i for i in range(10)]

    def test_batch_collate_and_partial(self):
        pipe = dp.InMemorySource(id_samples(10)).batch(4)
        batches = list(pipe)
        assert [b["x"].shape[0] for b in batches] == [4, 4, 2]
        assert flat_ids(batches) == list(range(10))
        pipe = dp.InMemorySource(id_samples(10)).batch(4, drop_last=True)
        assert [b["x"].shape[0] for b in pipe] == [4, 4]

    def test_batch_pad_to_bucket_stabilizes_tail_shape(self):
        pipe = dp.InMemorySource(id_samples(9)).batch(8,
                                                      pad_to_bucket=True)
        batches = list(pipe)
        # 9 = 8 + 1; the tail batch of 1 pads up to the bucket (8),
        # giving the jit cache one stable tail signature
        assert [b["x"].shape[0] for b in batches] == [8, 8]
        assert batches[1]["y"][1:, 0].tolist() == [0] * 7  # zero pad

    def test_tuple_samples_collate(self):
        data = [(np.float32(i), np.array([i], np.int64)) for i in range(6)]
        batches = list(dp.InMemorySource(data).batch(3))
        assert isinstance(batches[0], tuple)
        assert batches[0][0].shape == (3,)


class TestPrefetch:
    def test_prefetch_yields_device_arrays_in_order(self):
        import jax
        pipe = dp.InMemorySource(id_samples(12)).batch(4).prefetch(depth=2)
        batches = list(pipe)
        assert flat_ids(batches) == list(range(12))
        assert isinstance(batches[0]["x"], jax.Array)

    def test_prefetch_overlaps_producer(self):
        # producer latency is hidden behind consumer latency: with a
        # depth-2 queue, total time approaches max(sum(p), sum(c))
        # rather than sum(p) + sum(c)
        def slow(s):
            time.sleep(0.01)
            return s
        pipe = (dp.InMemorySource(id_samples(16))
                  .map(slow, workers=1).batch(4).prefetch(depth=2))
        t0 = time.perf_counter()
        for _ in pipe:
            time.sleep(0.01)  # consumer-side "compute"
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.33, elapsed  # serial would be ~0.2+0.04+eps

    def test_restored_pending_batches_are_device_placed(self):
        import jax
        pipe = dp.InMemorySource(id_samples(12)).batch(4).prefetch(depth=2)
        it = iter(pipe)
        next(it)
        it.close()                       # leaves batches queued/pending
        state = pickle.dumps(pipe.state_dict())
        fresh = dp.InMemorySource(id_samples(12)).batch(4) \
            .prefetch(depth=2)
        fresh.load_state_dict(pickle.loads(state))
        batches = list(fresh)
        # the first post-restore batch comes from the restored pending
        # buffer (host numpy in the pickle) — the stage must re-place it
        assert all(isinstance(b["x"], jax.Array) for b in batches)

    def test_abandoned_iterator_stops_threads_and_keeps_position(self):
        base = threading.active_count()
        pipe = std_pipe(id_samples(40))
        it = iter(pipe)
        first = next(it)
        it.close()
        deadline = time.time() + 5
        while threading.active_count() > base and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= base
        # the abandoned position is kept: continuing yields the rest
        rest = list(pipe)
        ref = list(std_pipe(id_samples(40)))
        assert ids_of([first]) + ids_of(rest) == ids_of(ref)


class TestStateDict:
    def test_mid_epoch_resume_exact_sequence(self):
        ref = list(std_pipe(id_samples(37)))
        pipe = std_pipe(id_samples(37))
        it = iter(pipe)
        first = [next(it) for _ in range(3)]
        it.close()
        blob = pickle.dumps(pipe.state_dict())
        fresh = std_pipe(id_samples(37))
        fresh.load_state_dict(pickle.loads(blob))
        rest = list(fresh)
        assert ids_of(first) + ids_of(rest) == ids_of(ref)

    def test_resume_across_epoch_boundary(self):
        pipe = std_pipe(id_samples(16))
        e0 = list(pipe)  # full epoch consumed; next iter = epoch 1
        state = pickle.dumps(pipe.state_dict())
        e1 = list(pipe)
        fresh = std_pipe(id_samples(16))
        fresh.load_state_dict(pickle.loads(state))
        assert ids_of(list(fresh)) == ids_of(e1)
        assert ids_of(e0) != ids_of(e1)

    def test_shape_mismatch_rejected(self):
        pipe = dp.InMemorySource(list(range(4))).batch(2)
        other = dp.InMemorySource(list(range(4))).shuffle(2)
        with pytest.raises(dp.PipelineStateError):
            other.load_state_dict(pipe.state_dict())

    def test_reset_rewinds_to_epoch_zero(self):
        pipe = std_pipe(id_samples(16))
        e0 = ids_of(list(pipe))
        _ = list(pipe)
        pipe.reset()
        assert ids_of(list(pipe)) == e0

    def test_per_step_state_dict_does_not_replay_source(self):
        """A checkpoint per step quiesces the chain; the source's live
        stream must survive that, not rebuild + re-skip O(offset)
        samples every step (quadratic re-reads on file corpora)."""
        reads = [0]

        class CountingSource(dp.Source):
            def _stream(self, epoch):
                for i in range(60):
                    reads[0] += 1
                    yield i

        pipe = (CountingSource().map(lambda x: x, workers=2)
                .batch(10, drop_last=True))
        it = iter(pipe)
        seen = []
        for _ in range(5):
            seen.append(next(it))
            pipe.state_dict()       # per-step checkpoint pattern
        it.close()
        assert [b.tolist() for b in seen] == \
            [list(range(i * 10, i * 10 + 10)) for i in range(5)]
        # 50 delivered + the bounded map window of lookahead — NOT the
        # ~165 a rebuild-per-checkpoint pays
        assert reads[0] <= 60, reads[0]


class TestMetricsAndChaos:
    def test_stage_metrics_reported(self):
        profiler.runtime_metrics.reset()
        list(std_pipe(id_samples(24)))
        snap = profiler.runtime_metrics.snapshot()
        assert snap["counters"]["datapipe.source.items"] == 24
        assert snap["counters"]["datapipe.batch.items"] == 6
        assert "datapipe.prefetch.stall_seconds" in snap["series"]
        assert any(k.startswith("datapipe.") for k in snap["gauges"])
        picked = dp.stats()
        assert "counters" in picked and all(
            k.startswith("datapipe.") for k in picked["counters"])

    def test_source_failpoint_propagates(self):
        chaos.inject("datapipe.source", after=5)
        src = dp.InMemorySource(list(range(10)))
        it = iter(src)
        got = [next(it) for _ in range(5)]
        with pytest.raises(chaos.FaultInjected):
            next(it)
        assert got == list(range(5))

    def test_source_failpoint_through_threaded_stages(self):
        chaos.inject("datapipe.source", after=6)
        pipe = std_pipe(id_samples(30))
        with pytest.raises(chaos.FaultInjected):
            list(pipe)


class TestRunPipeline:
    def _trainer(self):
        import paddle_tpu as fluid
        from paddle_tpu import layers
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[3], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            pred = layers.fc(x, 1)
            loss = layers.reduce_mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        return exe, main, loss

    def test_datapipe_pipeline_and_max_steps(self):
        exe, main, loss = self._trainer()
        samples = [{"x": np.full((3,), i, np.float32),
                    "y": np.array([float(i)], np.float32)}
                   for i in range(12)]
        pipe = dp.InMemorySource(samples).batch(4).prefetch()
        outs = exe.run_pipeline(main, pipe, fetch_list=[loss.name],
                                max_steps=2)
        assert len(outs) == 2
        # the unconsumed batch stays in the pipeline, not dropped
        assert sum(1 for _ in pipe) == 1

    def test_plain_iterable_of_feed_dicts(self):
        exe, main, loss = self._trainer()
        batches = [{"x": np.ones((4, 3), np.float32),
                    "y": np.zeros((4, 1), np.float32)}] * 3
        outs = exe.run_pipeline(main, batches, fetch_list=[loss.name])
        assert len(outs) == 3


class TestCheckpointManagerIntegration:
    def test_save_restore_roundtrip_with_datapipe(self, tmp_path):
        import paddle_tpu as fluid
        from paddle_tpu import layers
        from paddle_tpu.fault import CheckpointManager

        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[3], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            pred = layers.fc(x, 1)
            loss = layers.reduce_mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)

        samples = [{"x": np.full((3,), i, np.float32),
                    "y": np.array([float(i)], np.float32)}
                   for i in range(24)]

        def build():
            return (dp.InMemorySource(samples).shuffle(6, seed=1)
                      .batch(4).prefetch(depth=2))

        pipe = build()
        mgr = CheckpointManager(str(tmp_path), keep=3, executor=exe,
                                main_program=main, datapipe=pipe)
        it = iter(pipe)
        consumed = []
        for step in (1, 2):
            b = next(it)
            consumed.append(b)
            exe.run(main, feed={"x": np.asarray(b["x"]),
                                "y": np.asarray(b["y"])},
                    fetch_list=[loss.name])
            mgr.save(step)
        it.close()
        from paddle_tpu.fault.checkpoint import DATAPIPE_STATE_NAME
        assert os.path.exists(
            os.path.join(mgr.path(2), DATAPIPE_STATE_NAME))

        pipe2 = build()
        mgr2 = CheckpointManager(str(tmp_path), keep=3, executor=exe,
                                 main_program=main, datapipe=pipe2)
        assert mgr2.restore_latest() == 2
        rest = list(pipe2)
        ref = list(build())
        assert ids_of(consumed) + ids_of(rest) == ids_of(ref)


# ---------------------------------------------------------------------------
# kill -> checkpoint -> resume drill (acceptance criterion: the
# post-checkpoint sample order is EXACTLY what the uninterrupted run saw)
# ---------------------------------------------------------------------------

DATAPIPE_TRAINER = r'''
"""Deterministic datapipe trainer for the kill-and-resume drill: a
shuffled, mapped, batched, prefetched pipeline checkpointed through
CheckpointManager every step; every consumed batch's sample ids are
appended to --log AFTER the step runs."""
import argparse
import json

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
from paddle_tpu import layers
from paddle_tpu.fault import CheckpointManager, chaos

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", required=True)
ap.add_argument("--log", required=True)
ap.add_argument("--steps", type=int, required=True)
args = ap.parse_args()

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, 1, param_attr="w", bias_attr="b")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

exe = fluid.Executor()
exe.run(startup)

samples = [{"x": np.full((4,), i, np.float32),
            "y": np.array([float(i)], np.float32),
            "sid": np.array([i], np.int64)} for i in range(64)]
pipe = (dp.InMemorySource(samples)
          .shuffle(16, seed=3)
          .map(lambda s: dict(s, x=s["x"] * 0.1), workers=2)
          .batch(4, drop_last=True)
          .prefetch(depth=2))
mgr = CheckpointManager(args.ckpt, keep=3, executor=exe,
                        main_program=main, datapipe=pipe)
start = mgr.restore_latest() or 0

step = start
logf = open(args.log, "a")
it = iter(pipe)
while step < args.steps:
    batch = next(it)
    step += 1
    chaos.fire("train.step", step=step)
    sids = np.asarray(batch.pop("sid"))[:, 0].tolist()
    exe.run(main, feed={"x": np.asarray(batch["x"]),
                        "y": np.asarray(batch["y"])},
            fetch_list=[loss.name])
    logf.write(json.dumps({"step": step, "ids": sids}) + "\n")
    logf.flush()
    mgr.save(step)
it.close()
'''


@pytest.mark.chaos
class TestKillAndResumeSampleOrder:
    def _run(self, tmp_path, trainer, ckpt, log, steps, chaos_spec=None,
             expect_rc=0):
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_CHAOS", None)
        if chaos_spec:
            env["PADDLE_TPU_CHAOS"] = chaos_spec
        r = subprocess.run(
            [sys.executable, str(trainer), "--ckpt", str(ckpt),
             "--log", str(log), "--steps", str(steps)],
            cwd=repo_root, env=env, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == expect_rc, (r.returncode, r.stderr[-2000:])
        return r

    def test_killed_run_resumes_identical_sample_sequence(self, tmp_path):
        trainer = tmp_path / "trainer.py"
        trainer.write_text(DATAPIPE_TRAINER)
        steps = 10

        # uninterrupted reference
        ref_log = tmp_path / "ref.log"
        self._run(tmp_path, trainer, tmp_path / "ref_ckpt", ref_log, steps)
        ref = [json.loads(l) for l in ref_log.read_text().splitlines()]
        assert [r["step"] for r in ref] == list(range(1, steps + 1))

        # chaos run: hard-killed at step 6 (steps 1-5 committed)
        ckpt, log = tmp_path / "ckpt", tmp_path / "got.log"
        self._run(tmp_path, trainer, ckpt, log, steps,
                  chaos_spec="train.step=kill@5",
                  expect_rc=chaos.KILL_EXIT_CODE)
        partial = [json.loads(l) for l in log.read_text().splitlines()]
        assert [r["step"] for r in partial] == [1, 2, 3, 4, 5]

        # resume: the post-checkpoint sample order must be EXACTLY the
        # reference's — no lost, duplicated, or reordered samples
        self._run(tmp_path, trainer, ckpt, log, steps)
        got = [json.loads(l) for l in log.read_text().splitlines()]
        assert got == ref
