"""bench_compile smoke: the cold-start A/B harness must produce its
schema (subprocess-isolated baseline/optimized runs), its trajectory
extraction must round-trip through `paddle_tpu bench check`, and a
degraded run must fail the gate."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

import bench_compile  # noqa: E402
from paddle_tpu.obs import bench_history  # noqa: E402


@pytest.fixture(scope="module")
def smoke_summary():
    return bench_compile.run_bench(smoke=True)


def test_summary_schema(smoke_summary):
    assert {"bench", "smoke", "models", "reduction_best",
            "reduction_second_best", "models_ge_15pct",
            "step_time_ratio_worst"} <= set(smoke_summary)
    (model,) = smoke_summary["models"].values()
    assert {"cold_start_seconds", "captured_phase_seconds",
            "reduction", "steady_step_ms",
            "step_time_ratio"} <= set(model)
    assert model["cold_start_seconds"]["baseline"] > 0
    assert model["cold_start_seconds"]["optimized"] > 0
    assert model["step_time_ratio"] > 0


def test_opt_report_is_carried(smoke_summary):
    (model,) = smoke_summary["models"].values()
    rep = model["opt_report"]
    assert rep is not None
    assert {p["pass"] for p in rep["passes"]} >= {
        "constant_fold", "cse", "dce", "fuse_elementwise",
        "donation_plan", "amortize"}
    assert not [p for p in rep["passes"] if p["status"] == "aborted"]


def test_trajectory_record_and_check_gate(smoke_summary, tmp_path):
    path = str(tmp_path / "traj.json")
    metrics = bench_history.summary_metrics("compile", smoke_summary)
    assert set(metrics) == {"reduction_best", "reduction_second_best",
                            "models_ge_15pct", "step_time_ratio_worst"}
    bench_history.record("compile", metrics, path=path, baseline=True,
                         source="test")
    report = bench_history.check(path=path)
    assert report["ok"], report
    # a regressed run (compile reduction collapsed, steady step 2x)
    bench_history.record(
        "compile",
        {"reduction_best": 0.0, "reduction_second_best": 0.0,
         "models_ge_15pct": 0.0,
         "step_time_ratio_worst":
             metrics["step_time_ratio_worst"] * 2.0},
        path=path, source="test-degraded")
    report = bench_history.check(path=path)
    assert not report["ok"]
    regressed = {r["metric"]
                 for r in report["benches"]["compile"]["regressions"]}
    assert "step_time_ratio_worst" in regressed
