"""Run-ledger tests (``paddle_tpu.obs.ledger``): row/spec schemas,
atomic segment rotation, torn-tail crash recovery, the exactly-once
resume cursor (in-process and through a real kill -> restore drill),
drift-rule episodes, and the ``paddle_tpu runs`` readers."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.obs.ledger import (DriftWatch, EXAMPLE_DRIFT_SPEC,
                                   LEDGER_FORMAT, ROW_FIELDS, RunLedger,
                                   read_rows, summarize, tail_rows,
                                   validate_header, validate_row,
                                   validate_spec)
from paddle_tpu.profiler import RuntimeMetrics


def _mk(tmp_path, **kw):
    kw.setdefault("metrics", RuntimeMetrics())
    kw.setdefault("install", False)
    return RunLedger(str(tmp_path / "ledger"), **kw)


class TestSchemas:
    def test_good_row_is_clean(self):
        assert validate_row({"step": 3, "time_unix": 1.5,
                             "loss": 0.25, "mfu": None}) == []

    def test_row_rejections(self):
        assert validate_row({"time_unix": 1.0})          # no step
        assert validate_row({"step": -1, "time_unix": 1.0})
        assert validate_row({"step": True, "time_unix": 1.0})
        assert validate_row({"step": 1, "time_unix": float("nan")})
        assert validate_row({"step": 1, "time_unix": 1.0,
                             "loss": "0.5"})             # non-number
        assert validate_row({"step": 1, "time_unix": 1.0,
                             "bogus_field": 1.0})        # unknown key

    def test_header_round_trip(self):
        assert validate_header({"ledger_format": LEDGER_FORMAT,
                                "segment": 0, "rows_before": 0}) == []
        assert validate_header({"ledger_format": 99, "segment": 0,
                                "rows_before": 0})
        assert validate_header({"segment": 0, "rows_before": 0})

    def test_example_drift_spec_is_valid(self):
        assert validate_spec(EXAMPLE_DRIFT_SPEC) == []

    def test_drift_spec_rejections(self):
        assert validate_spec({"version": 1, "rules": []})
        assert validate_spec({"version": 2, "rules": [
            {"name": "r", "kind": "ceiling", "field": "loss", "max": 1}]})
        assert validate_spec({"version": 1, "rules": [
            {"name": "r", "kind": "nope", "field": "loss"}]})
        assert validate_spec({"version": 1, "rules": [
            {"name": "r", "kind": "spike", "field": "loss",
             "factor": 0.5}]})  # factor must exceed 1
        assert validate_spec({"version": 1, "rules": [
            {"name": "r", "kind": "ceiling", "field": "loss", "max": 1},
            {"name": "r", "kind": "floor", "field": "loss",
             "min": 0}]})       # duplicate names
        with pytest.raises(ValueError):
            DriftWatch({"version": 1, "rules": []})

    def test_append_sanitizes_non_finite_to_null(self, tmp_path):
        led = _mk(tmp_path, flush_every=1)
        led.append({"step": 0, "time_unix": 1.0,
                    "loss": float("nan"), "grad_norm": float("inf")})
        led.close()
        (row,) = read_rows(led.dirname)
        assert row["loss"] is None and row["grad_norm"] is None

    def test_append_rejects_unknown_fields(self, tmp_path):
        led = _mk(tmp_path)
        with pytest.raises(ValueError):
            led.append({"step": 0, "time_unix": 1.0, "sneaky": 1})
        led.close()


class TestRotationAndRecovery:
    def test_rotation_seals_segments(self, tmp_path):
        led = _mk(tmp_path, rotate_rows=4, flush_every=1)
        for i in range(10):
            led.note_step(loss=float(i))
        led.close()
        names = sorted(os.listdir(led.dirname))
        sealed = [n for n in names if n.endswith(".jsonl")]
        opens = [n for n in names if n.endswith(".open")]
        assert len(sealed) == 2 and len(opens) == 1
        # headers carry the cumulative row offset
        with open(os.path.join(led.dirname, sealed[1])) as f:
            hdr = json.loads(f.readline())
        assert hdr["rows_before"] == 4
        rows = read_rows(led.dirname)
        assert [r["step"] for r in rows] == list(range(10))
        assert led._metrics.counter("ledger.rotations") == 2

    def test_reopen_resumes_numbering(self, tmp_path):
        led = _mk(tmp_path, rotate_rows=4, flush_every=1)
        for i in range(6):
            led.note_step(loss=float(i))
        led.close()
        led2 = _mk(tmp_path, rotate_rows=4, flush_every=1)
        assert led2.rows_total == 6 and led2.last_step == 5
        led2.note_step(loss=9.0)
        led2.close()
        assert [r["step"] for r in read_rows(led2.dirname)] == \
            list(range(7))

    def test_torn_tail_is_truncated_on_reopen(self, tmp_path):
        led = _mk(tmp_path, flush_every=1)
        for i in range(5):
            led.note_step(loss=float(i))
        led.close()
        # simulate a crash mid-write: a torn half-row at the tail
        open_seg = [n for n in os.listdir(led.dirname)
                    if n.endswith(".open")][0]
        with open(os.path.join(led.dirname, open_seg), "ab") as f:
            f.write(b'{"step": 5, "time_un')
        led2 = _mk(tmp_path, flush_every=1)
        assert led2.rows_total == 5
        led2.note_step(loss=5.0)   # appends cleanly after the cut
        led2.close()
        assert [r["step"] for r in read_rows(led2.dirname)] == \
            list(range(6))

    def test_readers(self, tmp_path):
        led = _mk(tmp_path, rotate_rows=3, flush_every=1)
        for i in range(7):
            led.note_step(loss=float(i))
        led.close()
        assert [r["step"] for r in tail_rows(led.dirname, 2)] == [5, 6]
        s = summarize(led.dirname)
        assert s["rows"] == 7 and s["last_step"] == 6
        assert s["fields"]["loss"]["max"] == 6.0
        with pytest.raises(ValueError):
            read_rows(str(tmp_path / "missing"))


class TestResumeCursor:
    def test_rewind_to_cursor_drops_exact_rows(self, tmp_path):
        led = _mk(tmp_path, rotate_rows=3, flush_every=1)
        for i in range(5):
            led.note_step(loss=float(i))
        cursor = led.state_dict()
        assert cursor == {"format": LEDGER_FORMAT, "rows_total": 5,
                          "last_step": 4}
        for i in range(5, 9):
            led.note_step(loss=float(i))
        led.load_state_dict(cursor)          # the restore path
        assert led.rows_total == 5 and led.last_step == 4
        led.note_step(loss=50.0)             # resumes at step 5
        led.close()
        rows = read_rows(led.dirname)
        assert [r["step"] for r in rows] == list(range(6))
        assert rows[-1]["loss"] == 50.0
        assert led._metrics.counter("ledger.rewound_rows") == 4

    def test_rewind_across_sealed_segment_boundary(self, tmp_path):
        led = _mk(tmp_path, rotate_rows=3, flush_every=1)
        for i in range(3):
            led.note_step(loss=float(i))
        cursor = led.state_dict()            # exactly one sealed segment
        for i in range(3, 8):
            led.note_step(loss=float(i))
        led.load_state_dict(cursor)
        led.note_step(loss=3.5)
        led.close()
        rows = read_rows(led.dirname)
        assert [r["step"] for r in rows] == [0, 1, 2, 3]
        assert rows[-1]["loss"] == 3.5

    def test_bad_sidecars_raise(self, tmp_path):
        led = _mk(tmp_path, flush_every=1)
        led.note_step(loss=1.0)
        with pytest.raises(ValueError):
            led.load_state_dict({"format": 99, "rows_total": 1})
        with pytest.raises(ValueError):
            led.load_state_dict({"format": LEDGER_FORMAT,
                                 "rows_total": 5})  # history lost
        with pytest.raises(ValueError):
            led.load_state_dict({"format": LEDGER_FORMAT,
                                 "rows_total": -1})
        led.close()


class TestDrift:
    def _spec(self, sustained=2):
        return {"version": 1, "sustained": sustained, "rules": [
            {"name": "loss-spike", "kind": "spike", "field": "loss",
             "factor": 4.0, "warmup": 3, "ema_beta": 0.5},
            {"name": "grad-explosion", "kind": "ceiling",
             "field": "grad_norm", "max": 100.0}]}

    def test_spike_fires_after_warmup_only(self):
        m = RuntimeMetrics()
        watch = DriftWatch(self._spec(), metrics=m)
        # a huge first value during warmup must NOT breach
        assert watch.evaluate({"step": 0, "loss": 100.0}) == []
        for i in range(1, 4):
            assert watch.evaluate({"step": i, "loss": 1.0}) == []
        got = watch.evaluate({"step": 4, "loss": 1000.0})
        assert got == ["loss-spike"]
        assert m.counter("ledger.drift_breaches") == 1
        # a spike must not drag the EMA up: the next spike still trips
        assert watch.evaluate({"step": 5, "loss": 1000.0}) == \
            ["loss-spike"]

    def test_sustained_breach_posts_one_postmortem_per_episode(
            self, tmp_path, monkeypatch):
        pm_dir = tmp_path / "pm"
        pm_dir.mkdir()
        monkeypatch.setenv("PADDLE_TPU_POSTMORTEM", str(pm_dir))
        m = RuntimeMetrics()
        watch = DriftWatch(self._spec(sustained=2), metrics=m)
        for step in range(4):          # 4 consecutive ceiling breaches
            watch.evaluate({"step": step, "grad_norm": 1e6})
        assert m.counter("ledger.drift_postmortems") == 1
        (pm,) = os.listdir(pm_dir)
        body = json.loads((pm_dir / pm).read_text())
        assert "grad-explosion" in body["reason"]
        assert body["extra"]["breach"]["field"] == "grad_norm"
        # recovery re-arms the episode
        watch.evaluate({"step": 4, "grad_norm": 0.1})
        for step in range(5, 7):
            watch.evaluate({"step": step, "grad_norm": 1e6})
        assert m.counter("ledger.drift_postmortems") == 2

    def test_ledger_evaluates_drift_on_append(self, tmp_path):
        m = RuntimeMetrics()
        led = RunLedger(str(tmp_path / "led"), flush_every=1,
                        drift_spec=self._spec(), metrics=m,
                        install=False)
        led.append({"step": 0, "time_unix": 1.0, "grad_norm": 1e6})
        led.close()
        assert m.counter("ledger.drift_breaches") == 1

    def test_postmortems_embed_ledger_tail(self, tmp_path, monkeypatch):
        from paddle_tpu.obs import flight
        monkeypatch.setenv("PADDLE_TPU_POSTMORTEM",
                           str(tmp_path / "pm.json"))
        led = RunLedger(str(tmp_path / "led"), flush_every=1,
                        metrics=RuntimeMetrics(), install=True)
        for i in range(3):
            led.note_step(loss=float(i))
        path = flight.write_postmortem(reason="test")
        led.close()
        body = json.loads(open(path).read())
        assert [r["step"] for r in body["ledger_tail"]] == [0, 1, 2]


class TestCheckpointSidecar:
    def _model(self):
        import paddle_tpu as fluid
        from paddle_tpu import layers
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 3
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[3], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            pred = layers.fc(x, 1)
            loss = layers.reduce_mean(
                layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        return exe, main, loss

    def _feed(self, i):
        return {"x": np.full((2, 3), i, np.float32),
                "y": np.full((2, 1), float(i), np.float32)}

    def test_restore_rewinds_ledger_with_params(self, tmp_path):
        from paddle_tpu.fault import CheckpointManager
        from paddle_tpu.fault.checkpoint import LEDGER_STATE_NAME
        exe, main, loss = self._model()
        led = _mk(tmp_path, flush_every=1)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=3,
                                executor=exe, main_program=main,
                                ledger=led)
        for step in (1, 2):
            exe.run(main, feed=self._feed(step),
                    fetch_list=[loss.name])
            led.note_step(step=step, loss=float(step))
            mgr.save(step)
        assert os.path.exists(
            os.path.join(mgr.path(2), LEDGER_STATE_NAME))
        # the run continues past the checkpoint, then dies and restores
        for step in (3, 4):
            led.note_step(step=step, loss=float(step))
        assert mgr.restore_latest() == 2
        assert led.rows_total == 2 and led.last_step == 2
        led.note_step(step=3, loss=30.0)
        led.close()
        rows = read_rows(led.dirname)
        assert [r["step"] for r in rows] == [1, 2, 3]
        assert rows[-1]["loss"] == 30.0


# ---------------------------------------------------------------------------
# kill -> restore drill: the ledger must resume its append with no
# duplicated and no missing step rows (ISSUE acceptance criterion)
# ---------------------------------------------------------------------------

LEDGER_TRAINER = r'''
"""run_pipeline trainer for the ledger kill-and-resume drill: every
applied batch appends one ledger row BEFORE the checkpoint commits, so
a restore rewinds the ledger to exactly the committed step."""
import argparse

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
from paddle_tpu import layers
from paddle_tpu.fault import CheckpointManager
from paddle_tpu.obs.ledger import RunLedger

ap = argparse.ArgumentParser()
ap.add_argument("--ckpt", required=True)
ap.add_argument("--ledger", required=True)
ap.add_argument("--steps", type=int, required=True)
args = ap.parse_args()

main, startup = fluid.Program(), fluid.Program()
main.random_seed = startup.random_seed = 11
with fluid.program_guard(main, startup):
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, 1, param_attr="w", bias_attr="b")
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

exe = fluid.Executor()
exe.run(startup)

samples = [{"x": np.full((4,), i, np.float32),
            "y": np.array([float(i)], np.float32)} for i in range(64)]
pipe = dp.InMemorySource(samples).batch(4, drop_last=True)
ledger = RunLedger(args.ledger, rotate_rows=3, flush_every=1)
mgr = CheckpointManager(args.ckpt, keep=3, executor=exe,
                        main_program=main, datapipe=pipe,
                        ledger=ledger)
start = mgr.restore_latest() or 0

done = start
def on_step(step, fetches):
    global done
    done += 1
    mgr.save(done)

exe.run_pipeline(main, pipe, fetch_list=[loss],
                 max_steps=args.steps - start, on_step=on_step,
                 ledger=ledger)
ledger.close()
'''


@pytest.mark.chaos
class TestKillAndResumeLedger:
    def _run(self, trainer, ckpt, led, steps, chaos_spec=None,
             expect_rc=0):
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PADDLE_TPU_CHAOS", None)
        if chaos_spec:
            env["PADDLE_TPU_CHAOS"] = chaos_spec
        r = subprocess.run(
            [sys.executable, str(trainer), "--ckpt", str(ckpt),
             "--ledger", str(led), "--steps", str(steps)],
            cwd=repo_root, env=env, capture_output=True, text=True,
            timeout=300)
        assert r.returncode == expect_rc, (r.returncode, r.stderr[-2000:])
        return r

    def test_killed_run_resumes_without_dup_or_gap(self, tmp_path):
        from paddle_tpu.fault import chaos
        trainer = tmp_path / "trainer.py"
        trainer.write_text(LEDGER_TRAINER)
        steps = 10
        ckpt, led = tmp_path / "ckpt", tmp_path / "ledger"

        # hard-killed mid-run: 5 steps committed with their ledger
        # sidecars, the kill lands before batch 6 applies
        self._run(trainer, ckpt, led, steps,
                  chaos_spec="train.step=kill@5",
                  expect_rc=chaos.KILL_EXIT_CODE)
        # resume: restore_latest rewinds the ledger to the committed
        # cursor, then the loop appends the remaining steps
        self._run(trainer, ckpt, led, steps)
        rows = read_rows(str(led))
        got = [r["step"] for r in rows]
        assert got == sorted(set(got)), f"duplicated rows: {got}"
        assert got == list(range(steps)), got
        assert all(r["loss"] is not None for r in rows)
