"""`paddle_tpu selfcheck` keeps its own copies of the registry-scanner
regexes (it must run without a tests/ checkout); these agreement checks
are the lockstep guard the copies rely on: if a scanner idiom changes
on either side, the sets diverge and THIS file fails — not a release
gate at deploy time.

The end-to-end smoke (`paddle_tpu selfcheck` exits 0) lives in
tests/test_analysis_zoo.py::test_selfcheck_cli_passes, next to the zoo
gates it wraps.
"""

from paddle_tpu.analysis import selfcheck as sc

from tests import test_analysis_registry as reg
from tests import test_chaos_failpoint_registry as fp
from tests import test_obs_metric_registry as met


def test_metric_scanner_agrees_with_registry_test():
    assert sc._emitted_metric_names() == met.emitted_metric_names()
    doc = set(sc._DOC_METRIC.findall(sc._read_doc("observability.md")))
    assert doc == met.documented_metric_names()


def test_failpoint_scanner_agrees_with_registry_test():
    fired = set()
    for path, text in sc._iter_sources():
        import os
        if os.path.relpath(path, sc.SRC_ROOT) == os.path.join(
                "fault", "chaos.py"):
            continue
        fired.update(sc._FIRE.findall(text))
    assert fired == fp.fired_failpoint_names()
    doc = set(sc._DOC_FAILPOINT.findall(
        sc._read_doc("fault_tolerance.md")))
    assert doc == fp.documented_failpoint_names()


def test_diagnostic_scanner_agrees_with_registry_test():
    section = sc._check_diagnostic_registry()
    assert section["ok"], section["failures"]
    doc = set(sc._DOC_CODE.findall(sc._read_doc("static_analysis.md")))
    assert doc == reg.documented_codes()


def test_selfcheck_sections_are_complete():
    """Every gate selfcheck promises (docstring + CLI help) is present;
    a section silently dropped from run_selfcheck would hollow out the
    release gate."""
    report = sc.run_selfcheck()
    names = {s["name"] for s in report["sections"]}
    assert {"zoo-lint", "zoo-distribute", "zoo-pipeline", "gen-bundle",
            "paged-kv", "embedding", "diagnostic-registry",
            "metric-registry", "failpoint-registry", "slo-spec",
            "bench-trajectory", "perf", "ledger", "sessions"} <= names


def test_slo_spec_section_fails_on_malformed_env_spec(tmp_path,
                                                      monkeypatch):
    bad = tmp_path / "slo.json"
    bad.write_text('{"version": 1, "objectives": []}')
    monkeypatch.setenv("PADDLE_TPU_SLO", str(bad))
    section = sc._check_slo_spec()
    assert not section["ok"]
    assert any("objectives" in f for f in section["failures"])


def test_bench_trajectory_section_validates_repo_file():
    section = sc._check_bench_trajectory()
    assert section["ok"], section["failures"]
