"""save/load/save_combine/load_combine IR ops + program-level persistence
(reference ``save_op.cc``, ``load_op.cc``, ``save_load_combine_op_test.cc``).
"""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Program
from paddle_tpu.ops.persist_ops import MAGIC, read_tensor, write_tensor
from paddle_tpu.scope import Scope, scope_guard


def _scope_with(values):
    scope = Scope()
    for name, arr in values.items():
        scope.set_var(name, arr)
    return scope


class TestTensorFormat:
    def test_round_trip_dtypes(self, tmp_path):
        path = tmp_path / "t.bin"
        arrays = [
            np.arange(12, dtype="float32").reshape(3, 4),
            np.array([[1, 2], [3, 4]], dtype="int64"),
            np.float32(3.5).reshape(()),  # rank-0
        ]
        with open(path, "wb") as f:
            for a in arrays:
                write_tensor(f, a)
        with open(path, "rb") as f:
            for a in arrays:
                got, lod = read_tensor(f)
                np.testing.assert_array_equal(got, a)
                assert lod == []

    def test_lod_round_trip(self, tmp_path):
        path = tmp_path / "t.bin"
        a = np.ones((5, 2), "float32")
        with open(path, "wb") as f:
            write_tensor(f, a, lod=[[0, 2, 5]])
        with open(path, "rb") as f:
            got, lod = read_tensor(f)
        assert lod == [[0, 2, 5]]

    def test_bad_magic_rejected(self, tmp_path):
        path = tmp_path / "t.bin"
        path.write_bytes(b"XXXX" + b"\0" * 16)
        with open(path, "rb") as f:
            with pytest.raises(ValueError, match="magic"):
                read_tensor(f)

    def test_versioned_header(self, tmp_path):
        path = tmp_path / "t.bin"
        with open(path, "wb") as f:
            write_tensor(f, np.zeros((2,), "float32"))
        assert path.read_bytes()[:4] == MAGIC


class TestSaveLoadOps:
    def test_save_then_load_program(self, tmp_path):
        """A program containing save ops writes the files; a startup-style
        program containing load ops boots a fresh scope — mirroring
        save_load_combine_op_test.cc's lifecycle."""
        rng = np.random.RandomState(0)
        w = rng.rand(4, 3).astype("float32")
        b = rng.rand(3).astype("float32")

        save_prog = Program()
        blk = save_prog.global_block()
        for name, arr in (("w", w), ("b", b)):
            v = blk.create_var(name=name, shape=arr.shape,
                               dtype="float32")
            v.persistable = True
            blk.append_op(type="save", inputs={"X": [name]}, outputs={},
                          attrs={"file_path": str(tmp_path / name)})
        exe = fluid.Executor()
        with scope_guard(_scope_with({"w": w, "b": b})):
            exe.run(save_prog, feed={}, fetch_list=[])
        assert (tmp_path / "w").exists() and (tmp_path / "b").exists()

        boot_prog = Program()
        blk = boot_prog.global_block()
        for name, arr in (("w", w), ("b", b)):
            v = blk.create_var(name=name, shape=arr.shape,
                               dtype="float32")
            v.persistable = True
            blk.append_op(type="load", inputs={},
                          outputs={"Out": [name]},
                          attrs={"file_path": str(tmp_path / name)})
        fresh = Scope()
        with scope_guard(fresh):
            exe.run(boot_prog, feed={}, fetch_list=[])
            np.testing.assert_array_equal(
                np.asarray(fresh.find_var("w")), w)
            np.testing.assert_array_equal(
                np.asarray(fresh.find_var("b")), b)

    def test_save_combine_load_combine(self, tmp_path):
        """Port of save_load_combine_op_test.cc: several tensors through
        ONE file, restored in slot order."""
        rng = np.random.RandomState(1)
        tensors = {f"t{i}": rng.rand(2, i + 1).astype("float32")
                   for i in range(4)}
        names = sorted(tensors)
        path = str(tmp_path / "combined")

        save_prog = Program()
        blk = save_prog.global_block()
        for n in names:
            v = blk.create_var(name=n, shape=tensors[n].shape,
                               dtype="float32")
            v.persistable = True
        blk.append_op(type="save_combine", inputs={"X": names},
                      outputs={}, attrs={"file_path": path})
        exe = fluid.Executor()
        with scope_guard(_scope_with(tensors)):
            exe.run(save_prog, feed={}, fetch_list=[])

        load_prog = Program()
        blk = load_prog.global_block()
        for n in names:
            v = blk.create_var(name=n, shape=tensors[n].shape,
                               dtype="float32")
            v.persistable = True
        blk.append_op(type="load_combine", inputs={},
                      outputs={"Out": names}, attrs={"file_path": path})
        fresh = Scope()
        with scope_guard(fresh):
            exe.run(load_prog, feed={}, fetch_list=[])
            for n in names:
                np.testing.assert_array_equal(
                    np.asarray(fresh.find_var(n)), tensors[n])

    def test_save_no_overwrite_errors(self, tmp_path):
        path = str(tmp_path / "once")
        prog = Program()
        blk = prog.global_block()
        v = blk.create_var(name="x", shape=(2,), dtype="float32")
        v.persistable = True
        blk.append_op(type="save", inputs={"X": ["x"]}, outputs={},
                      attrs={"file_path": path, "overwrite": False})
        exe = fluid.Executor()
        with scope_guard(_scope_with({"x": np.zeros(2, "f")})):
            exe.run(prog, feed={}, fetch_list=[])
            with pytest.raises(Exception, match="overwrite"):
                exe.run(prog, feed={}, fetch_list=[])


class TestInferenceModelDirectory:
    def test_model_dir_is_model_plus_params(self, tmp_path):
        """save_inference_model emits __model__ + combined __params__ and
        load_inference_model (hence serving.Predictor / native/capi.cpp)
        runs it."""
        import paddle_tpu.layers as layers
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            y = layers.fc(input=x, size=3, act="softmax")
        exe = fluid.Executor()
        scope = Scope()
        d = str(tmp_path / "model")
        with scope_guard(scope):
            exe.run(startup)
            fluid.io.save_inference_model(d, ["x"], [y], exe, main)
            xv = np.random.RandomState(2).rand(5, 4).astype("f")
            (want,) = exe.run(main.prune([y]).inference_optimize(),
                              feed={"x": xv}, fetch_list=[y.name])
        assert os.path.exists(os.path.join(d, "__model__"))
        assert os.path.exists(os.path.join(d, "__params__"))
        with open(os.path.join(d, "__params__"), "rb") as f:
            assert f.read(4) == MAGIC

        fresh = Scope()
        with scope_guard(fresh):
            prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
            (got,) = exe.run(prog, feed={feeds[0]: xv},
                             fetch_list=[v.name for v in fetches])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)


class TestCombinedNameSafety:
    def test_partial_save_does_not_shift_records(self, tmp_path):
        """A var missing from the scope at save time must not mis-assign
        every later record on load (records carry names; load matches by
        name)."""
        import paddle_tpu.layers as layers
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            layers.fc(input=x, size=3)
        exe = fluid.Executor()
        scope = Scope()
        with scope_guard(scope):
            exe.run(startup)
            # drop ONE persistable from the scope -> save skips it
            names = [v.name for v in main.list_vars()
                     if getattr(v, "persistable", False)]
            dropped = sorted(names)[0]
            kept = {n: np.asarray(scope.find_var(n))
                    for n in names if n != dropped}
            scope2 = Scope()
            for n, v in kept.items():
                scope2.set_var(n, v)
        with scope_guard(scope2):
            fluid.io.save_persistables(exe, str(tmp_path), main,
                                       filename="__params__")
        fresh = Scope()
        with scope_guard(fresh):
            fluid.io.load_persistables(exe, str(tmp_path), main,
                                       filename="__params__")
            for n, want in kept.items():
                np.testing.assert_array_equal(
                    np.asarray(fresh.find_var(n)), want)
            assert fresh.find_var(dropped) is None
