"""GPipe pipeline parallelism (parallel/pipeline.py): forward equality
with the sequential stage composition, and gradient equality through the
differentiable ppermute schedule — on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline import gpipe, stack_stage_params


def _stage_fn(params, x):
    # one transformer-ish stage: linear + nonlinearity + residual
    h = jnp.tanh(x @ params["w"] + params["b"])
    return x + h


def _make(P_stages, d=8, m=6, mb=4, seed=0):
    rng = np.random.RandomState(seed)
    per_stage = [{"w": jnp.asarray(rng.randn(d, d).astype("f") * 0.3),
                  "b": jnp.asarray(rng.randn(d).astype("f") * 0.1)}
                 for _ in range(P_stages)]
    xs = jnp.asarray(rng.randn(m, mb, d).astype("f"))
    return per_stage, xs


def _sequential(per_stage, xs):
    out = xs.reshape(-1, xs.shape[-1])
    for p in per_stage:
        out = _stage_fn(p, out)
    return out.reshape(xs.shape)


class TestGPipe:
    def test_forward_matches_sequential(self):
        P_stages = 4
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        per_stage, xs = _make(P_stages)
        stacked = stack_stage_params(per_stage)
        got = gpipe(_stage_fn, stacked, xs, mesh, axis="pipe")
        want = _sequential(per_stage, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=1e-6)

    def test_gradients_match_sequential(self):
        """jax.grad through the pipelined schedule == grad of the
        sequential composition (the reverse pipeline falls out of
        ppermute's transpose — no hand-written backward)."""
        P_stages = 4
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        per_stage, xs = _make(P_stages, seed=1)
        stacked = stack_stage_params(per_stage)

        def pipe_loss(stacked_params):
            out = gpipe(_stage_fn, stacked_params, xs, mesh, axis="pipe")
            return jnp.sum(out ** 2)

        def seq_loss(stacked_params):
            out = xs.reshape(-1, xs.shape[-1])
            for p in range(P_stages):
                params = jax.tree_util.tree_map(lambda a, p=p: a[p],
                                                stacked_params)
                out = _stage_fn(params, out)
            return jnp.sum(out ** 2)

        np.testing.assert_allclose(float(pipe_loss(stacked)),
                                   float(seq_loss(stacked)), rtol=2e-5)
        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stacked)
        for k in g_seq:
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=5e-4, atol=1e-5)

    def test_training_converges_under_jit(self):
        """A jitted SGD loop over the pipelined loss trains."""
        P_stages = 2
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        per_stage, xs = _make(P_stages, d=6, m=4, mb=8, seed=2)
        stacked = stack_stage_params(per_stage)
        rng = np.random.RandomState(3)
        target = jnp.asarray(rng.randn(*xs.shape).astype("f"))

        @jax.jit
        def step(params):
            def loss(p):
                out = gpipe(_stage_fn, p, xs, mesh, axis="pipe")
                return jnp.mean((out - target) ** 2)
            l, g = jax.value_and_grad(loss)(params)
            return l, jax.tree_util.tree_map(
                lambda a, da: a - 0.1 * da, params, g)

        losses = []
        for _ in range(15):
            l, stacked = step(stacked)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.9, losses

    def test_stage_homogeneity_enforced(self):
        with pytest.raises(ValueError, match="homogeneous"):
            stack_stage_params([{"w": jnp.zeros((2, 2))},
                                {"v": jnp.zeros((2, 2))}])


def test_stage_count_must_match_mesh():
    import jax
    mesh = make_mesh((4,), ("pipe",), devices=jax.devices()[:4])
    per_stage, xs = _make(8)   # 8 stages on a 4-device axis
    stacked = stack_stage_params(per_stage)
    with pytest.raises(ValueError, match="one stage per device"):
        gpipe(_stage_fn, stacked, xs, mesh, axis="pipe")


class TestGPipeOverIRTransformerLayer:
    """PP over the REAL IR compute: the stage function is a lowered
    transformer encoder layer (Program IR -> jaxpr via lower_block), its
    parameters stacked per stage — gpipe output matches applying the
    same four layers sequentially."""

    def test_encoder_layers_pipelined(self):
        import paddle_tpu as fluid
        from paddle_tpu.executor import lower_block
        from paddle_tpu.models import transformer as T

        P_stages, mb, S = 4, 2, 8
        hp = T.ModelHyperParams()
        hp.d_model, hp.d_inner_hid = 16, 32
        hp.n_head, hp.d_key, hp.d_value = 2, 8, 8
        hp.dropout = hp.attention_dropout = 0.0
        hp.use_flash = False

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            import paddle_tpu.layers as L
            x = L.data("x", shape=[mb, S, hp.d_model], dtype="float32",
                       append_batch_size=False)
            out = T.encoder_layer(x, None, hp, idx=0)
        block = main.global_block()
        param_names = sorted(
            n for n, v in block.vars.items()
            if getattr(v, "persistable", False))

        # 4 independently-initialized copies of the layer's params
        per_stage = []
        for s in range(P_stages):
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                startup.random_seed = 100 + s
                exe = fluid.Executor()
                exe.run(startup)
                per_stage.append({n: jnp.asarray(scope.find_var(n))
                                  for n in param_names})

        out_name = out.name

        def stage_fn(params, xv):
            env = dict(params)
            env["x"] = xv
            aux = {"rng_counter": 0, "lower_block": lower_block}
            lower_block(block, env, None, False, aux)
            return env[out_name]

        rng = np.random.RandomState(7)
        xs = jnp.asarray(rng.randn(6, mb, S, hp.d_model).astype("f") * 0.3)
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        got = gpipe(stage_fn, stack_stage_params(per_stage), xs, mesh,
                    axis="pipe")
        want = xs
        for p in per_stage:
            want = jnp.stack([stage_fn(p, want[i])
                              for i in range(want.shape[0])])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=1e-5)
