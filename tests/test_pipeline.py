"""GPipe pipeline parallelism (parallel/pipeline.py): forward equality
with the sequential stage composition, and gradient equality through the
differentiable ppermute schedule — on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline import gpipe, stack_stage_params


def _stage_fn(params, x):
    # one transformer-ish stage: linear + nonlinearity + residual
    h = jnp.tanh(x @ params["w"] + params["b"])
    return x + h


def _make(P_stages, d=8, m=6, mb=4, seed=0):
    rng = np.random.RandomState(seed)
    per_stage = [{"w": jnp.asarray(rng.randn(d, d).astype("f") * 0.3),
                  "b": jnp.asarray(rng.randn(d).astype("f") * 0.1)}
                 for _ in range(P_stages)]
    xs = jnp.asarray(rng.randn(m, mb, d).astype("f"))
    return per_stage, xs


def _sequential(per_stage, xs):
    out = xs.reshape(-1, xs.shape[-1])
    for p in per_stage:
        out = _stage_fn(p, out)
    return out.reshape(xs.shape)


class TestGPipe:
    def test_forward_matches_sequential(self):
        P_stages = 4
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        per_stage, xs = _make(P_stages)
        stacked = stack_stage_params(per_stage)
        got = gpipe(_stage_fn, stacked, xs, mesh, axis="pipe")
        want = _sequential(per_stage, xs)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=1e-6)

    def test_gradients_match_sequential(self):
        """jax.grad through the pipelined schedule == grad of the
        sequential composition (the reverse pipeline falls out of
        ppermute's transpose — no hand-written backward)."""
        P_stages = 4
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        per_stage, xs = _make(P_stages, seed=1)
        stacked = stack_stage_params(per_stage)

        def pipe_loss(stacked_params):
            out = gpipe(_stage_fn, stacked_params, xs, mesh, axis="pipe")
            return jnp.sum(out ** 2)

        def seq_loss(stacked_params):
            out = xs.reshape(-1, xs.shape[-1])
            for p in range(P_stages):
                params = jax.tree_util.tree_map(lambda a, p=p: a[p],
                                                stacked_params)
                out = _stage_fn(params, out)
            return jnp.sum(out ** 2)

        np.testing.assert_allclose(float(pipe_loss(stacked)),
                                   float(seq_loss(stacked)), rtol=2e-5)
        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stacked)
        for k in g_seq:
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=5e-4, atol=1e-5)

    def test_training_converges_under_jit(self):
        """A jitted SGD loop over the pipelined loss trains."""
        P_stages = 2
        mesh = make_mesh((P_stages,), ("pipe",),
                         devices=jax.devices()[:P_stages])
        per_stage, xs = _make(P_stages, d=6, m=4, mb=8, seed=2)
        stacked = stack_stage_params(per_stage)
        rng = np.random.RandomState(3)
        target = jnp.asarray(rng.randn(*xs.shape).astype("f"))

        @jax.jit
        def step(params):
            def loss(p):
                out = gpipe(_stage_fn, p, xs, mesh, axis="pipe")
                return jnp.mean((out - target) ** 2)
            l, g = jax.value_and_grad(loss)(params)
            return l, jax.tree_util.tree_map(
                lambda a, da: a - 0.1 * da, params, g)

        losses = []
        for _ in range(15):
            l, stacked = step(stacked)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.9, losses

    def test_stage_homogeneity_enforced(self):
        with pytest.raises(ValueError, match="homogeneous"):
            stack_stage_params([{"w": jnp.zeros((2, 2))},
                                {"v": jnp.zeros((2, 2))}])


def test_stage_count_must_match_mesh():
    import jax
    mesh = make_mesh((4,), ("pipe",), devices=jax.devices()[:4])
    per_stage, xs = _make(8)   # 8 stages on a 4-device axis
    stacked = stack_stage_params(per_stage)
    with pytest.raises(ValueError, match="one stage per device"):
        gpipe(_stage_fn, stacked, xs, mesh, axis="pipe")
