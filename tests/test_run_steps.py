"""Executor.run_steps: the device-side multi-step training loop.

Covers: stacked per-step feeds, single-batch broadcast feeds, state
write-back across calls, and interleaving with plain ``run``.
"""
import numpy as np
import pytest

import paddle_tpu as fluid


@pytest.fixture
def regression():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    return main, startup, cost


def _data(steps=20, batch=8):
    rng = np.random.RandomState(0)
    w = np.array([[1.0], [2.0], [-1.0]], "float32")
    xs = rng.randn(steps, batch, 3).astype("float32")
    ys = xs @ w + 0.5
    return xs, ys


def test_stacked_feeds_train(regression):
    main, startup, cost = regression
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs, ys = _data()
        (losses,) = exe.run_steps(main, feed={"x": xs, "y": ys},
                                  fetch_list=[cost.name], steps=20)
        losses = np.asarray(losses).reshape(-1)
        assert losses.shape == (20,)
        assert losses[-1] < losses[0] * 0.2


def test_broadcast_single_batch(regression):
    main, startup, cost = regression
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs, ys = _data()
        (losses,) = exe.run_steps(main, feed={"x": xs[0], "y": ys[0]},
                                  fetch_list=[cost.name], steps=10)
        losses = np.asarray(losses).reshape(-1)
        assert losses.shape == (10,)
        assert losses[-1] < losses[0]


def test_state_persists_and_interleaves_with_run(regression):
    main, startup, cost = regression
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xs, ys = _data()
        (l1,) = exe.run_steps(main, feed={"x": xs, "y": ys},
                              fetch_list=[cost.name], steps=20)
        # a second multi-step call continues from the updated params
        (l2,) = exe.run_steps(main, feed={"x": xs, "y": ys},
                              fetch_list=[cost.name], steps=20)
        assert np.asarray(l2)[0] < np.asarray(l1)[0]
        # and a plain run sees the trained weights too
        (l3,) = exe.run(main, feed={"x": xs[0], "y": ys[0]},
                        fetch_list=[cost.name])
        assert float(np.asarray(l3).reshape(())) < \
            float(np.asarray(l1).reshape(-1)[0])


def test_equivalent_to_per_step_runs(regression):
    main, startup, cost = regression
    xs, ys = _data(steps=5)
    # run_steps path
    s1 = fluid.Scope()
    with fluid.scope_guard(s1):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (ls,) = exe.run_steps(main, feed={"x": xs, "y": ys},
                              fetch_list=[cost.name], steps=5)
    # per-step path
    s2 = fluid.Scope()
    with fluid.scope_guard(s2):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        per = [float(np.asarray(
            exe.run(main, feed={"x": xs[i], "y": ys[i]},
                    fetch_list=[cost.name])[0]).reshape(()))
            for i in range(5)]
    np.testing.assert_allclose(np.asarray(ls).reshape(-1), per, rtol=1e-5)
