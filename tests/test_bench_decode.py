"""bench_decode smoke: under closed-loop clients with mixed generation
lengths (sleep-modeled decode-step device time per the 2-vCPU
bench-host constraint), iteration-level continuous batching must
deliver >= 2x the aggregate tokens/s of the request-level admission
baseline AND a lower p99 time-to-first-token (new requests are admitted
into the running batch instead of queueing behind it).
BENCH_DECODE.json records the full acceptance run."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

import bench_decode  # noqa: E402


def _bench_with_retries(attempts, target_ratio, **kw):
    """Best-of-N against noisy-neighbor CPU: external load can only
    UNDERSTATE the gap (the capability is slot-occupancy math over
    sleeps), so one clean run suffices.  Zero lost requests must hold
    on EVERY attempt."""
    last = None
    for _ in range(attempts):
        last = bench_decode.run_bench(**kw)
        for mode in last["modes"].values():
            assert mode["failures"] == 0, mode
        ratio_ok = last["tokens_per_sec_ratio"] is not None and \
            last["tokens_per_sec_ratio"] >= target_ratio
        ttft_ok = last["ttft_p99_ms"]["continuous"] < \
            last["ttft_p99_ms"]["request_level"]
        if ratio_ok and ttft_ok:
            return last
    return last


@pytest.fixture(scope="module")
def smoke_summary():
    return _bench_with_retries(3, 2.0, clients=6, duration=1.5,
                               step_ms=20.0)


def test_summary_schema(smoke_summary):
    assert {"clients", "duration_sec", "decode_step_ms", "gen_lengths",
            "modes", "tokens_per_sec_ratio",
            "ttft_p99_ms"} <= set(smoke_summary)
    for mode in ("continuous", "request_level"):
        stats = smoke_summary["modes"][mode]
        assert {"tokens_per_sec", "tokens", "requests_ok", "failures",
                "ttft_ms"} <= set(stats)
        assert stats["requests_ok"] > 0
        assert stats["tokens"] > 0


def test_continuous_batching_doubles_tokens_per_sec(smoke_summary):
    assert smoke_summary["tokens_per_sec_ratio"] is not None
    assert smoke_summary["tokens_per_sec_ratio"] >= 2.0, smoke_summary


def test_continuous_batching_lowers_ttft_p99(smoke_summary):
    ttft = smoke_summary["ttft_p99_ms"]
    assert ttft["continuous"] < ttft["request_level"], smoke_summary


def test_no_lost_requests(smoke_summary):
    for mode in smoke_summary["modes"].values():
        assert mode["failures"] == 0, mode


def test_trajectory_gate_wiring(smoke_summary, tmp_path):
    """Smoke metrics record into a trajectory the bench gate accepts;
    a degraded tokens/s entry fails `paddle_tpu bench check`."""
    from paddle_tpu import cli
    from paddle_tpu.obs import bench_history

    path = str(tmp_path / "traj.json")
    metrics = bench_history.summary_metrics("decode", smoke_summary)
    bench_history.record("decode", metrics, path=path, baseline=True,
                         source="test_bench_decode")
    assert cli.main(["bench", "check", "--trajectory", path]) == 0
    degraded = dict(metrics,
                    tokens_per_sec=metrics["tokens_per_sec"] / 3,
                    tokens_per_sec_ratio=1.0)
    bench_history.record("decode", degraded, path=path)
    assert cli.main(["bench", "check", "--trajectory", path]) == 1


@pytest.mark.slow
def test_acceptance_full_run():
    summary = _bench_with_retries(4, 2.0, clients=8, duration=3.0,
                                  step_ms=20.0)
    assert summary["tokens_per_sec_ratio"] >= 2.0, summary
    assert summary["ttft_p99_ms"]["continuous"] < \
        summary["ttft_p99_ms"]["request_level"]
