"""bench_autoscale smoke: the closed-loop drill must shed ONLY with a
``Retry-After`` hint, the mid-ramp kill drill must lose zero accepted
requests, and standby prewarm must ride the persistent compile cache
(hits move, misses stay flat).  The full A/B acceptance — controller
fleet holds the p99 SLO under the 5x step while the fixed fleet
breaches — runs at the CLI's longer defaults and is marked slow."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

import bench_autoscale  # noqa: E402
from paddle_tpu.obs import bench_history  # noqa: E402

_SMOKE = dict(duration=2.5, service_ms=25.0, base_rps=4.0,
              peak_rps=20.0, p99_slo_ms=300.0, seed=7)


@pytest.fixture(scope="module")
def smoke_summary():
    return bench_autoscale.run_bench(**_SMOKE)


def test_summary_schema(smoke_summary):
    assert {"modes", "kill_drill",
            "sheds_without_retry_after"} <= set(smoke_summary)
    for mode in ("fixed", "controller"):
        run = smoke_summary["modes"][mode]
        assert {"p99_ms", "held_slo", "scale_ups", "traffic",
                "standby_compile_cache", "replicas_start",
                "replicas_end"} <= set(run)
        assert run["traffic"]["outcomes"]["ok"] > 0
    assert smoke_summary["modes"]["fixed"]["mode"] == "fixed"
    assert smoke_summary["modes"]["controller"]["mode"] == "controller"


def test_every_shed_carries_retry_after(smoke_summary):
    assert smoke_summary["sheds_without_retry_after"] == 0, smoke_summary


def test_kill_drill_loses_zero_accepted(smoke_summary):
    drill = smoke_summary["kill_drill"]
    assert drill["killed"], drill              # the failpoint fired
    assert drill["traffic"]["lost_accepted"] == 0, drill["traffic"]


def test_standby_prewarm_rides_compile_cache(smoke_summary):
    cache = smoke_summary["modes"]["controller"]["standby_compile_cache"]
    # the fixed pass populated the shared persistent cache; warming the
    # standby pool must replay it, never recompile
    assert cache["misses_delta"] == 0, cache
    assert cache["hits_delta"] >= 1, cache


def test_bench_history_extraction(smoke_summary):
    metrics = bench_history.summary_metrics("autoscale", smoke_summary)
    assert set(metrics) == {"p99_controller_ms", "scale_ups",
                            "lost_accepted", "sheds_without_retry_after"}
    assert metrics["lost_accepted"] == 0
    assert metrics["sheds_without_retry_after"] == 0


@pytest.mark.slow
def test_controller_holds_slo_while_fixed_breaches():
    # CLI defaults: 8s replay, 40ms device time, 5 -> 25 rps step
    # against a single fixed replica (sleep-modeled capacity well
    # under the peak) vs the controller fleet (max 3 replicas from
    # the warm-standby pool)
    summary = bench_autoscale.run_bench()
    fixed = summary["modes"]["fixed"]
    ctrl = summary["modes"]["controller"]
    assert not fixed["held_slo"], fixed
    assert ctrl["held_slo"], ctrl
    assert ctrl["scale_ups"] >= 1, ctrl
    assert summary["sheds_without_retry_after"] == 0
    assert summary["kill_drill"]["traffic"]["lost_accepted"] == 0
