"""Numerics-observatory tests (``paddle_tpu.obs.numerics``): tensor
stats, probe-forced interpret execution, organic NaN localization with
creation-site attribution, the fused health-norm reduction, and the
creation-site Program round-trip the localizer depends on."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.obs import numerics
from paddle_tpu.profiler import RuntimeMetrics, runtime_metrics


class TestTensorStats:
    def test_finite_float(self):
        s = numerics.tensor_stats(np.array([[1.0, -2.0], [0.0, 4.0]],
                                           np.float32))
        assert s["finite_frac"] == 1.0
        assert s["absmax"] == 4.0
        assert s["zero_frac"] == 0.25
        assert s["shape"] == [2, 2]

    def test_non_finite_fraction(self):
        s = numerics.tensor_stats(
            np.array([1.0, np.nan, np.inf, 2.0], np.float32))
        assert s["finite_frac"] == 0.5
        # stats computed over the finite entries only
        assert s["absmax"] == 2.0 and s["mean"] == 1.5

    def test_all_nan_degrades(self):
        s = numerics.tensor_stats(np.full(3, np.nan, np.float32))
        assert s["finite_frac"] == 0.0 and s["absmax"] is None

    def test_int_bool_empty_and_unstatable(self):
        assert numerics.tensor_stats(
            np.array([0, 3], np.int64))["absmax"] == 3.0
        assert numerics.tensor_stats(
            np.array([], np.float32))["finite_frac"] == 1.0
        assert numerics.tensor_stats(object())["kind"] == "object"

    def test_bfloat16(self):
        import jax.numpy as jnp
        s = numerics.tensor_stats(jnp.asarray([1.0, 2.0], jnp.bfloat16))
        assert s["finite_frac"] == 1.0 and s["absmax"] == 2.0


class TestProbeExecution:
    def test_probe_forces_interpret_and_counts_ops(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[3], dtype="float32")
            h = layers.fc(x, 2)
            loss = layers.reduce_mean(h)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 3), np.float32)}
        before = runtime_metrics.counter("numerics.ops_probed")
        collector = numerics.ProbeCollector()
        with numerics.probe(collector):
            assert numerics.probing_enabled()
            exe.run(main, feed=feed, fetch_list=[loss.name])
        assert not numerics.probing_enabled()
        assert collector.ops_probed >= len(main.global_block().ops)
        assert runtime_metrics.counter("numerics.ops_probed") == \
            before + collector.ops_probed
        assert collector.first_bad is None

    def test_organic_nan_localizes_to_first_bad_op(self):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.fc(x, 4)
            shifted = layers.elementwise_sub(
                h, layers.fill_constant([1], "float32", 1e6))
            bad = layers.log(shifted)   # log of a negative: NaN
            loss = layers.reduce_mean(bad)
        exe = fluid.Executor()
        exe.run(startup)
        before = runtime_metrics.counter("numerics.non_finite_ops")
        collector = numerics.ProbeCollector(trail=4)
        with numerics.probe(collector):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss.name])
        fb = collector.first_bad
        assert fb is not None and fb["type"] == "log"
        # the creation site names THIS test file, not framework code
        assert fb["creation_site"][0].endswith("test_numerics.py")
        # inputs were still finite going in — the op itself is guilty
        assert all(s["finite_frac"] == 1.0
                   for s in fb["inputs"].values())
        assert any(s["finite_frac"] < 1.0
                   for s in fb["outputs"].values())
        assert len(fb["trail"]) <= 4
        assert fb["trail"][-1]["type"] == "log"
        assert runtime_metrics.counter("numerics.non_finite_ops") == \
            before + 1

    def test_trail_is_bounded(self):
        class _Op:
            type = "fake"
            input_arg_names = []
            output_arg_names = ["o"]
            creation_site = ("f.py", 1)

        c = numerics.ProbeCollector(trail=3)
        for i in range(10):
            c.record_op(_Op(), {"o": None},
                        {"o": np.zeros(2, np.float32)})
        assert len(c.trail) == 3 and c.ops_probed == 10


class TestCreationSiteRoundTrip:
    def test_to_dict_from_dict_preserves_site(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = layers.data("x", shape=[3], dtype="float32")
            layers.reduce_mean(x)
        clone = fluid.Program.from_dict(main.to_dict())
        for op, op2 in zip(main.global_block().ops,
                           clone.global_block().ops):
            assert op2.creation_site == op.creation_site
            assert op2.creation_site[0].endswith("test_numerics.py")


class TestFusedHealth:
    def test_fused_check_reports_finite_and_norms(self):
        import jax.numpy as jnp
        fn = numerics.fused_check_fn()
        old = [jnp.zeros((2, 2), jnp.float32)]
        new = [jnp.full((2, 2), 0.5, jnp.float32)]
        finite, norms = fn([jnp.ones(3)], new, old)
        assert bool(finite)
        health = numerics.health_from_norms(np.asarray(norms))
        assert health["param_norm"] == pytest.approx(1.0)
        assert health["grad_norm"] == pytest.approx(1.0)
        assert health["update_ratio"] == pytest.approx(1.0)

    def test_fused_check_flags_non_finite(self):
        import jax.numpy as jnp
        fn = numerics.fused_check_fn()
        finite, norms = fn([jnp.asarray([1.0, jnp.nan])], [], [])
        assert not bool(finite)
        assert numerics.health_from_norms(np.asarray(norms)) is None

    def test_set_health_gauges(self):
        m = RuntimeMetrics()
        numerics.set_health_gauges(m, None)        # disabled: no-op
        assert m.gauge("train.grad_norm") is None
        numerics.set_health_gauges(
            m, {"param_norm": 2.0, "grad_norm": 0.5,
                "update_ratio": 0.25})
        assert m.gauge("train.param_norm") == 2.0
        assert m.gauge("train.grad_norm") == 0.5
        assert m.gauge("train.update_ratio") == 0.25
