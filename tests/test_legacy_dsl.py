"""Legacy trainer_config_helpers DSL (reference
``trainer_config_helpers/layers.py`` 7,610 LoC, ``networks.py`` 1,813 LoC,
``evaluators.py`` 813 LoC): projections/mixed, math/structure layers,
recurrent_group + memory name-binding, generation beam_search, composite
networks, evaluators, and a reference-style config through parse_config."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as F
import paddle_tpu.trainer_config_helpers as tch
from paddle_tpu.trainer_config_helpers import networks as tnets
from paddle_tpu.v2 import data_type as dt


def _run(main, startup, feed, fetch):
    exe = fluid.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


# ---------------------------------------------------------------------------
# projections & mixed_layer
# ---------------------------------------------------------------------------

class TestMixedProjections:
    def test_mixed_with_form_and_identity(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 8)
            with tch.mixed_layer(size=8) as m:
                m += tch.identity_projection(x)
                m += tch.dotmul_operator(a=x, b=x, scale=0.0)
            out = m.output
        rng = np.random.RandomState(0)
        xv = rng.rand(3, 8).astype("f")
        (o,) = _run(main, startup, {"x": xv}, [out.name])
        np.testing.assert_allclose(np.asarray(o), xv, rtol=1e-6)

    def test_slice_and_offset_projection(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 6)
            sl = tch.mixed_layer(size=4, input=[
                tch.slice_projection(x, [(0, 2), (4, 6)])])
            off = tch.mixed_layer(size=3, input=[
                tch.identity_projection(x, offset=2, size=3)])
        xv = np.arange(12, dtype="f").reshape(2, 6)
        o1, o2 = _run(main, startup, {"x": xv}, [sl.name, off.name])
        np.testing.assert_allclose(np.asarray(o1),
                                   xv[:, [0, 1, 4, 5]], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(o2), xv[:, 2:5], rtol=1e-6)

    def test_full_matrix_and_table_and_scaling(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 5)
            ids = tch.data_layer("ids", 7, type=dt.integer_value(7))
            out = tch.mixed_layer(size=4, input=[
                tch.full_matrix_projection(x),
                tch.table_projection(ids, size=4),
                tch.scaling_projection(x) if False else
                tch.dotmul_projection(
                    tch.fc_layer(x, 4, bias_attr=False))],
                bias_attr=True, act="tanh")
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(3, 5).astype("f"),
                "ids": rng.randint(0, 7, (3, 1)).astype("int64")}
        (o,) = _run(main, startup, feed, [out.name])
        assert np.asarray(o).shape == (3, 4)
        assert np.isfinite(np.asarray(o)).all()

    def test_context_projection_window(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 2, type=dt.dense_vector_sequence(2))
            out = tch.mixed_layer(size=6, input=[
                tch.context_projection(x, context_len=3)])
        xv = np.arange(10, dtype="f").reshape(5, 2)
        lod = [[0, 3, 5]]
        (o,) = _run(main, startup, {"x": (xv, lod)}, [out.name])
        o = np.asarray(o)
        # row 0 of seq 0: window [-1, 0, 1] -> [0s, row0, row1]
        np.testing.assert_allclose(o[0], [0, 0, 0, 1, 2, 3], rtol=1e-6)
        # row 3 (first of seq 1): [0s, row3, row4]
        np.testing.assert_allclose(o[3], [0, 0, 6, 7, 8, 9], rtol=1e-6)


# ---------------------------------------------------------------------------
# math / structure layers (numerics)
# ---------------------------------------------------------------------------

class TestMathLayers:
    def test_numerics(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 6)
            y = tch.data_layer("y", 6)
            w = tch.data_layer("w", 1)
            interp = tch.interpolation_layer([x, y], w)
            powr = tch.power_layer(
                tch.slope_intercept_layer(x, 1.0, 2.0), w)
            l2d = tch.l2_distance_layer(x, y)
            dp = tch.dot_prod_layer(x, y)
            op = tch.out_prod_layer(x, y)
            s2o = tch.sum_to_one_norm_layer(
                tch.slope_intercept_layer(x, 1.0, 1.0))
            rep = tch.repeat_layer(x, 3)
            lc = tch.linear_comb_layer(weights=tch.fc_layer(
                x, 2, bias_attr=False), vectors=tch.repeat_layer(x, 2),
                size=6)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(4, 6).astype("f"),
                "y": rng.rand(4, 6).astype("f"),
                "w": rng.rand(4, 1).astype("f")}
        outs = _run(main, startup, feed,
                    [interp.name, powr.name, l2d.name, dp.name, op.name,
                     s2o.name, rep.name, lc.name])
        iv, pv, lv, dv, ov, sv, rv, lcv = [np.asarray(o) for o in outs]
        xf, yf, wf = feed["x"], feed["y"], feed["w"]
        np.testing.assert_allclose(iv, wf * xf + (1 - wf) * yf, rtol=1e-5)
        np.testing.assert_allclose(pv, (xf + 2.0) ** wf, rtol=1e-4)
        np.testing.assert_allclose(lv.reshape(-1),
                                   np.linalg.norm(xf - yf, axis=1),
                                   rtol=1e-5)
        np.testing.assert_allclose(dv.reshape(-1), (xf * yf).sum(1),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            ov, np.einsum("ni,nj->nij", xf, yf).reshape(4, -1), rtol=1e-5)
        np.testing.assert_allclose(sv.sum(1), np.ones(4), rtol=1e-5)
        np.testing.assert_allclose(rv, np.tile(xf, (1, 3)), rtol=1e-6)
        assert lcv.shape == (4, 6)

    def test_rotate_and_trans(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 6)
            rot = tch.rotate_layer(x, height=2, width=3)
            tr = tch.trans_layer(x)
        xv = np.arange(6, dtype="f").reshape(1, 6)
        ov, tv = _run(main, startup, {"x": xv}, [rot.name, tr.name])
        # [[0,1,2],[3,4,5]] rotated 90° CCW -> [[2,5],[1,4],[0,3]]
        np.testing.assert_allclose(np.asarray(ov).reshape(3, 2),
                                   [[2, 5], [1, 4], [0, 3]])
        assert np.asarray(tv).shape == (6, 1)

    def test_image_layers_shapes(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = F.data("img", shape=[2, 3, 8, 8], dtype="float32",
                         append_batch_size=False)
            up = tch.upsample_layer(img, scale=2)
            bi = tch.bilinear_interp_layer(img, out_size_x=5, out_size_y=4)
            ccn = tch.cross_channel_norm_layer(img)
            cmr = tch.img_cmrnorm_layer(img)
            mo = tch.maxout_layer(
                tch.img_conv_layer(img, 3, 4, act=None), groups=2)
        rng = np.random.RandomState(0)
        feed = {"img": rng.rand(2, 3, 8, 8).astype("f")}
        outs = _run(main, startup, feed,
                    [up.name, bi.name, ccn.name, cmr.name, mo.name])
        shapes = [np.asarray(o).shape for o in outs]
        assert shapes[0] == (2, 3, 16, 16)
        assert shapes[1] == (2, 3, 4, 5)
        assert shapes[2] == (2, 3, 8, 8)
        assert shapes[3] == (2, 3, 8, 8)
        assert shapes[4][1] == 2  # 4 channels maxout 2 groups

    def test_bilinear_interp_align_corners(self):
        # align-corners ratios: src = i*(in-1)/(out-1), the reference
        # BilinearInterpLayer convention
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = F.data("img", shape=[1, 1, 3, 3], dtype="float32",
                         append_batch_size=False)
            bi = tch.bilinear_interp_layer(img, out_size_x=5, out_size_y=5)
        xv = np.arange(9, dtype="f").reshape(1, 1, 3, 3)
        (o,) = _run(main, startup, {"img": xv}, [bi.name])
        pos = np.arange(5) * (3 - 1) / (5 - 1)
        lo = np.minimum(np.floor(pos).astype(int), 1)
        fr = pos - lo
        src = xv[0, 0]
        rows = src[lo, :] * (1 - fr)[:, None] + src[lo + 1, :] * fr[:, None]
        want = rows[:, lo] * (1 - fr)[None, :] + rows[:, lo + 1] * fr[None, :]
        np.testing.assert_allclose(np.asarray(o).reshape(5, 5), want,
                                   rtol=1e-5, atol=1e-6)

    def test_img_cmrnorm_scale_over_size(self):
        # the reference config_parser divides scale by the window size
        # before it reaches the LRN kernel (norm_conf.scale /= size)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = F.data("img", shape=[1, 4, 2, 2], dtype="float32",
                         append_batch_size=False)
            cmr = tch.img_cmrnorm_layer(img, size=4, scale=0.4, power=0.75)
            direct = F.lrn(img, n=4, alpha=0.1, beta=0.75)
        rng = np.random.RandomState(1)
        feed = {"img": rng.rand(1, 4, 2, 2).astype("f")}
        ov, dv = _run(main, startup, feed, [cmr.name, direct.name])
        np.testing.assert_allclose(np.asarray(ov), np.asarray(dv),
                                   rtol=1e-6)

    def test_sequence_reverse(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = F.data("x", shape=[-1, 2], dtype="float32",
                       append_batch_size=False, lod_level=1)
            rev = F.sequence_reverse(x)
        xv = np.arange(10, dtype="f").reshape(5, 2)
        lod = [[0, 2, 5]]
        (o,) = _run(main, startup, {"x": (xv, lod)}, [rev.name])
        np.testing.assert_allclose(np.asarray(o), xv[[1, 0, 4, 3, 2]])


# ---------------------------------------------------------------------------
# cost layers train
# ---------------------------------------------------------------------------

class TestCostLayers:
    def test_hsigmoid_bias_attr_false_skips_bias(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 10)
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(8))
            tch.hsigmoid(x, lbl, num_classes=8, bias_attr=False)
        n_bias = sum(1 for v in main.global_block().vars.values()
                     if getattr(v, "persistable", False)
                     and tuple(v.shape or ())[-1:] == (1,))
        assert n_bias == 0, "bias_attr=False must not create a bias"

    def test_hsigmoid_and_fm_train(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 10)
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(8))
            h = tch.fc_layer(x, 16, act="tanh")
            hs = tch.hsigmoid(h, lbl, num_classes=8)
            fm = tch.factorization_machine(x, factor_size=3)
            cost = hs + tch.sum_cost(tch.square_error_cost(
                fm, tch.data_layer("t", 1)))
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(16, 10).astype("f"),
                "lbl": rng.randint(0, 8, (16, 1)).astype("int64"),
                "t": rng.rand(16, 1).astype("f")}
        losses = []
        for _ in range(20):
            (l,) = fluid.Executor().run(main, feed=feed,
                                        fetch_list=[cost.name])
            losses.append(float(np.asarray(l).reshape(())))
        assert losses[-1] < losses[0], losses

    def test_huber_classification_and_selfnorm(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 4)
            ylbl = tch.data_layer("ylbl", 1)
            f = tch.fc_layer(x, 1, act=None)
            hc = tch.huber_classification_cost(f, ylbl)
            probs = tch.fc_layer(x, 5, act="softmax")
            sn = tch.cross_entropy_with_selfnorm(
                probs, tch.data_layer("c", 1, type=dt.integer_value(5)))
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(6, 4).astype("f"),
                "ylbl": rng.randint(0, 2, (6, 1)).astype("f"),
                "c": rng.randint(0, 5, (6, 1)).astype("int64")}
        o1, o2 = _run(main, startup, feed, [hc.name, sn.name])
        assert np.isfinite(np.asarray(o1)).all()
        assert np.isfinite(np.asarray(o2)).all()


# ---------------------------------------------------------------------------
# recurrent_group / memory / step layers / networks
# ---------------------------------------------------------------------------

class TestRecurrentGroup:
    def _seq_feed(self, rng, rows=9, dim=8):
        return (rng.rand(rows, dim).astype("f"), [[0, 2, 5, 9]])

    def test_named_memory_binding_trains(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            seq = tch.data_layer("seq", 8,
                                 type=dt.dense_vector_sequence(8))
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(3))

            def step(x):
                prev = tch.memory(name="acc", size=8)
                h = tch.addto_layer([x, prev], act="tanh", name="acc")
                return h

            out = tch.recurrent_group(step, seq)
            feat = tch.last_seq(out)
            probs = tch.fc_layer(feat, 3, act="softmax")
            cost = tch.classification_cost(probs, lbl)
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"seq": self._seq_feed(rng),
                "lbl": np.array([[0], [1], [2]], dtype="int64")}
        losses = []
        for _ in range(25):
            (l,) = exe.run(main, feed=feed, fetch_list=[cost.name])
            losses.append(float(np.asarray(l).reshape(())))
        assert losses[-1] < losses[0] * 0.9, losses

    def test_reverse_group_matches_reversed_input(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            seq = tch.data_layer("seq", 4,
                                 type=dt.dense_vector_sequence(4))

            def step(x):
                prev = tch.memory(name="m", size=4)
                h = tch.addto_layer([x, prev], name="m")  # running sum
                return h

            fwd = tch.recurrent_group(step, seq, name="f")
            last_fwd = tch.last_seq(fwd)
            bwd = tch.recurrent_group(step, seq, reverse=True, name="b")
            first_bwd = tch.first_seq(bwd)
        rng = np.random.RandomState(0)
        xv = rng.rand(5, 4).astype("f")
        lod = [[0, 2, 5]]
        o1, o2 = _run(main, startup, {"seq": (xv, lod)},
                      [last_fwd.name, first_bwd.name])
        # running sum over a sequence = same total either direction
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=1e-5)

    def test_lstmemory_group_and_bidirectional_gru(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            seq = tch.data_layer("seq", 6,
                                 type=dt.dense_vector_sequence(6))
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(2))
            lstm_out = tnets.lstmemory_group(input=tch.fc_layer(
                seq, 16, bias_attr=False), size=4, name="lg")
            bigru = tnets.bidirectional_gru(input=seq, size=3, name="bg")
            feat = tch.concat_layer([tch.last_seq(lstm_out), bigru])
            probs = tch.fc_layer(feat, 2, act="softmax")
            cost = tch.classification_cost(probs, lbl)
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"seq": (rng.rand(9, 6).astype("f"), [[0, 2, 5, 9]]),
                "lbl": np.array([[0], [1], [0]], dtype="int64")}
        losses = []
        for _ in range(15):
            (l,) = exe.run(main, feed=feed, fetch_list=[cost.name])
            losses.append(float(np.asarray(l).reshape(())))
        assert losses[-1] < losses[0], losses

    def test_attention_decoder_trains(self):
        DICT, EMB, HID = 20, 8, 10
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            src = tch.data_layer("src", DICT,
                                 type=dt.integer_value_sequence(DICT))
            trg = tch.data_layer("trg", DICT,
                                 type=dt.integer_value_sequence(DICT))
            lblseq = tch.data_layer("lblseq", DICT,
                                    type=dt.integer_value_sequence(DICT))
            src_emb = tch.embedding_layer(src, EMB)
            enc = tnets.simple_gru(input=src_emb, size=HID)
            enc_proj = tch.fc_layer(enc, HID, bias_attr=False)
            enc_last = tch.last_seq(enc)
            trg_emb = tch.embedding_layer(trg, EMB)

            def decoder_step(enc_seq, enc_p, cur_word):
                mem = tch.memory(name="dec", size=HID,
                                 boot_layer=enc_last)
                context = tnets.simple_attention(
                    encoded_sequence=enc_seq, encoded_proj=enc_p,
                    decoder_state=mem, name="att")
                inp = tch.mixed_layer(size=HID * 3, input=[
                    tch.full_matrix_projection(context),
                    tch.full_matrix_projection(cur_word)])
                h = tch.gru_step_layer(input=inp, output_mem=mem,
                                       size=HID, name="dec")
                return tch.fc_layer(h, DICT, act="softmax")

            preds = tch.recurrent_group(
                decoder_step,
                [tch.StaticInput(enc, is_seq=True),
                 tch.StaticInput(enc_proj, is_seq=True), trg_emb])
            cost = tch.cross_entropy(preds, lblseq)
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"src": (rng.randint(1, DICT, (7, 1)).astype("int64"),
                        [[0, 3, 7]]),
                "trg": (rng.randint(1, DICT, (6, 1)).astype("int64"),
                        [[0, 2, 6]]),
                "lblseq": (rng.randint(1, DICT, (6, 1)).astype("int64"),
                           [[0, 2, 6]])}
        losses = []
        for _ in range(20):
            (l,) = exe.run(main, feed=feed, fetch_list=[cost.name])
            losses.append(float(np.asarray(l).reshape(())))
        assert losses[-1] < losses[0] * 0.8, losses


class TestBeamSearchGeneration:
    def test_generation_with_trained_weights(self):
        DICT, EMB, HID = 20, 8, 10

        def make_step():
            def decoder_step(enc_tiled, cur_word):
                mem = tch.memory(name="dm", size=HID)
                inp = tch.mixed_layer(size=HID * 3, input=[
                    tch.full_matrix_projection(
                        enc_tiled, param_attr=fluid.ParamAttr("d_e.w")),
                    tch.full_matrix_projection(
                        cur_word, param_attr=fluid.ParamAttr("d_w.w"))])
                h = tch.gru_step_layer(
                    input=inp, output_mem=mem, size=HID, name="dm",
                    param_attr=fluid.ParamAttr("d_u.w"))
                return tch.fc_layer(h, DICT, act="softmax",
                                    param_attr=fluid.ParamAttr("d_o.w"),
                                    bias_attr=fluid.ParamAttr("d_o.b"))
            return decoder_step

        def encoder(src):
            emb = tch.embedding_layer(src, EMB,
                                      param_attr=fluid.ParamAttr("s_e.w"))
            proj = F.fc(emb, HID * 3, bias_attr=False,
                        param_attr=fluid.ParamAttr("e_p.w"))
            enc = F.dynamic_gru(proj, HID,
                                param_attr=fluid.ParamAttr("e_g.w"),
                                bias_attr=fluid.ParamAttr("e_g.b"))
            return tch.last_seq(enc)

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            src = tch.data_layer("src", DICT,
                                 type=dt.integer_value_sequence(DICT))
            trg = tch.data_layer("trg", DICT,
                                 type=dt.integer_value_sequence(DICT))
            lblseq = tch.data_layer("lblseq", DICT,
                                    type=dt.integer_value_sequence(DICT))
            enc_last = encoder(src)
            trg_emb = tch.embedding_layer(
                trg, EMB, param_attr=fluid.ParamAttr("t_e.w"))
            preds = tch.recurrent_group(
                make_step(), [tch.StaticInput(enc_last), trg_emb])
            cost = tch.cross_entropy(preds, lblseq)
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost)

        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            feed = {"src": (rng.randint(1, DICT, (7, 1)).astype("int64"),
                            [[0, 3, 7]]),
                    "trg": (rng.randint(1, DICT, (6, 1)).astype("int64"),
                            [[0, 2, 6]]),
                    "lblseq": (rng.randint(1, DICT, (6, 1))
                               .astype("int64"), [[0, 2, 6]])}
            for _ in range(5):
                exe.run(main, feed=feed, fetch_list=[cost.name])

            dec_prog, dec_start = fluid.Program(), fluid.Program()
            with fluid.program_guard(dec_prog, dec_start):
                src = tch.data_layer("src", DICT,
                                     type=dt.integer_value_sequence(DICT))
                enc_last = encoder(src)
                sent, scores = tch.beam_search(
                    make_step(),
                    input=[tch.StaticInput(enc_last),
                           tch.GeneratedInput(size=DICT,
                                              embedding_name="t_e.w",
                                              embedding_size=EMB)],
                    bos_id=1, eos_id=0, beam_size=3, max_length=5)
            ids, sc = exe.run(dec_prog, feed={"src": feed["src"]},
                              fetch_list=[sent, scores])
            ids, sc = np.asarray(ids), np.asarray(sc)
            assert ids.shape[:2] == (2, 3)
            assert np.isfinite(sc).all()
            assert (ids >= 0).all() and (ids < DICT).all()
            # scores sorted best-first within each batch row
            assert (np.diff(sc, axis=1) <= 1e-5).all()


# ---------------------------------------------------------------------------
# evaluators DSL
# ---------------------------------------------------------------------------

class TestEvaluatorsDSL:
    def test_classification_error_and_sums(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 6)
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(3))
            probs = tch.fc_layer(x, 3, act="softmax")
            err = tch.classification_error_evaluator(probs, lbl,
                                                     name="err")
            s = tch.sum_evaluator(probs, name="s")
            cs = tch.column_sum_evaluator(probs, name="cs")
        from paddle_tpu.trainer_config_helpers.evaluators import \
            evaluators_of
        evs = evaluators_of(main)
        assert set(evs) == {"err", "s", "cs"}
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(8, 6).astype("f"),
                "lbl": rng.randint(0, 3, (8, 1)).astype("int64")}
        ev, sv, csv = _run(main, startup, feed,
                           [err.name, s.name, cs.name])
        assert 0.0 <= float(np.asarray(ev).reshape(())) <= 1.0
        np.testing.assert_allclose(float(np.asarray(sv).reshape(())),
                                   8.0, rtol=1e-4)  # softmax rows sum to 1
        assert np.asarray(csv).shape == (3,)

    def test_precision_recall_and_auc(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 6)
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(2))
            probs = tch.fc_layer(x, 2, act="softmax")
            pr = tch.precision_recall_evaluator(probs, lbl, name="pr")
            auc = tch.auc_evaluator(probs, lbl, name="auc")
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(10, 6).astype("f"),
                "lbl": rng.randint(0, 2, (10, 1)).astype("int64")}
        prv, aucv = _run(main, startup, feed, [pr.name, auc.name])
        assert np.asarray(prv).shape == (6,)   # macro+micro P/R/F1
        assert 0.0 <= float(np.asarray(aucv).reshape(())) <= 1.0


# ---------------------------------------------------------------------------
# reference-style config through parse_config (the VERDICT done-criterion)
# ---------------------------------------------------------------------------

class TestLegacyConfigTrains:
    def test_sample_config_builds_and_trains(self):
        """A reference-style config (modeled on
        ``paddle/trainer/tests/sample_trainer_config.conf``: data ->
        fc layers + mixed projections -> classification_cost) parses
        through parse_config, rebuilds via build_programs, and trains."""
        from paddle_tpu.proto_config import parse_config, build_programs

        def config():
            tch.settings(batch_size=8, learning_rate=1e-2)
            x = tch.data_layer("x", 12)
            lbl = tch.data_layer("lbl", 1, type=dt.integer_value(4))
            with tch.mixed_layer(size=16, act="tanh",
                                 bias_attr=True) as m:
                m += tch.full_matrix_projection(x)
            h2 = tch.fc_layer(m.output, 16, act="relu")
            skip = tch.addto_layer([m.output, h2], act="tanh")
            probs = tch.fc_layer(skip, 4, act="softmax")
            cost = tch.classification_cost(probs, lbl)
            tch.classification_error_evaluator(probs, lbl, name="err")
            return tnets.outputs(cost)

        tc = parse_config(config)
        main, startup, outs = build_programs(tc)
        cost_var = outs[0]
        with fluid.program_guard(main, startup):
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(cost_var)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(16, 12).astype("f"),
                "lbl": rng.randint(0, 4, (16, 1)).astype("int64")}
        losses = []
        for _ in range(30):
            (l,) = exe.run(main, feed=feed, fetch_list=[cost_var.name])
            losses.append(float(np.asarray(l).reshape(())))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_vgg16_builds(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = F.data("img", shape=[1, 3, 32, 32], dtype="float32",
                         append_batch_size=False)
            out = tnets.vgg_16_network(img, num_channels=3,
                                       num_classes=10)
        assert out.shape[-1] == 10
        # 13 conv + 3 fc layers emitted
        convs = [op for op in main.global_block().ops
                 if op.type == "conv2d"]
        assert len(convs) == 13


class TestReviewRegressions:
    """Round-3 review findings: per-row sampling independence, stable
    lambda_cost, and the ctc_greedy_decoder/ctc_error_evaluator chain."""

    def test_sampling_id_rows_independent(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 4)
            ids = tch.sampling_id_layer(x)
        exe = fluid.Executor()
        exe.run(startup)
        probs = np.full((64, 4), 0.25, "float32")
        (o,) = exe.run(main, feed={"x": probs}, fetch_list=[ids.name])
        vals = np.asarray(o).reshape(-1)
        assert (vals >= 0).all() and (vals < 4).all()
        # 64 independent uniform draws over 4 classes: all-equal has
        # probability 4^-63 — seeing >1 distinct id proves per-row draws
        assert len(np.unique(vals)) > 1, vals

    def test_lambda_cost_stable_for_large_scores(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            s = tch.data_layer("s", 1)
            y = tch.data_layer("y", 1)
            cost = tch.lambda_cost(input=s, score=y)
        scores = np.array([[500.0], [-500.0], [0.0]], "float32")
        rel = np.array([[2.0], [0.0], [1.0]], "float32")
        (o,) = _run(main, startup, {"s": scores, "y": rel}, [cost.name])
        assert np.isfinite(np.asarray(o)).all()

    def test_ctc_error_evaluator_chain(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            logits = F.data("logits", shape=[-1, 5], dtype="float32",
                            append_batch_size=False, lod_level=1)
            lbl = F.data("lbl", shape=[-1, 1], dtype="int64",
                         append_batch_size=False, lod_level=1)
            ed = tch.ctc_error_evaluator(logits, lbl, name="ctc")
        # one sequence, 4 frames; argmax path = [1, 1, 0, 2] -> decode
        # merges/drops blanks(0) -> [1, 2]; label [1, 2] -> distance 0
        frames = np.zeros((4, 5), "float32")
        frames[0, 1] = frames[1, 1] = 5.0
        frames[2, 0] = 5.0
        frames[3, 2] = 5.0
        lbls = np.array([[1], [2]], "int64")
        (o,) = _run(main, startup,
                    {"logits": (frames, [[0, 4]]),
                     "lbl": (lbls, [[0, 2]])}, [ed.name])
        np.testing.assert_allclose(np.asarray(o).reshape(-1), [0.0])


class TestKmaxSeqScore:
    def _build(self, k):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            s = F.data("s", shape=[-1, 1], dtype="float32",
                       append_batch_size=False, lod_level=1)
            t = tch.kmax_seq_score_layer(s, beam_size=k)
        return main, startup, t

    def test_static_topk_indices(self):
        main, startup, t = self._build(3)
        sv = np.arange(10, dtype="f").reshape(-1, 1)
        (o,) = _run(main, startup, {"s": (sv, [[0, 4, 10]])}, [t.name])
        # reference semantics: WITHIN-SEQUENCE indexes of the top scores
        np.testing.assert_array_equal(np.asarray(o),
                                      [[3, 2, 1], [5, 4, 3]])

    def test_short_sequence_pads_minus_one(self):
        main, startup, t = self._build(3)
        sv = np.array([[0.5], [0.1], [0.9]], "f")
        (o,) = _run(main, startup, {"s": (sv, [[0, 2, 3]])}, [t.name])
        np.testing.assert_array_equal(np.asarray(o),
                                      [[0, 1, -1], [0, -1, -1]])

    def test_bucketed_matches_static(self):
        rng = np.random.RandomState(6)
        sv = rng.rand(9, 1).astype("f")
        lod = [[0, 2, 5, 9]]
        outs = {}
        for bucketed in (False, True):
            main, startup, t = self._build(2)
            main.lod_buckets = bucketed
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor()
                exe.run(startup)
                (o,) = exe.run(main, feed={"s": (sv, lod)},
                               fetch_list=[t.name])
            outs[bucketed] = np.asarray(o)
        # bucketed padding must not clobber any sequence's winners
        want = np.stack([np.argsort(sv[a:b, 0])[::-1][:2]
                         for a, b in zip(lod[0], lod[0][1:])])
        np.testing.assert_array_equal(outs[False], want)
        np.testing.assert_array_equal(outs[True], want)


class TestSubNestedSeq:
    def test_select_subsequences_by_kmax_ids(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = F.data("x", shape=[-1, 2], dtype="float32",
                       append_batch_size=False, lod_level=2)
            sel = F.data("sel", shape=[-1, 2], dtype="int64",
                         append_batch_size=False)
            out = tch.sub_nested_seq_layer(x, sel)
            pooled = F.sequence_pool(out, "sum")
        xv = np.arange(18, dtype="f").reshape(9, 2)
        lod = [[0, 2, 5], [0, 2, 5, 7, 8, 9]]
        sel_v = np.array([[1, -1], [2, 0]], "int64")
        o, p = _run(main, startup, {"x": (xv, lod), "sel": sel_v},
                    [out.name, pooled.name])
        # outer0 picks subseq 1 (rows 2-4); outer1 picks subseq 2 (row 8)
        # then subseq 0 (rows 5-6)
        np.testing.assert_allclose(np.asarray(o), xv[[2, 3, 4, 8, 5, 6]])
        assert np.asarray(p).shape == (3, 2)  # 3 selected sub-sequences


def test_sub_nested_seq_gradients_flow():
    """Beam training: gradients flow through the sub-sequence selection
    back to the upstream encoder (reference SubNestedSequenceLayer.cpp
    implements backward)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = F.data("x", shape=[-1, 2], dtype="float32",
                   append_batch_size=False, lod_level=2)
        sel = F.data("sel", shape=[-1, 2], dtype="int64",
                     append_batch_size=False)
        h = F.fc(x, 2, bias_attr=False,
                 param_attr=fluid.ParamAttr("sub_w"))
        h.lod_level = 2
        picked = tch.sub_nested_seq_layer(h, sel)
        loss = F.reduce_sum(F.square(picked))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.arange(18, dtype="f").reshape(9, 2)
    lod = [[0, 2, 5], [0, 2, 5, 7, 8, 9]]
    sel_v = np.array([[1, -1], [2, 0]], "int64")
    w0 = np.asarray(fluid.global_scope().find_var("sub_w")).copy()
    exe.run(main, feed={"x": (xv, lod), "sel": sel_v},
            fetch_list=[loss.name])
    w1 = np.asarray(fluid.global_scope().find_var("sub_w"))
    assert not np.allclose(w0, w1), "no gradient reached the encoder"


class TestProjectionWeightSharing:
    def test_tied_autoencoder_shares_one_matrix(self):
        """trans_full_matrix_projection's stated purpose: tie the decoder
        to the encoder's weight (used transposed).  One parameter, both
        directions; training moves the single matrix."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 6)
            enc = tch.mixed_layer(size=3, input=[
                tch.full_matrix_projection(
                    x, param_attr=fluid.ParamAttr("tied.w"))])
            dec = tch.mixed_layer(size=6, input=[
                tch.trans_full_matrix_projection(
                    enc, param_attr=fluid.ParamAttr("tied.w"))])
            cost = tch.sum_cost(tch.square_error_cost(dec, x))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(cost)
        # exactly ONE weight parameter exists
        from paddle_tpu.framework import Parameter
        params = [n for n, v in main.global_block().vars.items()
                  if isinstance(v, Parameter)]
        assert params == ["tied.w"], params
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(16, 6).astype("f")}
        losses = []
        for _ in range(30):
            (l,) = exe.run(main, feed=feed, fetch_list=[cost.name])
            losses.append(float(np.asarray(l).reshape(())))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_conv_operator_numeric(self):
        """conv_operator correlates the image with a graph-supplied
        filter (reference ConvOperator): identity 1x1 filter passes the
        image through."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = F.data("img", shape=[2, 1, 4, 4], dtype="float32",
                         append_batch_size=False)
            filt = F.data("filt", shape=[1, 1], dtype="float32",
                          append_batch_size=False)
            out = tch.mixed_layer(size=16, input=[
                tch.conv_operator(img=img, filter=filt, filter_size=1,
                                  num_filters=1, num_channels=1)])
        rng = np.random.RandomState(0)
        iv = rng.rand(2, 1, 4, 4).astype("f")
        (o,) = _run(main, startup,
                    {"img": iv, "filt": np.ones((1, 1), "f")}, [out.name])
        np.testing.assert_allclose(np.asarray(o), iv.reshape(2, 16),
                                   rtol=1e-6)


class TestRowConvAndScaleSubRegionShims:
    def test_row_conv_layer(self):
        """DSL shim over the fluid row_conv op (reference layers.py:6690);
        context_len = lookahead + 1, out[t] = sum_j w[j] * x[t+j]."""
        from paddle_tpu.initializer import NumpyArrayInitializer
        from paddle_tpu.param_attr import ParamAttr
        rng = np.random.RandomState(3)
        wv = rng.rand(3, 4).astype("float32")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = tch.data_layer("x", 4, type=dt.dense_vector_sequence(4))
            out = tch.row_conv_layer(
                x, context_len=3,
                param_attr=ParamAttr(initializer=NumpyArrayInitializer(wv)))
        xv = rng.rand(7, 4).astype("float32")
        lod = [[0, 4, 7]]
        (o,) = _run(main, startup, {"x": (xv, lod)}, [out.name])
        want = np.zeros_like(xv)
        for lo, hi in [(0, 4), (4, 7)]:
            for t in range(lo, hi):
                for j in range(3):
                    if t + j < hi:
                        want[t] += wv[j] * xv[t + j]
        np.testing.assert_allclose(np.asarray(o), want, rtol=1e-5,
                                   atol=1e-6)

    def test_scale_sub_region_layer(self):
        """DSL shim over the scale_sub_region op (reference
        layers.py:7493 / ScaleSubRegionLayer.cpp)."""
        rng = np.random.RandomState(4)
        xv = rng.rand(2, 2, 3, 3).astype("float32")
        idx = np.array([[1, 1, 1, 2, 1, 3],
                        [2, 2, 2, 3, 2, 2]], np.float32)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = F.data(name="x", shape=[2, 2, 3, 3],
                       append_batch_size=False)
            ind = F.data(name="ind", shape=[2, 6],
                         append_batch_size=False)
            out = tch.scale_sub_region_layer(x, ind, value=3.0)
        (o,) = _run(main, startup, {"x": xv, "ind": idx}, [out.name])
        want = xv.copy()
        want[0, 0:1, 0:2, 0:3] *= 3.0
        want[1, 1:2, 1:3, 1:2] *= 3.0
        np.testing.assert_allclose(np.asarray(o), want, rtol=1e-6)
