"""Scanner test for the shared op-metadata registry
(paddle_tpu/analysis/opmeta.py): the pure/effectful/stateful/sub-block
classification has ONE owner — if the dead-op lint, the optimization
passes, or the cost model grew a private effect-op list, a pass could
delete what a lint protects.  This test fails any module that does."""

import ast
import os
import re

import paddle_tpu
from paddle_tpu import layers
from paddle_tpu.analysis import lints, opmeta
from paddle_tpu.analysis.opt import passes as opt_passes

SRC_ROOT = os.path.dirname(os.path.abspath(paddle_tpu.__file__))

#: markers of a home-grown effect classification: any module (other
#: than opmeta) defining a frozenset/set literal containing BOTH
#: "channel_send" and "save_combine" is re-growing the effect-op list
_EFFECT_MARKERS = ("channel_send", "save_combine")


def _iter_sources():
    for dirpath, _, names in os.walk(SRC_ROOT):
        for n in sorted(names):
            if n.endswith(".py"):
                path = os.path.join(dirpath, n)
                with open(path) as f:
                    yield path, f.read()


def test_effect_op_list_has_one_owner():
    owners = []
    for path, text in _iter_sources():
        if all(m in text for m in _EFFECT_MARKERS):
            owners.append(os.path.relpath(path, SRC_ROOT))
    assert owners == [os.path.join("analysis", "opmeta.py")], (
        f"effect-op classification found outside the shared registry: "
        f"{owners} — import paddle_tpu.analysis.opmeta instead of "
        f"re-declaring the list")


def test_consumers_bind_the_shared_predicates():
    # the dead-op lint's exemption predicate IS the registry's
    assert lints._has_effects is opmeta.has_effects
    # the passes module resolves eligibility through the registry
    src = open(opt_passes.__file__).read()
    assert "opmeta.is_pure" in src and "opmeta.has_effects" in src
    # fusion's allow-list is the registry's, not a local copy
    assert "ELEMENTWISE_PURE_OPS" not in re.sub(
        r"opmeta\.ELEMENTWISE_PURE_OPS", "", src)


def test_classification_sanity():
    import paddle_tpu as fluid
    from paddle_tpu.ops import registry

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32")
        h = layers.fc(x, 4, act="relu")
        d = fluid.layers.dropout(h, dropout_prob=0.5)
        cost = fluid.layers.mean(h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    block = main.global_block()
    by_type = {}
    for op in block.ops:
        by_type.setdefault(op.type, op)

    relu = by_type["relu"]
    assert opmeta.is_pure(relu, block, registry)
    assert not opmeta.needs_rng_key(relu, registry)
    assert relu.type in opmeta.ELEMENTWISE_PURE_OPS

    dropout = by_type["dropout"]
    assert opmeta.has_effects(dropout, registry)      # RNG = effect
    assert opmeta.needs_rng_key(dropout, registry)
    assert dropout.type not in opmeta.ELEMENTWISE_PURE_OPS

    sgd = by_type["sgd"]
    assert opmeta.has_effects(sgd, registry)          # in-place state
    assert opmeta.stateful_output_names(sgd, registry)
    assert opmeta.writes_persistable(sgd, block)

    # unknown op types classify conservatively
    from paddle_tpu.framework import Operator
    mystery = Operator(block, "never_registered",
                       {"X": ["x"]}, {"Out": ["m"]}, {})
    assert opmeta.needs_rng_key(mystery, registry)

    # grads of RNG-free forwards never get keys; grads of RNG forwards do
    relu_grad = Operator(block, "relu_grad", {}, {}, {})
    assert not opmeta.needs_rng_key(relu_grad, registry)
    dropout_grad = by_type.get("dropout_grad")
    # (dropout registers an explicit key-free grad lowering, and it is
    # registered — so lookup succeeds and uses_rng is False)
    if dropout_grad is not None:
        assert not opmeta.uses_rng(dropout_grad, registry)


def test_sub_block_ops_classify_effectful():
    import paddle_tpu as fluid
    from paddle_tpu.ops import registry

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], dtype="float32",
                        append_batch_size=False)
        i = fluid.layers.zeros(shape=[1], dtype="int64")
        n = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                       value=3)
        cond = fluid.layers.less_than(x=i, y=n)
        w = fluid.layers.While(cond)
        with w.block():
            fluid.layers.increment(x=i, in_place=True)
            fluid.layers.less_than(x=i, y=n, cond=cond)
    block = main.global_block()
    while_op = next(op for op in block.ops if op.type == "while")
    assert opmeta.has_sub_block(while_op)
    assert opmeta.has_effects(while_op, registry)
    assert opmeta.needs_rng_key(while_op, registry)  # body may use RNG
