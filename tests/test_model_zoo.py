"""Model-zoo parity with the reference benchmark suite
(``benchmark/fluid/``): the two workloads added in r4 build and LEARN —
stacked dynamic LSTM (stacked_dynamic_lstm.py) and attention seq2seq
(machine_translation.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import seq2seq, stacked_lstm


def test_stacked_lstm_learns_parity_task():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_cost, acc, _ = stacked_lstm.stacked_lstm_net(
            dict_size=32, emb_dim=16, hidden_dim=16, n_layers=2)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
    exe = fluid.Executor()
    exe.run(startup)
    feed = stacked_lstm.fake_batch(16, 8, 32, seed=1)
    losses = []
    for _ in range(60):
        lv, av = exe.run(main, feed=feed,
                         fetch_list=[avg_cost.name, acc.name])
        losses.append(float(np.asarray(lv).reshape(())))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert float(np.asarray(av).reshape(())) > 0.8


def test_attention_seq2seq_learns_copyish_task():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        avg_cost, _ = seq2seq.seq_to_seq_net(
            src_dict_size=16, trg_dict_size=16, emb_dim=16,
            encoder_size=16, decoder_size=16)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
    exe = fluid.Executor()
    exe.run(startup)
    feed = seq2seq.fake_batch(8, 6, 5, 16, 16, seed=2)
    losses = []
    for _ in range(80):
        (lv,) = exe.run(main, feed=feed, fetch_list=[avg_cost.name])
        losses.append(float(np.asarray(lv).reshape(())))
    # trg[t] = f(trg[t-1], src[0]) is fully predictable once attention
    # reads the source
    assert losses[-1] < losses[0] - 1.0, (losses[0], losses[-1])
