"""Book test: seq2seq NMT with GRU encoder + DynamicRNN decoder converges
(reference ``python/paddle/fluid/tests/book/test_machine_translation.py``)."""

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.layers as layers


DICT = 64
EMB = 16
HID = 32
B = 8
SRC_LEN = 6
TRG_LEN = 5


def _batches(n, seed=0):
    """Synthetic copy-ish task: target tokens are a fixed function of
    source tokens — learnable with a small model."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        src = rng.randint(2, DICT, size=(B, SRC_LEN)).astype("int64")
        # autoregressive chain seeded by the source: next = 3*prev+1.
        # Teacher forcing makes every step after the first learnable from
        # trg_in alone; the first step needs the encoder state.
        trg_out = np.empty((B, TRG_LEN), "int64")
        trg_out[:, 0] = (src[:, 0] * 3 + 1) % DICT
        for t in range(1, TRG_LEN):
            trg_out[:, t] = (trg_out[:, t - 1] * 3 + 1) % DICT
        trg_in = np.concatenate(
            [np.ones((B, 1), "int64"), trg_out[:, :-1]], axis=1)
        src_lod = [list(range(0, B * SRC_LEN + 1, SRC_LEN))]
        trg_lod = [list(range(0, B * TRG_LEN + 1, TRG_LEN))]
        yield (src.reshape(-1, 1), src_lod,
               trg_in.reshape(-1, 1), trg_lod,
               trg_out.reshape(-1, 1))


def test_machine_translation():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = layers.data(name="src", shape=[-1, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        trg = layers.data(name="trg", shape=[-1, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        label = layers.data(name="label", shape=[-1, 1], dtype="int64",
                            append_batch_size=False, lod_level=1)

        src_emb = layers.embedding(input=src, size=[DICT, EMB])
        enc_proj = layers.fc(input=src_emb, size=HID * 3)
        enc = layers.dynamic_gru(input=enc_proj, size=HID)
        enc_last = layers.sequence_last_step(enc)

        trg_emb = layers.embedding(input=trg, size=[DICT, EMB])

        drnn = layers.DynamicRNN()
        with drnn.block():
            cur = drnn.step_input(trg_emb)
            mem = drnn.memory(init=enc_last)
            dec_h = layers.fc(input=[cur, mem], size=HID, act="tanh")
            drnn.update_memory(mem, dec_h)
            out = layers.fc(input=dec_h, size=DICT, act="softmax")
            drnn.output(out)
        predictions = drnn()

        cost = layers.cross_entropy(input=predictions, label=label)
        avg_cost = layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for src_f, src_lod, trg_f, trg_lod, lab in _batches(150):
        (lv,) = exe.run(
            main,
            feed={"src": (src_f, src_lod), "trg": (trg_f, trg_lod),
                  "label": (lab, trg_lod)},
            fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv).reshape(())))
    # the mapping trg=f(src) is deterministic; most of it is learnable
    # from trg_in alone (teacher forcing) — expect a big drop
    assert losses[-1] < 1.5 and losses[-1] < losses[0] - 2.0, (
        losses[0], losses[-1])
