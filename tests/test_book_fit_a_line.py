"""Book test: linear regression converges + save/load inference model
(reference ``python/paddle/fluid/tests/book/test_fit_a_line.py``)."""

import tempfile

import numpy as np

import paddle_tpu as fluid


def test_fit_a_line_converges(tmp_path):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        y_predict = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_cost = fluid.layers.mean(cost)
        sgd = fluid.optimizer.SGD(learning_rate=0.05)
        sgd.minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    train_reader = fluid.reader.shuffle(fluid.dataset.uci_housing.train(),
                                        buf_size=500)
    feeder = fluid.DataFeeder(place=fluid.CPUPlace(), feed_list=[x, y],
                              program=main)

    def batches(reader, bs):
        batch = []
        for sample in reader():
            batch.append(sample)
            if len(batch) == bs:
                yield batch
                batch = []

    first_loss = last_loss = None
    for epoch in range(12):
        for batch in batches(train_reader, 32):
            loss, = exe.run(main, feed=feeder.feed(batch),
                            fetch_list=[avg_cost])
            if first_loss is None:
                first_loss = float(loss)
            last_loss = float(loss)
    assert last_loss < first_loss * 0.25, (first_loss, last_loss)

    # save + reload inference model, check same predictions
    model_dir = str(tmp_path / "fit_a_line_model")
    fluid.io.save_inference_model(model_dir, ["x"], [y_predict], exe, main)

    infer_prog, feed_names, fetch_vars = fluid.io.load_inference_model(
        model_dir, exe)
    xs = np.random.RandomState(0).uniform(-1, 1, (8, 13)).astype("float32")
    ref_prog = fluid.io.get_inference_program([y_predict], main)
    ref, = exe.run(ref_prog, feed={"x": xs}, fetch_list=[y_predict])
    got, = exe.run(infer_prog, feed={feed_names[0]: xs},
                   fetch_list=fetch_vars)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
