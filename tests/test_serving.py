"""Serving runtime tests: Predictor, HTTP server, and the embeddable C
inference ABI (reference ``paddle/capi`` + ``inference/tests/book``)."""

import ctypes
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.serving import Predictor, InferenceServer


@pytest.fixture()
def model_dir(tmp_path):
    """Train a tiny regression and save an inference model."""
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype("float32")
    ys = (xs @ np.array([[1.0], [2.0], [3.0], [4.0]], "float32"))
    x = layers.data(name="x", shape=[8, 4], append_batch_size=False)
    y = layers.data(name="y", shape=[8, 1], append_batch_size=False)
    pred = layers.fc(input=x, size=1)
    loss = layers.mean(layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for _ in range(60):
        exe.run(fluid.default_main_program(), feed={"x": xs, "y": ys},
                fetch_list=[loss])
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [pred], exe)
    # reference predictions for the test inputs
    test_x = rng.rand(8, 4).astype("float32")
    (want,) = exe.run(fluid.io.get_inference_program([pred]),
                      feed={"x": test_x}, fetch_list=[pred])
    return d, test_x, np.asarray(want)


class TestPredictor:
    def test_run(self, model_dir):
        d, test_x, want = model_dir
        p = Predictor(d)
        assert p.feed_names == ["x"]
        (got,) = p.run({"x": test_x})
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestHTTPServer:
    def test_predict_roundtrip(self, model_dir):
        d, test_x, want = model_dir
        server = InferenceServer(d, port=0)
        server.start_background()
        try:
            host, port = server.addr
            meta = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/meta", timeout=30).read())
            assert meta["feeds"] == ["x"]
            req = urllib.request.Request(
                f"http://{host}:{port}/predict",
                data=json.dumps(
                    {"feeds": {"x": test_x.tolist()}}).encode(),
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(
                req, timeout=60).read())
            got = np.asarray(resp["outputs"][0], "float32")
            np.testing.assert_allclose(got, want, rtol=1e-4)
            health = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/health", timeout=30).read())
            assert health["status"] == "ok"
        finally:
            server.shutdown()


class TestCAPI:
    def test_c_abi_inference(self, model_dir):
        from paddle_tpu import native
        lib = native.load_capi()
        assert lib is not None, "native toolchain expected in image"
        d, test_x, want = model_dir
        assert lib.pd_tpu_init() == 0, lib.pd_tpu_last_error()
        h = lib.pd_tpu_create(d.encode())
        assert h, lib.pd_tpu_last_error()
        try:
            assert lib.pd_tpu_num_feeds(h) == 1
            assert lib.pd_tpu_feed_name(h, 0) == b"x"

            data = np.ascontiguousarray(test_x)
            names = (ctypes.c_char_p * 1)(b"x")
            bufs = (ctypes.c_void_p * 1)(
                data.ctypes.data_as(ctypes.c_void_p))
            lens = (ctypes.c_longlong * 1)(data.nbytes)
            shape = (ctypes.c_longlong * 2)(*data.shape)
            shapes = (ctypes.POINTER(ctypes.c_longlong) * 1)(shape)
            ranks = (ctypes.c_int * 1)(2)
            dtypes = (ctypes.c_char_p * 1)(b"float32")
            res = lib.pd_tpu_run(h, 1, names, bufs, lens, shapes, ranks,
                                 dtypes)
            assert res, lib.pd_tpu_last_error()
            try:
                assert lib.pd_tpu_result_count(res) == 1
                rank = lib.pd_tpu_result_rank(res, 0)
                out_shape = tuple(lib.pd_tpu_result_dim(res, 0, i)
                                  for i in range(rank))
                assert lib.pd_tpu_result_dtype(res, 0) == b"float32"
                blen = ctypes.c_longlong()
                ptr = lib.pd_tpu_result_data(res, 0, ctypes.byref(blen))
                raw = ctypes.string_at(ptr, blen.value)
                got = np.frombuffer(raw, "float32").reshape(out_shape)
                np.testing.assert_allclose(got, want, rtol=1e-5)
            finally:
                lib.pd_tpu_free_result(res)
        finally:
            lib.pd_tpu_destroy(h)
