"""Serving runtime tests: Predictor, HTTP server, and the embeddable C
inference ABI (reference ``paddle/capi`` + ``inference/tests/book``)."""

import ctypes
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from paddle_tpu.serving import (InferenceServer, Predictor, ServingClient,
                                ServingError)


@pytest.fixture()
def model_dir(tmp_path):
    """Train a tiny regression and save an inference model."""
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 4).astype("float32")
    ys = (xs @ np.array([[1.0], [2.0], [3.0], [4.0]], "float32"))
    x = layers.data(name="x", shape=[8, 4], append_batch_size=False)
    y = layers.data(name="y", shape=[8, 1], append_batch_size=False)
    pred = layers.fc(input=x, size=1)
    loss = layers.mean(layers.square_error_cost(input=pred, label=y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for _ in range(60):
        exe.run(fluid.default_main_program(), feed={"x": xs, "y": ys},
                fetch_list=[loss])
    d = str(tmp_path / "model")
    fluid.io.save_inference_model(d, ["x"], [pred], exe)
    # reference predictions for the test inputs
    test_x = rng.rand(8, 4).astype("float32")
    (want,) = exe.run(fluid.io.get_inference_program([pred]),
                      feed={"x": test_x}, fetch_list=[pred])
    return d, test_x, np.asarray(want)


class TestPredictor:
    def test_run(self, model_dir):
        d, test_x, want = model_dir
        p = Predictor(d)
        assert p.feed_names == ["x"]
        (got,) = p.run({"x": test_x})
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestHTTPServer:
    def test_predict_roundtrip(self, model_dir):
        d, test_x, want = model_dir
        server = InferenceServer(d, port=0)
        server.start_background()
        try:
            host, port = server.addr
            meta = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/meta", timeout=30).read())
            assert meta["feeds"] == ["x"]
            req = urllib.request.Request(
                f"http://{host}:{port}/predict",
                data=json.dumps(
                    {"feeds": {"x": test_x.tolist()}}).encode(),
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(
                req, timeout=60).read())
            got = np.asarray(resp["outputs"][0], "float32")
            np.testing.assert_allclose(got, want, rtol=1e-4)
            health = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/health", timeout=30).read())
            assert health["status"] == "ok"
        finally:
            server.shutdown()


class TestGracefulDegradation:
    """/healthz is liveness, /readyz gates traffic, requests that beat
    the model load get 503 + retryable (not a crash/hang), errors are
    structured JSON, and saturation sheds load."""

    def _get(self, host, port, path):
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{port}{path}", timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def _post(self, host, port, path, obj):
        req = urllib.request.Request(
            f"http://{host}:{port}{path}", data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_requests_before_load_get_503_retryable(self, model_dir):
        from paddle_tpu.fault import chaos

        d, test_x, want = model_dir
        # hold the model load long enough to observe the loading window
        chaos.inject("serving.load", delay=1.0)
        try:
            server = InferenceServer(d, port=0, async_load=True)
            server.start_background()
            host, port = server.addr
            code, body = self._get(host, port, "/healthz")
            assert code == 200                     # alive while loading
            code, body = self._get(host, port, "/readyz")
            assert code == 503 and body["retryable"] is True
            assert body["error"]["type"] == "model_loading"
            code, body = self._post(host, port, "/run",
                                    {"feeds": {"x": test_x.tolist()}})
            assert code == 503 and body["retryable"] is True
            # once loaded, the same request succeeds
            assert server.wait_until_ready(60)
            code, body = self._get(host, port, "/readyz")
            assert code == 200
            code, body = self._post(host, port, "/run",
                                    {"feeds": {"x": test_x.tolist()}})
            assert code == 200
            np.testing.assert_allclose(
                np.asarray(body["outputs"][0], "float32"), want, rtol=1e-4)
            server.shutdown()
        finally:
            chaos.clear()

    def test_structured_errors_with_retryable_flag(self, model_dir):
        d, _, _ = model_dir
        server = InferenceServer(d, port=0)
        server.start_background()
        try:
            host, port = server.addr
            # bad feed name -> 400, permanent
            code, body = self._post(host, port, "/predict",
                                    {"feeds": {"nope": [1.0]}})
            assert code == 400 and body["retryable"] is False
            assert set(body["error"]) == {"type", "message"}
            # unknown route -> structured 404
            code, body = self._get(host, port, "/nope")
            assert code == 404 and body["error"]["type"] == "not_found"
        finally:
            server.shutdown()

    def test_load_shedding_when_saturated(self, model_dir):
        d, test_x, _ = model_dir
        server = InferenceServer(d, port=0, max_inflight=1)
        server.start_background()
        try:
            host, port = server.addr
            # saturate the single slot from another thread
            import threading
            from paddle_tpu.fault import chaos
            chaos.inject("serving.run", delay=1.5, times=1)
            slow = threading.Thread(
                target=self._post, args=(host, port, "/predict",
                                         {"feeds": {"x": test_x.tolist()}}))
            slow.start()
            time.sleep(0.3)  # let the slow request take the slot
            code, body = self._post(host, port, "/predict",
                                    {"feeds": {"x": test_x.tolist()}})
            assert code == 503 and body["error"]["type"] == "overloaded"
            assert body["retryable"] is True
            slow.join()
            chaos.clear()
            # slot free again: next request succeeds
            code, _ = self._post(host, port, "/predict",
                                 {"feeds": {"x": test_x.tolist()}})
            assert code == 200
        finally:
            server.shutdown()


class TestServingClient:
    def test_predict_retries_through_model_load(self, model_dir):
        """The retrying client rides out the 503 loading window that
        would kill a naive caller (the serving analog of the master RPC
        retry path)."""
        from paddle_tpu.fault import RetryPolicy, chaos

        d, test_x, want = model_dir
        chaos.inject("serving.load", delay=1.0)
        try:
            server = InferenceServer(d, port=0, async_load=True)
            server.start_background()
            client = ServingClient(server.addr, retry=RetryPolicy(
                max_attempts=30, base_delay=0.1, max_delay=0.25, jitter=0))
            assert client.healthy()              # liveness: up immediately
            assert not client.ready()            # readiness: still loading
            (got,) = client.predict({"x": test_x})  # retries until ready
            np.testing.assert_allclose(got, want, rtol=1e-4)
            assert client.ready()
            server.shutdown()
        finally:
            chaos.clear()

    def test_failed_async_load_surfaces_not_hangs(self, tmp_path):
        server = InferenceServer(str(tmp_path / "no_such_model"), port=0,
                                 async_load=True)
        server.start_background()
        try:
            # wait_until_ready must raise the load error, not block
            with pytest.raises(Exception):
                server.wait_until_ready(timeout=60)
            assert server.load_error is not None
            client = ServingClient(server.addr)
            assert client.healthy() and not client.ready()
            with pytest.raises(ServingError) as ei:
                client.predict({"x": [1.0]})
            assert ei.value.etype == "model_load_failed"
            assert ei.value.retryable is False
        finally:
            server.shutdown()

    def test_permanent_errors_not_retried(self, model_dir):
        d, _, _ = model_dir
        server = InferenceServer(d, port=0)
        server.start_background()
        try:
            client = ServingClient(server.addr)
            with pytest.raises(ServingError) as ei:
                client.predict({"wrong_name": [1.0, 2.0]})
            assert ei.value.retryable is False
        finally:
            server.shutdown()


class TestCAPI:
    def test_c_abi_inference(self, model_dir):
        from paddle_tpu import native
        lib = native.load_capi()
        assert lib is not None, "native toolchain expected in image"
        d, test_x, want = model_dir
        assert lib.pd_tpu_init() == 0, lib.pd_tpu_last_error()
        h = lib.pd_tpu_create(d.encode())
        assert h, lib.pd_tpu_last_error()
        try:
            assert lib.pd_tpu_num_feeds(h) == 1
            assert lib.pd_tpu_feed_name(h, 0) == b"x"

            data = np.ascontiguousarray(test_x)
            names = (ctypes.c_char_p * 1)(b"x")
            bufs = (ctypes.c_void_p * 1)(
                data.ctypes.data_as(ctypes.c_void_p))
            lens = (ctypes.c_longlong * 1)(data.nbytes)
            shape = (ctypes.c_longlong * 2)(*data.shape)
            shapes = (ctypes.POINTER(ctypes.c_longlong) * 1)(shape)
            ranks = (ctypes.c_int * 1)(2)
            dtypes = (ctypes.c_char_p * 1)(b"float32")
            res = lib.pd_tpu_run(h, 1, names, bufs, lens, shapes, ranks,
                                 dtypes)
            assert res, lib.pd_tpu_last_error()
            try:
                assert lib.pd_tpu_result_count(res) == 1
                rank = lib.pd_tpu_result_rank(res, 0)
                out_shape = tuple(lib.pd_tpu_result_dim(res, 0, i)
                                  for i in range(rank))
                assert lib.pd_tpu_result_dtype(res, 0) == b"float32"
                blen = ctypes.c_longlong()
                ptr = lib.pd_tpu_result_data(res, 0, ctypes.byref(blen))
                raw = ctypes.string_at(ptr, blen.value)
                got = np.frombuffer(raw, "float32").reshape(out_shape)
                np.testing.assert_allclose(got, want, rtol=1e-5)
            finally:
                lib.pd_tpu_free_result(res)
        finally:
            lib.pd_tpu_destroy(h)
