"""bench_fleet smoke: aggregate RPS must scale >= 1.7x from 1 to 3
router-fronted replicas (device time modeled with sleeps per the 2-vCPU
bench-host constraint), and the kill drill — hard-kill one replica
mid-load — must lose zero requests.  BENCH_FLEET.json records the full
acceptance run."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

import bench_fleet  # noqa: E402


def _bench_with_retries(attempts, target_scaling, **kw):
    """Best-of-N against noisy-neighbor CPU: external load can only
    UNDERSTATE the scaling (the capability is queueing math over
    sleeps), so one clean run suffices.  The kill drill's zero-lost
    invariant must hold on EVERY attempt."""
    last = None
    for _ in range(attempts):
        last = bench_fleet.run_bench(**kw)
        assert last["kill_drill"]["failures"] == 0, last["kill_drill"]
        if last["scaling"] is not None and \
                last["scaling"] >= target_scaling:
            return last
    return last


@pytest.fixture(scope="module")
def smoke_summary():
    return _bench_with_retries(3, 1.7, clients=6, duration=1.2,
                               service_ms=30.0)


def test_summary_schema(smoke_summary):
    assert {"clients", "duration_sec", "service_ms", "fleet",
            "scaling", "kill_drill"} <= set(smoke_summary)
    for mode in ("1", "3"):
        stats = smoke_summary["fleet"][mode]
        assert {"rps", "requests_ok", "failures",
                "latency_ms"} <= set(stats)
        assert stats["requests_ok"] > 0


def test_rps_scales_with_replicas(smoke_summary):
    assert smoke_summary["scaling"] is not None
    assert smoke_summary["scaling"] >= 1.7, smoke_summary


def test_kill_drill_loses_zero_requests(smoke_summary):
    drill = smoke_summary["kill_drill"]
    assert drill["failures"] == 0, drill
    assert len(drill["killed"]) == 1          # the failpoint fired once
    assert drill["requests_ok"] > 0
    # the kill was survived BY failover, not by luck: at least one
    # request completed on a different replica than it first tried
    assert drill["failovers"] >= 1, drill


def test_healthy_modes_never_fail_over(smoke_summary):
    for mode in ("1", "3"):
        assert smoke_summary["fleet"][mode]["failures"] == 0
    assert smoke_summary["fleet"]["1"]["killed"] == []


def test_trajectory_gate_wiring(smoke_summary, tmp_path):
    """The smoke run's metrics flow through the shared recorder into a
    trajectory `paddle_tpu bench check` accepts — and a synthetically
    degraded follow-up run flips the gate to exit-1 (the regression
    the trajectory exists to catch)."""
    from paddle_tpu import cli
    from paddle_tpu.obs import bench_history

    path = str(tmp_path / "traj.json")
    metrics = bench_history.summary_metrics("fleet", smoke_summary)
    bench_history.record("fleet", metrics, path=path, baseline=True,
                         source="test_bench_fleet")
    bench_history.record("fleet", dict(metrics), path=path)
    assert cli.main(["bench", "check", "--trajectory", path]) == 0
    degraded = dict(metrics, scaling=1.0,
                    rps_aggregate=metrics["rps_aggregate"] / 10)
    bench_history.record("fleet", degraded, path=path)
    assert cli.main(["bench", "check", "--trajectory", path]) == 1


@pytest.mark.slow
def test_acceptance_full_run():
    summary = _bench_with_retries(4, 1.7, clients=8, duration=3.0,
                                  service_ms=30.0)
    assert summary["scaling"] >= 1.7, summary
    assert summary["kill_drill"]["failures"] == 0
