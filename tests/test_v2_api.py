"""v2 API shim: the reference README's MNIST flow end-to-end
(reference ``python/paddle/v2/tests/`` + book examples)."""

import io

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.v2 as paddle


def test_v2_mnist_train_and_infer():
    paddle.init(use_gpu=False, trainer_count=1)

    images = paddle.layer.data(
        name="pixel", type=paddle.data_type.dense_vector(784))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(10))
    hidden = paddle.layer.fc(input=images, size=64,
                             act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=hidden, size=10,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Adam(learning_rate=2e-3)
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    events = {"iters": 0, "last_err": 1.0, "passes": 0}

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            events["iters"] += 1
            events["last_err"] = e.metrics.get(
                "classification_error_evaluator", 1.0)
        elif isinstance(e, paddle.event.EndPass):
            events["passes"] += 1

    def limited_train():
        src = paddle.dataset.mnist.train()()
        for i, s in enumerate(src):
            if i >= 64 * 40:
                return
            yield s

    trainer.train(
        reader=paddle.batch(lambda: limited_train(), 64),
        num_passes=2, event_handler=handler,
        feeding={"pixel": 0, "label": 1})

    assert events["passes"] == 2
    assert events["iters"] > 0
    assert events["last_err"] < 0.25, events["last_err"]

    # parameters round-trip through tar
    buf = io.BytesIO()
    parameters.to_tar(buf)
    buf.seek(0)
    parameters.init_from_tar(buf)

    # inference
    samples = [s for i, s in enumerate(paddle.dataset.mnist.test()())
               if i < 32]
    probs = paddle.infer(output_layer=predict, parameters=parameters,
                         input=samples, feeding={"pixel": 0})
    assert probs.shape == (32, 10)
    acc = (probs.argmax(1) == np.asarray([s[1] for s in samples])).mean()
    assert acc > 0.7, acc


def test_v2_sequence_model():
    paddle.init()
    dict_dim = 200
    words = paddle.layer.data(
        name="words",
        type=paddle.data_type.integer_value_sequence(dict_dim))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=16)
    lstm = paddle.networks.simple_lstm(input=emb, size=16)
    pooled = paddle.layer.pooling(input=lstm,
                                  pooling_type=paddle.layer.Max())
    predict = paddle.layer.fc(input=pooled, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    rng = np.random.RandomState(0)

    def reader():
        for _ in range(6 * 16):
            lab = int(rng.randint(0, 2))
            lo, hi = (0, 100) if lab == 0 else (100, 200)
            seq = list(rng.randint(lo, hi, size=12))
            yield seq, lab

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(reader=paddle.batch(reader, 16), num_passes=3,
                  event_handler=handler,
                  feeding={"words": 0, "label": 1})
    assert costs[-1] < costs[0], (costs[0], costs[-1])


class TestV2ExtendedLayers:
    """Legacy gserver layer-type subset added for V4 parity: crf, max_id,
    rank_cost, huber_cost, scaling, slope_intercept."""

    def test_crf_tagging_path(self):
        import paddle_tpu.layers as F
        from paddle_tpu.v2 import layer as v2l
        em = F.data(name="em", shape=[6, 4], append_batch_size=False,
                    lod_level=1)
        lab = F.data(name="lab", shape=[6, 1], append_batch_size=False,
                     dtype="int64", lod_level=1)
        cost = v2l.crf(input=em, label=lab,
                       param_attr=fluid.ParamAttr(name="v2crfw"))
        decoded = v2l.crf_decoding(input=em,
                                   param_attr=fluid.ParamAttr(name="v2crfw"))
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        lod = [[0, 3, 6]]
        nll, path = exe.run(
            fluid.default_main_program(),
            feed={"em": (rng.rand(6, 4).astype("float32"), lod),
                  "lab": (rng.randint(0, 4, (6, 1)).astype("int64"), lod)},
            fetch_list=[cost, decoded])
        assert np.isfinite(np.asarray(nll)).all()
        assert np.asarray(path).shape == (6, 1) or \
            np.asarray(path).size == 6

    def test_misc_layers(self):
        import paddle_tpu.layers as F
        from paddle_tpu.v2 import layer as v2l
        x = F.data(name="x", shape=[4, 5], append_batch_size=False)
        mid = v2l.max_id(v2l.fc(input=x, size=3, act="softmax"))
        si = v2l.slope_intercept(x, slope=2.0, intercept=1.0)
        w = F.data(name="w", shape=[4, 1], append_batch_size=False)
        sc = v2l.scaling(x, w)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(1)
        xv = rng.rand(4, 5).astype("float32")
        wv = rng.rand(4, 1).astype("float32")
        mv, sv, scv = exe.run(fluid.default_main_program(),
                              feed={"x": xv, "w": wv},
                              fetch_list=[mid, si, sc])
        assert np.asarray(mv).shape[0] == 4
        np.testing.assert_allclose(np.asarray(sv), xv * 2.0 + 1.0,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(scv), xv * wv, rtol=1e-6)

    def test_cost_layers(self):
        import paddle_tpu.layers as F
        from paddle_tpu.v2 import layer as v2l
        left = F.data(name="l", shape=[4, 1], append_batch_size=False)
        right = F.data(name="r", shape=[4, 1], append_batch_size=False)
        lab = F.data(name="lb", shape=[4, 1], append_batch_size=False)
        rc = v2l.rank_cost(left, right, lab)
        x = F.data(name="hx", shape=[4, 1], append_batch_size=False)
        y = F.data(name="hy", shape=[4, 1], append_batch_size=False)
        hc = v2l.huber_cost(x, y)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(2)
        out = exe.run(fluid.default_main_program(),
                      feed={"l": rng.rand(4, 1).astype("float32"),
                            "r": rng.rand(4, 1).astype("float32"),
                            "lb": (rng.rand(4, 1) > 0.5).astype("float32"),
                            "hx": rng.rand(4, 1).astype("float32"),
                            "hy": rng.rand(4, 1).astype("float32")},
                      fetch_list=[rc, hc])
        for v in out:
            assert np.isfinite(np.asarray(v)).all()


def test_v2_trainer_surfaces_dsl_evaluators():
    """Evaluators declared through the legacy DSL ride the trainer's
    event metrics (reference: the trainer polls Evaluator objects each
    batch)."""
    import paddle_tpu.v2 as paddle
    import paddle_tpu.trainer_config_helpers as tch

    x = paddle.layer.data(name="ev_x", type=paddle.data_type.dense_vector(8))
    label = paddle.layer.data(name="ev_lbl",
                              type=paddle.data_type.integer_value(3))
    predict = paddle.layer.fc(input=x, size=3,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    tch.sum_evaluator(predict, name="psum")

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))

    seen = {}

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            seen.update(e.metrics)

    rng = np.random.RandomState(0)

    def reader():
        for _ in range(32):
            yield rng.rand(8).astype("float32"), int(rng.randint(0, 3))

    trainer.train(paddle.batch(reader, batch_size=8), num_passes=1,
                  event_handler=handler)
    assert any(k.startswith("psum.") for k in seen), seen
    v = [v for k, v in seen.items() if k.startswith("psum.")][0]
    np.testing.assert_allclose(float(np.asarray(v).reshape(())), 8.0,
                               rtol=1e-4)  # softmax rows sum to 1
