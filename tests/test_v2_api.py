"""v2 API shim: the reference README's MNIST flow end-to-end
(reference ``python/paddle/v2/tests/`` + book examples)."""

import io

import numpy as np

import paddle_tpu.v2 as paddle


def test_v2_mnist_train_and_infer():
    paddle.init(use_gpu=False, trainer_count=1)

    images = paddle.layer.data(
        name="pixel", type=paddle.data_type.dense_vector(784))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(10))
    hidden = paddle.layer.fc(input=images, size=64,
                             act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=hidden, size=10,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Adam(learning_rate=2e-3)
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    events = {"iters": 0, "last_err": 1.0, "passes": 0}

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            events["iters"] += 1
            events["last_err"] = e.metrics.get(
                "classification_error_evaluator", 1.0)
        elif isinstance(e, paddle.event.EndPass):
            events["passes"] += 1

    def limited_train():
        src = paddle.dataset.mnist.train()()
        for i, s in enumerate(src):
            if i >= 64 * 40:
                return
            yield s

    trainer.train(
        reader=paddle.batch(lambda: limited_train(), 64),
        num_passes=2, event_handler=handler,
        feeding={"pixel": 0, "label": 1})

    assert events["passes"] == 2
    assert events["iters"] > 0
    assert events["last_err"] < 0.25, events["last_err"]

    # parameters round-trip through tar
    buf = io.BytesIO()
    parameters.to_tar(buf)
    buf.seek(0)
    parameters.init_from_tar(buf)

    # inference
    samples = [s for i, s in enumerate(paddle.dataset.mnist.test()())
               if i < 32]
    probs = paddle.infer(output_layer=predict, parameters=parameters,
                         input=samples, feeding={"pixel": 0})
    assert probs.shape == (32, 10)
    acc = (probs.argmax(1) == np.asarray([s[1] for s in samples])).mean()
    assert acc > 0.7, acc


def test_v2_sequence_model():
    paddle.init()
    dict_dim = 200
    words = paddle.layer.data(
        name="words",
        type=paddle.data_type.integer_value_sequence(dict_dim))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=16)
    lstm = paddle.networks.simple_lstm(input=emb, size=16)
    pooled = paddle.layer.pooling(input=lstm,
                                  pooling_type=paddle.layer.Max())
    predict = paddle.layer.fc(input=pooled, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    rng = np.random.RandomState(0)

    def reader():
        for _ in range(6 * 16):
            lab = int(rng.randint(0, 2))
            lo, hi = (0, 100) if lab == 0 else (100, 200)
            seq = list(rng.randint(lo, hi, size=12))
            yield seq, lab

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(reader=paddle.batch(reader, 16), num_passes=3,
                  event_handler=handler,
                  feeding={"words": 0, "label": 1})
    assert costs[-1] < costs[0], (costs[0], costs[-1])
