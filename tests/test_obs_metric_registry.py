"""Registry cross-check: every metric name the runtime emits must be
documented in docs/observability.md.

The scanner walks ``paddle_tpu/`` source for emission sites
(``runtime_metrics.inc/observe/bucket/set_gauge`` literals,
``record_latency(...)`` literals, ``self._metrics + ".suffix"`` stage
patterns, and the jax-monitoring mirror tables in profiler.py) and
fails naming any emitted metric the doc's registry table misses — so a
PR adding a counter without documenting it fails here, not in a 3am
dashboard hunt."""

import os
import re

import paddle_tpu

SRC_ROOT = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
DOC = os.path.join(os.path.dirname(SRC_ROOT), "docs", "observability.md")

# literal emissions; \s* spans the line breaks black-style wrapping adds
_LITERAL = re.compile(
    r"\.(?:inc|observe|bucket|set_gauge)\(\s*[\"']([a-zA-Z0-9_.]+)[\"']")
_LATENCY = re.compile(r"record_latency\(\s*[\"']([a-zA-Z0-9_.]+)[\"']")
# dynamic per-stage emissions: self._metrics + ".suffix" inside an
# inc/observe/set_gauge call -> datapipe.<stage>.suffix
_STAGE = re.compile(
    r"\.(?:inc|observe|bucket|set_gauge)\(\s*\n?\s*self\._metrics\s*\+"
    r"\s*[\"']\.([a-zA-Z0-9_]+)[\"']")
# jax monitoring mirror tables (profiler.py): mapped target names
_MIRROR = re.compile(r"[\"']((?:compile|compile_cache)\.[a-zA-Z0-9_.]+)[\"']")


def _iter_sources():
    for dirpath, _, names in os.walk(SRC_ROOT):
        for n in sorted(names):
            if n.endswith(".py"):
                with open(os.path.join(dirpath, n)) as f:
                    yield os.path.join(dirpath, n), f.read()


def emitted_metric_names():
    names = set()
    latency_series = set()
    for path, text in _iter_sources():
        names.update(_LITERAL.findall(text))
        found = _LATENCY.findall(text)
        latency_series.update(found)
        names.update(found)
        for suffix in _STAGE.findall(text):
            names.add(f"datapipe.<stage>.{suffix}")
        if path.endswith("profiler.py"):
            names.update(_MIRROR.findall(text))
    # record_latency's exception path derives <series>.errors for every
    # literal series it is given
    names.update(f"{n}.errors" for n in latency_series)
    return names


def documented_metric_names():
    with open(DOC) as f:
        doc = f.read()
    # registry rows are "| `name` | kind | ..." in the metric table
    return set(re.findall(r"^\|\s*`([a-zA-Z0-9_.<>]+)`\s*\|", doc,
                          flags=re.M))


def _is_documented(name, documented):
    if name in documented:
        return True
    # <series>.errors documents the whole record_latency error family
    if name.endswith(".errors") and "<series>.errors" in documented:
        return True
    # a concrete datapipe.<stage>.suffix emission (none today — stages
    # always use self._metrics) maps onto its placeholder row
    m = re.match(r"datapipe\.[a-zA-Z0-9_]+\.([a-zA-Z0-9_]+)$", name)
    if m and f"datapipe.<stage>.{m.group(1)}" in documented:
        return True
    return False


class TestMetricRegistry:
    def test_scanner_finds_known_emissions(self):
        """The scanner itself must keep seeing the load-bearing names —
        an over-tight regex silently passing the doc check is worse
        than a missing doc row."""
        emitted = emitted_metric_names()
        assert {"jit_cache.hits", "serving.requests_ok",
                "executor.step_seconds", "serving.request_seconds",
                "serving.batch_occupancy", "compile_cache.hits",
                "datapipe.<stage>.wait_seconds",
                "datapipe.<stage>.queue_depth",
                "datapipe.step_seconds.errors"} <= emitted

    def test_every_emitted_metric_is_documented(self):
        emitted = emitted_metric_names()
        documented = documented_metric_names()
        assert documented, f"no registry table parsed from {DOC}"
        missing = sorted(n for n in emitted
                         if not _is_documented(n, documented))
        assert not missing, (
            f"metrics emitted by the runtime but missing from the "
            f"docs/observability.md registry table: {missing}")
