"""Book test: IMDB sentiment via conv-pool and stacked-LSTM nets
(reference ``python/paddle/fluid/tests/book/test_understand_sentiment.py``,
``benchmark/fluid/stacked_dynamic_lstm.py``)."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers


CLIP_LEN = 24  # fixed length => one compiled executable (bucketing)
BATCH = 16
EMB = 32
HID = 32


def _batches(n_batches):
    dict_dim = fluid.dataset.imdb._VOCAB
    reader = fluid.dataset.imdb.train()
    ids, labels = [], []
    for sample, label in reader():
        if len(sample) < CLIP_LEN:
            continue
        ids.append(sample[:CLIP_LEN])
        labels.append(label)
        if len(ids) == BATCH:
            flat = np.asarray(ids, "int64").reshape(-1, 1)
            lod = [list(range(0, BATCH * CLIP_LEN + 1, CLIP_LEN))]
            yield flat, lod, np.asarray(labels, "int64").reshape(-1, 1)
            ids, labels = [], []
            n_batches -= 1
            if n_batches == 0:
                return


def _convolution_net(data, label, dict_dim):
    emb = layers.embedding(input=data, size=[dict_dim, EMB])
    conv3 = fluid.nets.sequence_conv_pool(
        input=emb, num_filters=HID, filter_size=3, act="tanh",
        pool_type="sqrt")
    conv4 = fluid.nets.sequence_conv_pool(
        input=emb, num_filters=HID, filter_size=4, act="tanh",
        pool_type="sqrt")
    prediction = layers.fc(input=[conv3, conv4], size=2, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    return layers.mean(cost), layers.accuracy(input=prediction, label=label)


def _stacked_lstm_net(data, label, dict_dim, stacked_num=3):
    emb = layers.embedding(input=data, size=[dict_dim, EMB])
    fc1 = layers.fc(input=emb, size=HID * 4)
    lstm1, cell1 = layers.dynamic_lstm(input=fc1, size=HID * 4,
                                       use_peepholes=False)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = layers.fc(input=inputs, size=HID * 4)
        lstm, cell = layers.dynamic_lstm(
            input=fc, size=HID * 4, is_reverse=(i % 2) == 0,
            use_peepholes=False)
        inputs = [fc, lstm]
    fc_last = layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = layers.sequence_pool(input=inputs[1], pool_type="max")
    prediction = layers.fc(input=[fc_last, lstm_last], size=2,
                           act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    return layers.mean(cost), layers.accuracy(input=prediction, label=label)


@pytest.mark.parametrize("net", ["conv", "stacked_lstm"])
def test_understand_sentiment(net):
    dict_dim = fluid.dataset.imdb._VOCAB
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = layers.data(name="words", shape=[-1, 1], dtype="int64",
                           append_batch_size=False, lod_level=1)
        label = layers.data(name="label", shape=[-1, 1], dtype="int64",
                            append_batch_size=False)
        builder = _convolution_net if net == "conv" else _stacked_lstm_net
        avg_cost, acc = builder(data, label, dict_dim)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    accs = []
    # 80 LSTM batches: the dual-place chip pass converges later than the
    # CPU run from benign backend drift (same-seed step-0 loss is
    # bit-identical; measured r5: chip hits 0.92 by batch 80, 0.5 at 40)
    n = 60 if net == "conv" else 80
    for flat, lod, lab in _batches(n):
        _, a = exe.run(main, feed={"words": (flat, lod), "label": lab},
                       fetch_list=[avg_cost, acc])
        accs.append(float(np.asarray(a).reshape(())))
    assert np.mean(accs[-8:]) > 0.8, np.mean(accs[-8:])
