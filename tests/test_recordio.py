"""recordio round-trip: native C++ writer/scanner/loader + pure-python
interop (reference ``paddle/fluid/recordio/*_test.cc``,
``test_recordio_reader.py``)."""

import os
import pickle

import numpy as np
import pytest

from paddle_tpu import native
from paddle_tpu.recordio_writer import (
    RecordIOWriter, RecordIOScanner, RecordIOLoader,
    convert_reader_to_recordio_file)


def test_native_builds():
    assert native.load() is not None, "native toolchain expected in image"


def test_roundtrip(tmp_path):
    p = str(tmp_path / "t.recordio")
    records = [os.urandom(n) for n in (1, 10, 1000, 65536)] + [b""]
    with RecordIOWriter(p, max_num_records=2) as w:
        for r in records:
            w.write(r)
    got = list(RecordIOScanner(p))
    assert got == records


def test_python_fallback_interop(tmp_path, monkeypatch):
    # write with the pure-python path, read with native (same layout)
    p = str(tmp_path / "interop.recordio")
    records = [b"alpha", b"beta" * 1000, b"gamma"]
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_error", RuntimeError("forced"))
    with RecordIOWriter(p) as w:
        for r in records:
            w.write(r)
    monkeypatch.setattr(native, "_build_error", None)
    assert native.load() is not None
    assert list(RecordIOScanner(p)) == records


def test_threaded_loader(tmp_path):
    paths = []
    all_records = set()
    for i in range(4):
        p = str(tmp_path / f"f{i}.recordio")
        with RecordIOWriter(p, max_num_records=10) as w:
            for j in range(100):
                rec = f"file{i}-rec{j}".encode()
                w.write(rec)
                all_records.add(rec)
        paths.append(p)
    loader = RecordIOLoader(paths, n_threads=3, capacity=16)
    got = set(loader)
    loader.close()
    assert got == all_records


def test_convert_reader(tmp_path):
    p = str(tmp_path / "samples.recordio")
    rng = np.random.RandomState(0)
    samples = [(rng.rand(4).astype("float32"), i) for i in range(25)]
    n = convert_reader_to_recordio_file(p, lambda: iter(samples))
    assert n == 25
    back = [pickle.loads(r) for r in RecordIOScanner(p)]
    for (a, i), (b, j) in zip(samples, back):
        np.testing.assert_array_equal(a, b)
        assert i == j
