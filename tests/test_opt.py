"""Program-IR optimization passes (paddle_tpu/analysis/opt): per-pass
unit tests, verify-sandwich negatives (a deliberately broken pass must
be rejected), RNG-slot exactness, executor PADDLE_TPU_OPT wiring, and
the donation planner's PTA009 proof obligation."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis import lints, opmeta
from paddle_tpu.analysis.opt import (OptReport, PassPipeline,
                                     optimize_program)
from paddle_tpu.analysis.opt.passes import (FUSED_OP_TYPE,
                                            RNG_SLOTS_ATTR,
                                            PassContext,
                                            constant_fold_pass,
                                            cse_pass, dce_pass,
                                            fuse_elementwise_pass)
from paddle_tpu.memory_optimization_transpiler import plan_donation


def _run(program, feed=None, fetches=(), scope=None, seed=0):
    program.random_seed = seed
    scope = scope or fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        return exe.run(program, feed=feed or {},
                       fetch_list=list(fetches), scope=scope)


def _op_types(program):
    return [op.type for op in program.global_block().ops]


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

class TestConstantFold:
    def _chain_program(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.append_op("fill_constant", outputs={"Out": ["c0"]},
                        attrs={"shape": [2, 2], "dtype": "float32",
                               "value": 3.0})
            b.append_op("scale", inputs={"X": ["c0"]},
                        outputs={"Out": ["c1"]},
                        attrs={"scale": 2.0, "bias": 1.0})
            b.append_op("elementwise_add", inputs={"X": ["c1"],
                                                   "Y": ["c0"]},
                        outputs={"Out": ["c2"]}, attrs={})
        return main

    def test_folds_chain_to_constant(self):
        main = self._chain_program()
        ctx = PassContext(fetch_names=("c2",))
        stats = constant_fold_pass(main, ctx)
        assert stats["folded"] == 2  # scale + elementwise_add
        assert stats["swept"] == 2   # orphaned fill + intermediate
        assert _op_types(main) == ["assign_value"]  # just the fetch
        (out,) = _run(main, fetches=["c2"])
        np.testing.assert_allclose(out, np.full((2, 2), 10.0))

    def test_fold_then_dce_leaves_one_constant(self):
        main = self._chain_program()
        optimized, report = optimize_program(main, fetch_names=("c2",))
        # the whole chain collapses to the single fetched constant
        assert _op_types(optimized) == ["assign_value"]
        (out,) = _run(optimized, fetches=["c2"])
        np.testing.assert_allclose(out, np.full((2, 2), 10.0))

    def test_redefined_constant_not_stale_folded(self):
        # c0 is re-written between consumers: the second consumer must
        # not fold the first literal
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.append_op("fill_constant", outputs={"Out": ["c0"]},
                        attrs={"shape": [2], "dtype": "float32",
                               "value": 1.0})
            b.append_op("scale", inputs={"X": ["c0"]},
                        outputs={"Out": ["a"]}, attrs={"scale": 2.0})
            # non-const writer of c0 (reads a feed)
            x = b.create_var(name="x", shape=(2,), dtype="float32",
                             is_data=True)
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["c0"]}, attrs={"scale": 1.0})
            b.append_op("scale", inputs={"X": ["c0"]},
                        outputs={"Out": ["out"]}, attrs={"scale": 3.0})
        constant_fold_pass(main, PassContext(feed_names=("x",),
                                             fetch_names=("a", "out")))
        a, out = _run(main, feed={"x": np.array([5.0, 5.0], "float32")},
                      fetches=["a", "out"])
        np.testing.assert_allclose(a, [2.0, 2.0])
        np.testing.assert_allclose(out, [15.0, 15.0])


# ---------------------------------------------------------------------------
# CSE
# ---------------------------------------------------------------------------

class TestCSE:
    def test_duplicate_pure_ops_dedupe(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.create_var(name="x", shape=(4,), dtype="float32",
                         is_data=True)
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["s1"]}, attrs={"scale": 2.0})
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["s2"]}, attrs={"scale": 2.0})
            b.append_op("elementwise_add", inputs={"X": ["s1"],
                                                   "Y": ["s2"]},
                        outputs={"Out": ["out"]}, attrs={})
        stats = cse_pass(main, PassContext(feed_names=("x",),
                                           fetch_names=("out",)))
        assert stats["deduped"] == 1
        assert _op_types(main).count("scale") == 1
        # the consumer now reads the canonical output twice
        add = main.global_block().ops[-1]
        assert add.input("X") == add.input("Y") == ["s1"]
        (out,) = _run(main, feed={"x": np.ones(4, "float32")},
                      fetches=["out"])
        np.testing.assert_allclose(out, np.full(4, 4.0))

    def test_fetched_duplicate_is_kept(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.create_var(name="x", shape=(4,), dtype="float32",
                         is_data=True)
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["s1"]}, attrs={"scale": 2.0})
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["s2"]}, attrs={"scale": 2.0})
        stats = cse_pass(main, PassContext(feed_names=("x",),
                                           fetch_names=("s1", "s2")))
        assert stats["deduped"] == 0
        assert _op_types(main).count("scale") == 2

    def test_attr_difference_blocks_dedupe(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.create_var(name="x", shape=(4,), dtype="float32",
                         is_data=True)
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["s1"]}, attrs={"scale": 2.0})
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["s2"]}, attrs={"scale": 3.0})
            b.append_op("elementwise_add", inputs={"X": ["s1"],
                                                   "Y": ["s2"]},
                        outputs={"Out": ["out"]}, attrs={})
        stats = cse_pass(main, PassContext(feed_names=("x",),
                                           fetch_names=("out",)))
        assert stats["deduped"] == 0


# ---------------------------------------------------------------------------
# DCE
# ---------------------------------------------------------------------------

class TestDCE:
    def test_removes_dead_and_unfetched_grad_chains(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.fc(x, 8, act="relu")
            cost = fluid.layers.mean(h)
            fluid.backward.append_backward(cost)
        n_before = len(main.global_block().ops)
        stats = dce_pass(main, PassContext(feed_names=("x",),
                                           fetch_names=(cost.name,)))
        # nothing fetches the grads and no optimizer consumes them:
        # the whole autodiff chain is dead (XLA would DCE it after
        # paying trace+lower for it)
        assert stats["removed"] > 0
        types = _op_types(main)
        assert not any(t.endswith("_grad") for t in types)
        assert len(types) < n_before

    def test_keeps_effectful_and_persistable_writes(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.fc(x, 8)
            cost = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        stats = dce_pass(main, PassContext(feed_names=("x",),
                                           fetch_names=(cost.name,)))
        types = _op_types(main)
        assert "sgd" in types  # persistable write = live


# ---------------------------------------------------------------------------
# fusion
# ---------------------------------------------------------------------------

class TestFusion:
    def _chain(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            b = main.global_block()
            b.create_var(name="x", shape=(4,), dtype="float32",
                         is_data=True)
            b.append_op("scale", inputs={"X": ["x"]},
                        outputs={"Out": ["t0"]}, attrs={"scale": 2.0})
            b.append_op("relu", inputs={"X": ["t0"]},
                        outputs={"Out": ["t1"]}, attrs={})
            b.append_op("scale", inputs={"X": ["t1"]},
                        outputs={"Out": ["out"]},
                        attrs={"scale": 3.0, "bias": 1.0})
        return main

    def test_chain_collapses_and_computes_identically(self):
        main = self._chain()
        x = np.array([-1.0, 0.0, 1.0, 2.0], "float32")
        (ref,) = _run(main, feed={"x": x}, fetches=["out"])
        stats = fuse_elementwise_pass(
            main, PassContext(feed_names=("x",), fetch_names=("out",)))
        assert stats == {"chains": 1, "members": 3}
        assert _op_types(main) == [FUSED_OP_TYPE]
        fused = main.global_block().ops[0]
        assert fused.attr(RNG_SLOTS_ATTR) == 3  # keeps key positions
        (out,) = _run(main, feed={"x": x}, fetches=["out"])
        np.testing.assert_array_equal(out, ref)

    def test_externally_consumed_intermediate_splits_chain(self):
        main = self._chain()
        # t1 is now also fetched -> it may not vanish inside a fusion
        stats = fuse_elementwise_pass(
            main, PassContext(feed_names=("x",),
                              fetch_names=("out", "t1")))
        types = _op_types(main)
        assert types[0] == FUSED_OP_TYPE  # scale+relu still fuse
        assert types[-1] == "scale"       # the tail stays separate
        out, t1 = _run(main,
                       feed={"x": np.ones(4, "float32")},
                       fetches=["out", "t1"])
        np.testing.assert_allclose(t1, np.full(4, 2.0))
        np.testing.assert_allclose(out, np.full(4, 7.0))


# ---------------------------------------------------------------------------
# the verify-sandwich: a broken pass must be rejected
# ---------------------------------------------------------------------------

class TestVerifySandwich:
    def _program(self):
        main, _startup, feeds, fetches = self._program_with_startup()
        return main, feeds, fetches

    def _program_with_startup(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.fc(x, 8, act="relu")
            cost = fluid.layers.mean(h)
        return main, startup, ("x",), (cost.name,)

    def test_pass_deleting_a_needed_op_is_aborted(self):
        main, startup, feeds, fetches = self._program_with_startup()

        def evil_delete(program, ctx):
            # drop the op producing the fetch target
            program.global_block().ops.pop()
            return {"mangled": 1}

        pipe = PassPipeline([evil_delete])
        optimized, report = pipe.run(main, feed_names=feeds,
                                     fetch_names=fetches)
        assert report.passes[0]["status"] == "aborted"
        assert report.passes[0]["new_diagnostics"]
        # the program reverted: still runs and fetches
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            (out,) = exe.run(optimized,
                             feed={"x": np.ones((1, 4), "float32")},
                             fetch_list=list(fetches), scope=scope)
        assert np.isfinite(out).all()

    def test_pass_rewiring_to_undefined_name_is_aborted(self):
        main, feeds, fetches = self._program()

        def evil_rewire(program, ctx):
            op = program.global_block().ops[-1]
            op.inputs = {k: ["__no_such_var__"] for k in op.inputs}
            return {"mangled": 1}

        pipe = PassPipeline([evil_rewire])
        optimized, report = pipe.run(main, feed_names=feeds,
                                     fetch_names=fetches)
        assert report.passes[0]["status"] == "aborted"
        codes = {d["code"] for d in
                 report.passes[0]["new_diagnostics"]}
        assert "PTA001" in codes

    def test_raising_pass_is_aborted_not_fatal(self):
        main, feeds, fetches = self._program()

        def evil_raise(program, ctx):
            raise RuntimeError("boom")

        optimized, report = PassPipeline([evil_raise]).run(
            main, feed_names=feeds, fetch_names=fetches)
        assert report.passes[0]["status"] == "aborted"
        assert report.passes[0]["stats"] == {"raised": 1}

    def test_input_program_never_mutated(self):
        main, feeds, fetches = self._program()
        before = main.to_dict()
        optimize_program(main, feed_names=feeds, fetch_names=fetches)
        assert main.to_dict() == before

    def test_unknown_pass_name_rejected(self):
        with pytest.raises(ValueError, match="unknown optimization"):
            PassPipeline(["not_a_pass"])


# ---------------------------------------------------------------------------
# RNG-slot exactness: removing ops must not shift dropout keys
# ---------------------------------------------------------------------------

class TestRngSlots:
    def test_dce_preserves_dropout_masks(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[16], dtype="float32")
            # a dead pure op BEFORE the dropout: removing it shifts the
            # op positions, and without slot bookkeeping the mask key
            dead = layers.fc(x, 4)
            h = layers.fc(x, 16)
            d = fluid.layers.dropout(h, dropout_prob=0.5)
            out = fluid.layers.mean(d)
        feed = {"x": np.random.RandomState(0)
                .randn(4, 16).astype("float32")}
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            main.random_seed = 9
            (ref,) = exe.run(main, feed=feed, fetch_list=[out.name],
                             scope=scope)
        optimized, report = optimize_program(
            main, feed_names=("x",), fetch_names=(out.name,))
        assert report.ops_removed() > 0  # the dead fc went away
        # surviving ops carry the removed ops' rng slots
        slots = [op.attr(RNG_SLOTS_ATTR, 1)
                 for op in optimized.global_block().ops]
        assert sum(slots) == len(main.global_block().ops)
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe2 = fluid.Executor()
            exe2.run(startup)
            (opt_out,) = exe2.run(optimized, feed=feed,
                                  fetch_list=[out.name], scope=scope2)
        # EXACT: the dropout folded the same key
        np.testing.assert_array_equal(ref, opt_out)


# ---------------------------------------------------------------------------
# executor wiring (PADDLE_TPU_OPT)
# ---------------------------------------------------------------------------

class TestExecutorWiring:
    def _train(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            y = layers.data("y", shape=[1], dtype="float32")
            h = layers.fc(x, 8, act="relu")
            pred = layers.fc(h, 1)
            cost = fluid.layers.mean(
                fluid.layers.square(pred - y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)
        return main, startup, cost

    def test_env_gated_and_memoized(self, monkeypatch):
        main, startup, cost = self._train()
        main.random_seed = startup.random_seed = 4
        rng = np.random.RandomState(1)
        feed = {"x": rng.randn(4, 8).astype("float32"),
                "y": rng.randn(4, 1).astype("float32")}

        scope = fluid.Scope()
        monkeypatch.delenv("PADDLE_TPU_OPT", raising=False)
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            (ref,) = exe.run(main, feed=feed, fetch_list=[cost.name],
                             scope=scope)
            assert exe._opt_cache == {}  # off by default

        monkeypatch.setenv("PADDLE_TPU_OPT", "1")
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe2 = fluid.Executor()
            exe2.run(startup)
            (opt1,) = exe2.run(main, feed=feed, fetch_list=[cost.name],
                               scope=scope2)
            assert len(exe2._opt_cache) >= 1
            memo = dict(exe2._opt_cache)
            (_,) = exe2.run(main, feed=feed, fetch_list=[cost.name],
                            scope=scope2)
            # second run re-used the optimized clone (same objects)
            for k, v in memo.items():
                assert exe2._opt_cache[k] is v
        np.testing.assert_allclose(ref, opt1, rtol=1e-5, atol=1e-6)

    def test_program_mutation_reoptimizes(self, monkeypatch):
        main, startup, cost = self._train()
        monkeypatch.setenv("PADDLE_TPU_OPT", "1")
        scope = fluid.Scope()
        feed = {"x": np.zeros((2, 8), "float32"),
                "y": np.zeros((2, 1), "float32")}
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            exe.run(main, feed=feed, fetch_list=[cost.name],
                    scope=scope)
            n = len(exe._opt_cache)
            main.bump_version()
            exe.run(main, feed=feed, fetch_list=[cost.name],
                    scope=scope)
            assert len(exe._opt_cache) == n + 1

    def test_amortize_gate_interprets_startup(self, monkeypatch):
        from paddle_tpu.analysis.opt.passes import AMORTIZE_MIN_OPS
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            h = x
            for _ in range(1 + AMORTIZE_MIN_OPS // 2):
                h = layers.fc(h, 8)
            cost = fluid.layers.mean(h)
        assert len(startup.global_block().ops) >= AMORTIZE_MIN_OPS
        optimized, _ = optimize_program(startup)
        assert getattr(optimized, "_opt_interpret", False)
        # ...but never for a program with fetch targets
        opt_main, _ = optimize_program(main, feed_names=("x",),
                                       fetch_names=(cost.name,))
        assert not getattr(opt_main, "_opt_interpret", False)
        # and the interpreted startup still initializes the scope
        monkeypatch.setenv("PADDLE_TPU_OPT", "1")
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            (out,) = exe.run(main,
                             feed={"x": np.ones((2, 8), "float32")},
                             fetch_list=[cost.name], scope=scope)
        assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# donation planner (memory_optimization_transpiler rewrite)
# ---------------------------------------------------------------------------

class TestDonationPlan:
    def test_plan_facts_and_feed_donation(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.fc(x, 4)
            cost = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        plan = plan_donation(main, feed_names=("x",),
                             fetch_names=(cost.name,))
        assert main._donation_plan is plan
        assert "x" in plan.donatable_feeds  # dies inside the step
        assert plan.inplace_updates         # sgd ParamOut facts
        assert all(t == "sgd" for _, t, _ in
                   plan.inplace_updates.values())
        assert plan.dropped == []
        assert "donation plan" in plan.report()

    def test_hazardous_update_is_dropped_not_planned(self):
        # a read AFTER the in-place update: PTA009 — the planner must
        # refuse the aliasing fact for that var
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[4], dtype="float32")
            h = layers.fc(x, 4)
            cost = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        b = main.global_block()
        sgd = next(op for op in b.ops if op.type == "sgd")
        param = sgd.output("ParamOut")[0]
        b.append_op("scale", inputs={"X": [param]},
                    outputs={"Out": ["late_read"]}, attrs={"scale": 1.0})
        hazards = [d for d in lints.check_graph(main)
                   if d.code == "PTA009"]
        assert hazards  # the lint sees it...
        plan = plan_donation(main, feed_names=("x",),
                             fetch_names=(cost.name, "late_read"))
        dropped_vars = {v for v, _ in plan.dropped}
        assert param in dropped_vars          # ...so the plan drops it
        assert param not in plan.inplace_updates


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

class TestOptCli:
    def test_zoo_target(self, capsys):
        from paddle_tpu.cli import main
        assert main(["opt", "--zoo", "mnist"]) == 0
        out = capsys.readouterr().out
        assert "optimization report" in out
        assert "donation plan" in out

    def test_bad_target_exits_2(self, tmp_path, capsys):
        from paddle_tpu.cli import main
        assert main(["opt", str(tmp_path / "nope")]) == 2
        assert main(["opt"]) == 2

    def test_json_report(self, capsys):
        import json
        from paddle_tpu.cli import main
        assert main(["opt", "--zoo", "mnist", "--json"]) == 0
        body = json.loads(capsys.readouterr().out)
        assert body["targets"]
        t = body["targets"][0]
        assert {"passes", "ops_before", "ops_after", "target",
                "donation_plan", "interpret"} <= set(t)
