"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
initializes, so multi-chip sharding tests run without TPU hardware
(mirrors the reference's strategy of simulating clusters on one host,
SURVEY.md §4.5)."""

import os

# PADDLE_TPU_TEST_TPU=1 runs the selected tests ON the real chip (the
# reference's dual-place OpTest discipline, op_test.py:290) — everything
# else pins the 8-device virtual CPU platform.
_ON_TPU = os.environ.get("PADDLE_TPU_TEST_TPU") == "1"

if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon sitecustomize force-sets jax_platforms="axon,cpu" via
# jax.config.update at interpreter boot; override it back before any
# backend initializes so tests run on the 8-device virtual CPU platform.
if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")
else:
    # dual-place discipline: the suite's tolerances/convergence targets
    # are f32-derived, so the chip pass runs matmuls at full f32
    # precision (TPU default is bf16 passes — enough to sink e.g. the
    # sentiment test's parity-style toy task)
    jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def corrupt_largest_file(ckpt_dir, truncate_to_half=True):
    """Tear a committed checkpoint for fault-tolerance tests: truncate
    (or bit-flip) its largest payload file, sparing the manifest."""
    files = [(os.path.getsize(os.path.join(dp, f)), os.path.join(dp, f))
             for dp, _, fs in os.walk(str(ckpt_dir))
             for f in fs if f != "MANIFEST.json"]
    size, victim = max(files)
    with open(victim, "r+b") as f:
        if truncate_to_half:
            f.truncate(size // 2)
        else:
            f.seek(size - 1)
            byte = f.read(1)
            f.seek(-1, 2)
            f.write(bytes([byte[0] ^ 0xFF]))
    return victim


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs and a fresh scope."""
    import paddle_tpu as fluid
    from paddle_tpu.framework import Program
    from paddle_tpu.scope import Scope, scope_guard

    main, startup = Program(), Program()
    prev_main = fluid.switch_main_program(main)
    prev_startup = fluid.switch_startup_program(startup)
    scope = Scope()
    with scope_guard(scope):
        yield
    fluid.switch_main_program(prev_main)
    fluid.switch_startup_program(prev_startup)
