"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
initializes, so multi-chip sharding tests run without TPU hardware
(mirrors the reference's strategy of simulating clusters on one host,
SURVEY.md §4.5)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# the axon sitecustomize force-sets jax_platforms="axon,cpu" via
# jax.config.update at interpreter boot; override it back before any
# backend initializes so tests run on the 8-device virtual CPU platform.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_programs():
    """Each test gets fresh default programs and a fresh scope."""
    import paddle_tpu as fluid
    from paddle_tpu.framework import Program
    from paddle_tpu.scope import Scope, scope_guard

    main, startup = Program(), Program()
    prev_main = fluid.switch_main_program(main)
    prev_startup = fluid.switch_startup_program(startup)
    scope = Scope()
    with scope_guard(scope):
        yield
    fluid.switch_main_program(prev_main)
    fluid.switch_startup_program(prev_startup)
