"""Detection op group tests (mirror reference test_prior_box_op.py,
test_box_coder_op.py, test_iou_similarity_op.py, test_bipartite_match_op.py,
test_target_assign_op.py, test_mine_hard_examples_op.py,
test_multiclass_nms_op.py, test_roi_pool_op.py, test_detection_map_op.py,
plus an SSD-head convergence test in the book-test style)."""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
from op_test import OpTest


def _np_iou(a, b):
    n, m = a.shape[0], b.shape[0]
    out = np.zeros((n, m), np.float32)
    for i in range(n):
        for j in range(m):
            ixmin = max(a[i, 0], b[j, 0])
            iymin = max(a[i, 1], b[j, 1])
            ixmax = min(a[i, 2], b[j, 2])
            iymax = min(a[i, 3], b[j, 3])
            iw = max(ixmax - ixmin, 0.0)
            ih = max(iymax - iymin, 0.0)
            inter = iw * ih
            union = ((a[i, 2] - a[i, 0]) * (a[i, 3] - a[i, 1]) +
                     (b[j, 2] - b[j, 0]) * (b[j, 3] - b[j, 1]) - inter)
            out[i, j] = inter / union if union > 0 else 0.0
    return out


def _rand_boxes(rng, n):
    x1 = rng.rand(n) * 0.5
    y1 = rng.rand(n) * 0.5
    x2 = x1 + rng.rand(n) * 0.5
    y2 = y1 + rng.rand(n) * 0.5
    return np.stack([x1, y1, x2, y2], axis=1).astype("float32")


def _run_program(feed, fetch_list):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed,
                   fetch_list=fetch_list)


class TestIouSimilarity:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = _rand_boxes(rng, 5)
        b = _rand_boxes(rng, 7)
        x = layers.data(name="x", shape=[5, 4], append_batch_size=False)
        y = layers.data(name="y", shape=[7, 4], append_batch_size=False)
        out = layers.iou_similarity(x=x, y=y)
        (got,) = _run_program({"x": a, "y": b}, [out])
        np.testing.assert_allclose(got, _np_iou(a, b), rtol=1e-5, atol=1e-6)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(1)
        prior = _rand_boxes(rng, 6)
        pvar = (rng.rand(6, 4).astype("float32") * 0.3 + 0.1)
        target = _rand_boxes(rng, 3)
        pb = layers.data(name="pb", shape=[6, 4], append_batch_size=False)
        pv = layers.data(name="pv", shape=[6, 4], append_batch_size=False)
        tb = layers.data(name="tb", shape=[3, 4], append_batch_size=False)
        enc = layers.box_coder(prior_box=pb, prior_box_var=pv, target_box=tb,
                               code_type="encode_center_size")
        dec = layers.box_coder(prior_box=pb, prior_box_var=pv,
                               target_box=enc,
                               code_type="decode_center_size")
        enc_v, dec_v = _run_program({"pb": prior, "pv": pvar, "tb": target},
                                    [enc, dec])
        assert enc_v.shape == (3, 6, 4)
        # decoding the encoded deltas must recover the target box for every
        # prior column
        for j in range(6):
            np.testing.assert_allclose(dec_v[:, j, :], target, rtol=1e-4,
                                       atol=1e-5)


class TestPriorBox:
    def test_shapes_and_values(self):
        feat = np.zeros((1, 8, 2, 2), np.float32)
        img = np.zeros((1, 3, 8, 8), np.float32)
        fv = layers.data(name="feat", shape=list(feat.shape),
                         append_batch_size=False)
        iv = layers.data(name="img", shape=list(img.shape),
                         append_batch_size=False)
        box, var = layers.prior_box(
            fv, iv, min_sizes=[4.0], max_sizes=[8.0], aspect_ratios=[2.0],
            flip=True, clip=True)
        b, v = _run_program({"feat": feat, "img": img}, [box, var])
        # priors = len([1, 2, 1/2]) * 1 min_size + 1 max_size = 4
        assert b.shape == (2, 2, 4, 4)
        assert v.shape == (2, 2, 4, 4)
        np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2],
                                   rtol=1e-6)
        # first prior at (0,0): center (2,2) of an 8x8 image, ar=1, size 4
        cx = cy = 0.5 * (8 / 2)
        expect = [(cx - 2) / 8, (cy - 2) / 8, (cx + 2) / 8, (cy + 2) / 8]
        np.testing.assert_allclose(b[0, 0, 0], expect, rtol=1e-5)
        assert b.min() >= 0.0 and b.max() <= 1.0  # clip


def _np_bipartite(dist):
    """Reference greedy BipartiteMatch (bipartite_match_op.cc)."""
    row, col = dist.shape
    match_idx = np.full(col, -1, np.int32)
    match_dist = np.zeros(col, np.float32)
    row_pool = list(range(row))
    while row_pool:
        max_idx = max_row = -1
        max_d = -1.0
        for j in range(col):
            if match_idx[j] != -1:
                continue
            for m in row_pool:
                if dist[m, j] < 1e-6:
                    continue
                if dist[m, j] > max_d:
                    max_idx, max_row, max_d = j, m, dist[m, j]
        if max_idx == -1:
            break
        match_idx[max_idx] = max_row
        match_dist[max_idx] = max_d
        row_pool.remove(max_row)
    return match_idx, match_dist


class TestBipartiteMatch:
    def test_vs_reference_greedy(self):
        rng = np.random.RandomState(3)
        lod = [[0, 5, 11]]
        dist = rng.rand(11, 7).astype("float32")
        d = layers.data(name="d", shape=[11, 7], append_batch_size=False,
                        lod_level=1)
        mi, md = layers.bipartite_match(d)
        mi_v, md_v = _run_program({"d": (dist, lod)}, [mi, md])
        for i, (lo, hi) in enumerate([(0, 5), (5, 11)]):
            want_idx, want_dist = _np_bipartite(dist[lo:hi])
            np.testing.assert_array_equal(mi_v[i], want_idx)
            np.testing.assert_allclose(md_v[i], want_dist, rtol=1e-5)

    def test_per_prediction(self):
        rng = np.random.RandomState(4)
        dist = rng.rand(4, 10).astype("float32")
        d = layers.data(name="d", shape=[4, 10], append_batch_size=False)
        mi, md = layers.bipartite_match(d, match_type="per_prediction",
                                        dist_threshold=0.5)
        mi_v, md_v = _run_program({"d": dist}, [mi, md])
        base_idx, _ = _np_bipartite(dist)
        for j in range(10):
            if base_idx[j] != -1:
                assert mi_v[0, j] == base_idx[j]
            else:
                best = dist[:, j].max()
                if best >= 0.5:
                    assert mi_v[0, j] == dist[:, j].argmax()
                    np.testing.assert_allclose(md_v[0, j], best, rtol=1e-5)
                else:
                    assert mi_v[0, j] == -1


class TestTargetAssign:
    def test_assign_with_lod(self):
        # 2 instances: 2 and 1 gt rows; P (cols) = 3
        x = np.arange(3 * 1 * 2, dtype="float32").reshape(3, 1, 2)
        lod = [[0, 2, 3]]
        match = np.array([[0, -1, 1], [-1, 0, -1]], np.int32)
        xv = layers.data(name="x", shape=[3, 1, 2], append_batch_size=False,
                         lod_level=1)
        mv = layers.data(name="m", shape=[2, 3], append_batch_size=False,
                         dtype="int32")
        out, w = layers.target_assign(xv, mv, mismatch_value=9)
        out_v, w_v = _run_program({"x": (x, lod), "m": match}, [out, w])
        # instance 0: col0 -> row 0, col2 -> row 1 (offset 0)
        np.testing.assert_allclose(out_v[0, 0], x[0, 0])
        np.testing.assert_allclose(out_v[0, 1], [9, 9])
        np.testing.assert_allclose(out_v[0, 2], x[1, 0])
        # instance 1: col1 -> row 0 + offset 2
        np.testing.assert_allclose(out_v[1, 1], x[2, 0])
        np.testing.assert_allclose(
            w_v.reshape(2, 3), [[1, 0, 1], [0, 1, 0]])


class TestMineHardExamples:
    def test_max_negative(self):
        cls_loss = np.array([[0.1, 0.9, 0.5, 0.3, 0.7]], np.float32)
        match = np.array([[0, -1, -1, -1, -1]], np.int32)
        match_dist = np.array([[0.8, 0.1, 0.2, 0.3, 0.1]], np.float32)
        cl = layers.data(name="cl", shape=[1, 5], append_batch_size=False)
        mi = layers.data(name="mi", shape=[1, 5], append_batch_size=False,
                         dtype="int32")
        md = layers.data(name="md", shape=[1, 5], append_batch_size=False)
        helper = fluid.layer_helper.LayerHelper("mine_hard_examples")
        neg = helper.create_tmp_variable(dtype="int32")
        upd = helper.create_tmp_variable(dtype="int32")
        helper.append_op(
            type="mine_hard_examples",
            inputs={"ClsLoss": cl, "MatchIndices": mi, "MatchDist": md},
            outputs={"NegIndices": neg, "UpdatedMatchIndices": upd},
            attrs={"neg_pos_ratio": 2.0, "neg_dist_threshold": 0.5,
                   "mining_type": "max_negative", "sample_size": 0})
        neg_v, upd_v = _run_program(
            {"cl": cls_loss, "mi": match, "md": match_dist}, [neg, upd])
        # 1 positive, ratio 2 -> 2 negatives; eligible: cols 1..4; highest
        # losses are col 1 (0.9) and col 4 (0.7)
        picked = set(neg_v[0][neg_v[0] >= 0].tolist())
        assert picked == {1, 4}
        np.testing.assert_array_equal(upd_v, match)  # unchanged


class TestMulticlassNMS:
    def test_suppression(self):
        # two nearly identical boxes + one distinct, 2 classes (0=background)
        bboxes = np.array([[[0.1, 0.1, 0.4, 0.4],
                            [0.11, 0.11, 0.41, 0.41],
                            [0.6, 0.6, 0.9, 0.9]]], np.float32)
        scores = np.array([[[0.1, 0.2, 0.3],         # class 0 (bg)
                            [0.9, 0.85, 0.8]]], np.float32)  # class 1
        bv = layers.data(name="b", shape=[1, 3, 4], append_batch_size=False)
        sv = layers.data(name="s", shape=[1, 2, 3], append_batch_size=False)
        helper = fluid.layer_helper.LayerHelper("multiclass_nms")
        out = helper.create_tmp_variable(dtype="float32")
        helper.append_op(type="multiclass_nms",
                         inputs={"BBoxes": bv, "Scores": sv},
                         outputs={"Out": out},
                         attrs={"background_label": 0, "nms_threshold": 0.5,
                                "nms_top_k": 10, "keep_top_k": 10,
                                "score_threshold": 0.01, "nms_eta": 1.0})
        (got,) = _run_program({"b": bboxes, "s": scores}, [out])
        # box 1 suppressed by box 0; two rows remain, both class 1
        assert got.shape == (2, 6)
        assert set(got[:, 0].astype(int).tolist()) == {1}
        np.testing.assert_allclose(sorted(got[:, 1], reverse=True),
                                   [0.9, 0.8], rtol=1e-5)


class TestRoiPool(OpTest):
    op_type = "roi_pool"

    def setup(self):
        rng = np.random.RandomState(7)
        x = rng.rand(2, 3, 6, 6).astype("float32")
        # (batch_id, x1, y1, x2, y2) in input scale
        rois = np.array([[0, 0, 0, 3, 3], [1, 2, 2, 5, 5]], np.int64)
        self.inputs = {"X": x, "ROIs": rois}
        self.attrs = {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0}
        out = np.zeros((2, 3, 2, 2), np.float32)
        for r, roi in enumerate(rois):
            b, x1, y1, x2, y2 = [int(v) for v in roi]
            rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
            for c in range(3):
                for ph in range(2):
                    for pw in range(2):
                        hs = min(max(int(math.floor(ph * rh / 2.)) + y1, 0), 6)
                        he = min(max(int(math.ceil((ph + 1) * rh / 2.)) + y1,
                                     0), 6)
                        ws = min(max(int(math.floor(pw * rw / 2.)) + x1, 0), 6)
                        we = min(max(int(math.ceil((pw + 1) * rw / 2.)) + x1,
                                     0), 6)
                        patch = x[b, c, hs:he, ws:we]
                        out[r, c, ph, pw] = patch.max() if patch.size else 0.0
        self.outputs = {"Out": out, "Argmax": None}

    def test_forward(self):
        self.setup()
        self.check_output(no_check_set=("Argmax",))

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestDetectionMAP:
    def _build(self, with_state=False):
        det = layers.data(name="det", shape=[6, 6],
                          append_batch_size=False, lod_level=1)
        lab = layers.data(name="lab", shape=[4, 6],
                          append_batch_size=False, lod_level=1)
        return det, lab

    def test_perfect_detection(self):
        det, lab = self._build()
        m = layers.detection_map(det, lab, class_num=3,
                                 overlap_threshold=0.5)
        # image 0: one gt class 1; detection matches exactly
        dets = np.array([[1, 0.9, 0.1, 0.1, 0.4, 0.4]], np.float32)
        labels = np.array([[1, 0, 0.1, 0.1, 0.4, 0.4]], np.float32)
        (got,) = _run_program({"det": (dets, [[0, 1]]),
                               "lab": (labels, [[0, 1]])}, [m])
        np.testing.assert_allclose(got, [1.0], atol=1e-6)

    def test_false_positive_halves_ap(self):
        det, lab = self._build()
        m = layers.detection_map(det, lab, class_num=3,
                                 overlap_threshold=0.5)
        dets = np.array([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                         [1, 0.8, 0.6, 0.6, 0.9, 0.9]], np.float32)
        labels = np.array([[1, 0, 0.1, 0.1, 0.4, 0.4]], np.float32)
        (got,) = _run_program({"det": (dets, [[0, 2]]),
                               "lab": (labels, [[0, 1]])}, [m])
        # tp at rank 1 (p=1, r=1), fp at rank 2 -> integral AP = 1.0
        np.testing.assert_allclose(got, [1.0], atol=1e-6)
        # flip scores: fp first -> AP = 0.5
        fluid.switch_main_program(fluid.Program())
        det2 = layers.data(name="det", shape=[6, 6],
                           append_batch_size=False, lod_level=1)
        lab2 = layers.data(name="lab", shape=[4, 6],
                           append_batch_size=False, lod_level=1)
        m2 = layers.detection_map(det2, lab2, class_num=3,
                                  overlap_threshold=0.5)
        dets2 = np.array([[1, 0.9, 0.6, 0.6, 0.9, 0.9],
                          [1, 0.8, 0.1, 0.1, 0.4, 0.4]], np.float32)
        (got2,) = _run_program({"det": (dets2, [[0, 2]]),
                                "lab": (labels, [[0, 1]])}, [m2])
        np.testing.assert_allclose(got2, [0.5], atol=1e-6)


class TestSSDHeadTraining:
    def test_loss_decreases(self):
        rng = np.random.RandomState(11)
        images = rng.rand(2, 3, 8, 8).astype("float32")
        gt_box = np.array([[0.1, 0.1, 0.45, 0.45],
                           [0.5, 0.5, 0.95, 0.95],
                           [0.2, 0.3, 0.6, 0.7]], np.float32)
        gt_label = np.array([[1], [2], [1]], np.int32)
        lod = [[0, 2, 3]]

        img = layers.data(name="img", shape=[2, 3, 8, 8],
                          append_batch_size=False)
        gb = layers.data(name="gb", shape=[3, 4], append_batch_size=False,
                         lod_level=1)
        gl = layers.data(name="gl", shape=[3, 1], append_batch_size=False,
                         dtype="int32", lod_level=1)
        feat = layers.conv2d(input=img, num_filters=8, filter_size=3,
                             padding=1, act="relu")
        locs, confs, box, var = layers.multi_box_head(
            inputs=[feat], image=img, base_size=8, num_classes=3,
            aspect_ratios=[[2.0]], min_sizes=[3.0], max_sizes=[6.0],
            flip=True, clip=True)
        loss = layers.ssd_loss(locs, confs, gb, gl, box, var)
        avg = layers.reduce_mean(loss)
        opt = fluid.optimizer.SGD(learning_rate=0.05)
        opt.minimize(avg)

        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        feed = {"img": images, "gb": (gt_box, lod), "gl": (gt_label, lod)}
        losses = []
        for _ in range(12):
            (lv,) = exe.run(fluid.default_main_program(), feed=feed,
                            fetch_list=[avg])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.8, losses


class TestScaleSubRegionOp(OpTest):
    """Mirrors reference function/ScaleSubRegionOpTest.cpp +
    test_scale_sub_region_layer config test: one-based inclusive CHW
    ranges, region scaled by ``value``, identity elsewhere."""
    op_type = "scale_sub_region"

    def setup(self):
        rng = np.random.RandomState(7)
        x = rng.rand(3, 4, 5, 6).astype("float32")
        idx = np.array([[1, 2, 1, 3, 2, 4],
                        [2, 4, 2, 5, 1, 6],
                        [3, 3, 1, 1, 1, 1]], np.float32)
        value = 2.5
        out = x.copy()
        for n in range(3):
            c0, c1, h0, h1, w0, w1 = idx[n].astype(int)
            out[n, c0 - 1:c1, h0 - 1:h1, w0 - 1:w1] *= value
        self.inputs = {"X": x, "Indices": idx}
        self.outputs = {"Out": out}
        self.attrs = {"value": value}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out")
