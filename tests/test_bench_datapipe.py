"""bench_datapipe smoke: the datapipe stack must beat the serial
DataFeeder loop on the input-bound workload, and the JSON summary must
keep its schema (BENCH_DATAPIPE.json records the full acceptance run,
which demands >= 2x; CI keeps the fast schema + beats-serial check)."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

import bench_datapipe  # noqa: E402


@pytest.fixture(scope="module")
def smoke_summary():
    return bench_datapipe.run_bench(n_samples=192, payload_floats=1 << 13,
                                    io_ms=1.0, workers=8, smoke=True)


def test_summary_schema(smoke_summary):
    assert {"workload", "smoke", "serial", "datapipe",
            "speedup"} <= set(smoke_summary)
    for mode in ("serial", "datapipe"):
        stats = smoke_summary[mode]
        assert {"mode", "steps", "elapsed_sec",
                "samples_per_sec"} <= set(stats)
        assert stats["steps"] > 0
        assert stats["samples_per_sec"] > 0
    assert {"n_samples", "batch_size", "io_ms", "workers",
            "steps"} <= set(smoke_summary["workload"])


def test_modes_ran_equal_steps(smoke_summary):
    assert smoke_summary["serial"]["steps"] == \
        smoke_summary["datapipe"]["steps"]


def test_pipeline_counters_recorded(smoke_summary):
    items = smoke_summary["datapipe"]["pipeline_items"]
    assert items.get("datapipe.source.items", 0) > 0
    assert items.get("datapipe.prefetch.items", 0) > 0
    assert smoke_summary["datapipe"]["prefetch_stall_sec_total"] is not None


def test_datapipe_beats_serial(smoke_summary):
    # the overlap win is structural (parallel fetch + prefetch); even a
    # noisy 2-core CI box shows >1x on the io-bound smoke workload
    assert smoke_summary["speedup"] is not None
    assert smoke_summary["speedup"] > 1.0, smoke_summary


@pytest.mark.slow
def test_acceptance_2x():
    summary = bench_datapipe.run_bench()
    assert summary["speedup"] >= 2.0, summary
