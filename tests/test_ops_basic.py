"""Per-op forward + numeric-gradient tests for the tier-1 op set
(pattern: reference ``tests/unittests/test_*_op.py``)."""

import numpy as np
import pytest

from op_test import OpTest


class TestMulOp(OpTest):
    op_type = "mul"

    def setup_method(self, _):
        rng = np.random.RandomState(1)
        x = rng.uniform(-1, 1, (4, 5)).astype("float32")
        y = rng.uniform(-1, 1, (5, 3)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup_method(self, _):
        rng = np.random.RandomState(2)
        x = rng.uniform(-1, 1, (3, 4)).astype("float32")
        y = rng.uniform(-1, 1, (5, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_Y": True}
        self.outputs = {"Out": x @ y.T}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def setup_method(self, _):
        rng = np.random.RandomState(3)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype("float32")
        y = rng.uniform(-1, 1, (3,)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup_method(self, _):
        rng = np.random.RandomState(4)
        x = rng.uniform(-2, 2, (5, 7)).astype("float32")
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def setup_method(self, _):
        rng = np.random.RandomState(5)
        probs = rng.uniform(0.1, 1.0, (6, 4)).astype("float32")
        probs /= probs.sum(-1, keepdims=True)
        labels = rng.randint(0, 4, (6, 1)).astype("int64")
        loss = -np.log(probs[np.arange(6), labels.ravel()]).reshape(6, 1)
        self.inputs = {"X": probs, "Label": labels}
        self.outputs = {"Out": loss}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out", max_relative_error=0.05)


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup_method(self, _):
        rng = np.random.RandomState(6)
        logits = rng.uniform(-2, 2, (5, 7)).astype("float32")
        labels = rng.randint(0, 7, (5, 1)).astype("int64")
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(5), labels.ravel()]).reshape(5, 1)
        self.inputs = {"Logits": logits, "Label": labels}
        self.outputs = {"Softmax": sm, "Loss": loss}

    def test_output(self):
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.check_grad(["Logits"], "Loss", max_relative_error=0.02)


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def setup_method(self, _):
        rng = np.random.RandomState(7)
        x = rng.uniform(-1, 1, (3, 4, 5)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False}
        self.outputs = {"Out": x.sum(axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestConcat(OpTest):
    op_type = "concat"

    def setup_method(self, _):
        rng = np.random.RandomState(8)
        a = rng.uniform(-1, 1, (2, 3)).astype("float32")
        b = rng.uniform(-1, 1, (2, 4)).astype("float32")
        self.inputs = {"X": [("a", a), ("b", b)]}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["a"], "Out")


class TestConv2d(OpTest):
    op_type = "conv2d"

    def setup_method(self, _):
        rng = np.random.RandomState(9)
        x = rng.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
        w = rng.uniform(-0.5, 0.5, (4, 3, 3, 3)).astype("float32")
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1}
        # reference conv via explicit loops (small sizes)
        out = np.zeros((2, 4, 8, 8), dtype=np.float64)
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        for n in range(2):
            for f in range(4):
                for i in range(8):
                    for j in range(8):
                        out[n, f, i, j] = np.sum(
                            xp[n, :, i:i + 3, j:j + 3] * w[f])
        self.outputs = {"Output": out.astype("float32")}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.03)


class TestPool2dAvg(OpTest):
    op_type = "pool2d"

    def setup_method(self, _):
        rng = np.random.RandomState(10)
        x = rng.uniform(-1, 1, (2, 3, 4, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        out = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestPool2dMax(OpTest):
    op_type = "pool2d"

    def setup_method(self, _):
        rng = np.random.RandomState(11)
        x = rng.uniform(-1, 1, (2, 3, 4, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        out = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def setup_method(self, _):
        rng = np.random.RandomState(12)
        x = rng.uniform(-1, 1, (4, 6)).astype("float32")
        scale = rng.uniform(0.5, 1.5, (6,)).astype("float32")
        bias = rng.uniform(-0.5, 0.5, (6,)).astype("float32")
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) / np.sqrt(var + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}
        self.outputs = {"Y": y, "Mean": mean.ravel(), "Variance": var.ravel()}

    def test_output(self):
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.02)


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def setup_method(self, _):
        rng = np.random.RandomState(13)
        w = rng.uniform(-1, 1, (10, 4)).astype("float32")
        ids = rng.randint(0, 10, (5, 1)).astype("int64")
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids.ravel()]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["W"], "Out")


class TestTranspose(OpTest):
    op_type = "transpose"

    def setup_method(self, _):
        rng = np.random.RandomState(14)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": [1, 0, 2]}
        self.outputs = {"Out": x.transpose(1, 0, 2)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestReshape(OpTest):
    op_type = "reshape"

    def setup_method(self, _):
        rng = np.random.RandomState(15)
        x = rng.uniform(-1, 1, (2, 6)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"shape": [4, 3]}
        self.outputs = {"Out": x.reshape(4, 3)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestSigmoid(OpTest):
    op_type = "sigmoid"

    def setup_method(self, _):
        rng = np.random.RandomState(16)
        x = rng.uniform(-3, 3, (4, 5)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": 1.0 / (1.0 + np.exp(-x))}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestTanh(OpTest):
    op_type = "tanh"

    def setup_method(self, _):
        rng = np.random.RandomState(17)
        x = rng.uniform(-2, 2, (4, 5)).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.tanh(x)}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestBatchNormTrain(OpTest):
    op_type = "batch_norm"

    def setup_method(self, _):
        rng = np.random.RandomState(18)
        x = rng.uniform(-1, 1, (4, 3, 2, 2)).astype("float32")
        scale = rng.uniform(0.5, 1.5, 3).astype("float32")
        bias = rng.uniform(-0.5, 0.5, 3).astype("float32")
        mean = np.zeros(3, np.float32)
        var = np.ones(3, np.float32)
        bm = x.mean(axis=(0, 2, 3))
        bv = x.var(axis=(0, 2, 3))
        y = (x - bm.reshape(1, 3, 1, 1)) / np.sqrt(
            bv.reshape(1, 3, 1, 1) + 1e-5)
        y = y * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        momentum = 0.9
        self.inputs = {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                       "Variance": var}
        self.attrs = {"momentum": momentum, "epsilon": 1e-5,
                      "is_test": False}
        self.outputs = {
            "Y": y,
            "MeanOut": mean * momentum + bm * (1 - momentum),
            "VarianceOut": var * momentum + bv * (1 - momentum),
            "SavedMean": bm,
            "SavedVariance": None,  # inv-std convention; skip value check
        }

    def test_output(self):
        self.check_output(atol=1e-4)


class TestTopK(OpTest):
    op_type = "top_k"

    def setup_method(self, _):
        rng = np.random.RandomState(19)
        x = rng.uniform(-1, 1, (3, 6)).astype("float32")
        k = 2
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": k}
        self.outputs = {"Out": vals, "Indices": idx.astype("int64")}

    def test_output(self):
        self.check_output()


class TestDropoutInference(OpTest):
    op_type = "dropout"

    def setup_method(self, _):
        rng = np.random.RandomState(20)
        x = rng.uniform(-1, 1, (4, 5)).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.3, "is_test": True}
        self.outputs = {"Out": x * 0.7, "Mask": None}

    def test_output(self):
        self.check_output()


def _conv3d_transpose_np(x, w, strides, paddings, dilations):
    """Naive summation reference for NCDHW transposed conv, filter
    (C_in, C_out, kd, kh, kw) — mirrors the reference semantics of
    conv_transpose_op.cc:314 at loop level."""
    n, ci, di, hi, wi = x.shape
    _, co, kd, kh, kw = w.shape
    sd, sh, sw = strides
    pd, ph, pw = paddings
    dd, dh, dw = dilations
    od = (di - 1) * sd - 2 * pd + dd * (kd - 1) + 1
    oh = (hi - 1) * sh - 2 * ph + dh * (kh - 1) + 1
    ow = (wi - 1) * sw - 2 * pw + dw * (kw - 1) + 1
    out = np.zeros((n, co, od + 2 * pd, oh + 2 * ph, ow + 2 * pw),
                   x.dtype)
    for b in range(n):
        for c in range(ci):
            for z in range(di):
                for y in range(hi):
                    for t in range(wi):
                        patch = np.einsum(
                            "odhw->odhw",
                            w[c] * x[b, c, z, y, t])
                        out[b, :, z * sd:z * sd + dd * (kd - 1) + 1:dd,
                            y * sh:y * sh + dh * (kh - 1) + 1:dh,
                            t * sw:t * sw + dw * (kw - 1) + 1:dw] += patch
    if pd or ph or pw:
        out = out[:, :, pd:out.shape[2] - pd, ph:out.shape[3] - ph,
                  pw:out.shape[4] - pw]
    return out


class TestConv3DTranspose(OpTest):
    op_type = "conv3d_transpose"

    def setup_method(self, _):
        rng = np.random.RandomState(21)
        x = rng.uniform(-1, 1, (2, 3, 3, 4, 4)).astype("float32")
        w = rng.uniform(-1, 1, (3, 2, 2, 3, 3)).astype("float32")
        attrs = {"strides": [2, 2, 2], "paddings": [1, 1, 1],
                 "dilations": [1, 1, 1], "groups": 1}
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = attrs
        self.outputs = {"Output": _conv3d_transpose_np(
            x, w, attrs["strides"], attrs["paddings"],
            attrs["dilations"])}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.02)


class TestConv2DTransposeAsymmetric(OpTest):
    """k=2, p=1 — the case where transposed-side and forward-side padding
    interpretations diverge (regression for use_consistent_padding)."""
    op_type = "conv2d_transpose"

    def setup_method(self, _):
        rng = np.random.RandomState(22)
        x = rng.uniform(-1, 1, (2, 3, 5, 5)).astype("float32")
        w = rng.uniform(-1, 1, (3, 4, 2, 2)).astype("float32")
        attrs = {"strides": [2, 2], "paddings": [1, 1],
                 "dilations": [1, 1], "groups": 1}
        want = _conv3d_transpose_np(
            x[:, :, None], w[:, :, None],
            [1] + attrs["strides"], [0] + attrs["paddings"],
            [1] + attrs["dilations"])[:, :, 0]
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = attrs
        self.outputs = {"Output": want}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.02)


class TestConv2DTransposeGrouped(OpTest):
    """groups=2: conv_transpose runs one per-group deconv, concatenated
    on channels (jax.lax.conv_transpose has no feature_group_count)."""
    op_type = "conv2d_transpose"

    def setup_method(self, _):
        rng = np.random.RandomState(23)
        x = rng.uniform(-1, 1, (1, 4, 3, 3)).astype("float32")
        w = rng.uniform(-1, 1, (4, 3, 2, 2)).astype("float32")
        attrs = {"strides": [2, 2], "paddings": [0, 0],
                 "dilations": [1, 1], "groups": 2}
        parts = []
        for g in range(2):
            parts.append(_conv3d_transpose_np(
                x[:, 2 * g:2 * g + 2, None], w[2 * g:2 * g + 2, :, None],
                [1, 2, 2], [0, 0, 0], [1, 1, 1])[:, :, 0])
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = attrs
        self.outputs = {"Output": np.concatenate(parts, axis=1)}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)
