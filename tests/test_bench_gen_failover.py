"""bench_gen_failover smoke: the kill-owner chaos drill must deliver
every stream token-identical to the unkilled reference with zero lost,
zero duplicated tokens and zero client errors — on EVERY attempt, at
smoke scale (exactly-once delivery is an invariant, not a tolerance).
BENCH_GEN_FAILOVER.json records the full acceptance run (3 replicas,
6 concurrent streams)."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root

import bench_gen_failover  # noqa: E402


@pytest.fixture(scope="module")
def smoke_summary():
    return bench_gen_failover.run_bench(streams=3, replicas=2,
                                        max_new=8, stall_ms=25.0,
                                        kill_after=2)


def test_summary_schema(smoke_summary):
    assert {"streams", "replicas", "max_new_tokens", "stall_ms",
            "reference", "kill_drill", "drain_drill",
            "resume_overhead_ratio"} <= set(smoke_summary)
    kill = smoke_summary["kill_drill"]
    assert {"ttft_after_failover_ms", "lost_tokens", "dup_tokens",
            "client_errors", "token_identical", "resumes",
            "spliced_tokens", "killed_replica"} <= set(kill)


def test_reference_run_is_clean(smoke_summary):
    ref = smoke_summary["reference"]
    assert ref["lost_tokens"] == 0
    assert ref["dup_tokens"] == 0
    assert ref["client_errors"] == 0


def test_kill_drill_exactly_once(smoke_summary):
    kill = smoke_summary["kill_drill"]
    assert kill["lost_tokens"] == 0, kill
    assert kill["dup_tokens"] == 0, kill
    assert kill["client_errors"] == 0, kill
    assert kill["token_identical"], kill
    # the kill was survived BY resume, not by luck: at least one stream
    # was re-prefilled on a survivor and its continuation spliced in
    assert kill["resumes"] >= 1, kill
    assert kill["spliced_tokens"] >= 1, kill
    assert kill["ttft_after_failover_ms"] > 0, kill


def test_drain_drill_migrates_without_errors(smoke_summary):
    drain = smoke_summary["drain_drill"]
    assert drain["client_errors"] == 0, drain
    assert drain["lost_tokens"] == 0 and drain["dup_tokens"] == 0
    assert drain["token_identical"], drain
    assert drain["migrations"] >= 1, drain


def test_trajectory_gate_wiring(smoke_summary, tmp_path):
    """The smoke run's metrics flow through the shared recorder into a
    trajectory `paddle_tpu bench check` accepts — and a run that loses
    one token flips the gate to exit-1 (the zero-tolerance invariant
    the trajectory enforces)."""
    from paddle_tpu import cli
    from paddle_tpu.obs import bench_history

    path = str(tmp_path / "traj.json")
    metrics = bench_history.summary_metrics("gen_failover",
                                            smoke_summary)
    assert metrics["lost_tokens"] == 0 and metrics["dup_tokens"] == 0
    bench_history.record("gen_failover", metrics, path=path,
                         baseline=True, source="test_bench_gen_failover")
    bench_history.record("gen_failover", dict(metrics), path=path)
    assert cli.main(["bench", "check", "--trajectory", path]) == 0
    degraded = dict(metrics, lost_tokens=1)
    bench_history.record("gen_failover", degraded, path=path)
    assert cli.main(["bench", "check", "--trajectory", path]) == 1


@pytest.mark.slow
def test_acceptance_full_run():
    summary = bench_gen_failover.run_bench()
    kill = summary["kill_drill"]
    assert kill["lost_tokens"] == 0 and kill["dup_tokens"] == 0
    assert kill["client_errors"] == 0 and kill["token_identical"]
