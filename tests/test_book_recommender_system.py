"""Book test: MovieLens rating regression converges
(reference ``python/paddle/fluid/tests/book/test_recommender_system.py``)."""

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.layers as layers

ml = fluid.dataset.movielens


def test_recommender_system():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = layers.data(name="user_id", shape=[1], dtype="int64")
        gender = layers.data(name="gender_id", shape=[1], dtype="int64")
        age = layers.data(name="age_id", shape=[1], dtype="int64")
        job = layers.data(name="job_id", shape=[1], dtype="int64")
        mid = layers.data(name="movie_id", shape=[1], dtype="int64")
        label = layers.data(name="score", shape=[1], dtype="float32")

        usr_emb = layers.embedding(input=uid, size=[ml.max_user_id() + 1, 32])
        usr_gender = layers.embedding(input=gender, size=[2, 8])
        usr_age = layers.embedding(input=age, size=[len(ml.age_table), 8])
        usr_job = layers.embedding(input=job, size=[ml.max_job_id() + 1, 8])
        usr_combined = layers.fc(
            input=[usr_emb, usr_gender, usr_age, usr_job], size=64,
            act="tanh")

        mov_emb = layers.embedding(input=mid,
                                   size=[ml.max_movie_id() + 1, 32])
        mov_combined = layers.fc(input=mov_emb, size=64, act="tanh")

        inference = layers.cos_sim(X=usr_combined, Y=mov_combined)
        scale_infer = layers.scale(x=inference, scale=5.0)
        cost = layers.square_error_cost(input=scale_infer, label=label)
        avg_cost = layers.mean(cost)
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    batch, losses = [], []
    for sample in ml.train()():
        batch.append(sample)
        if len(batch) < 64:
            continue
        feed = {
            "user_id": np.asarray([[b[0]] for b in batch], "int64"),
            "gender_id": np.asarray([[b[1]] for b in batch], "int64"),
            "age_id": np.asarray([[b[2]] for b in batch], "int64"),
            "job_id": np.asarray([[b[3]] for b in batch], "int64"),
            "movie_id": np.asarray([[b[4]] for b in batch], "int64"),
            "score": np.asarray([[b[7]] for b in batch], "float32"),
        }
        batch = []
        (lv,) = exe.run(main, feed=feed, fetch_list=[avg_cost])
        losses.append(float(np.asarray(lv).reshape(())))
    # must beat predicting the global mean (variance of scores ~ 0.5)
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.6, (
        np.mean(losses[:5]), np.mean(losses[-5:]))
