"""Training sentinel: numerical-fault detection, batch quarantine,
automatic rollback, and deterministic replay (docs/fault_tolerance.md
"Numerical faults").

The acceptance drill runs the full escalation ladder IN-PROCESS (no
subprocess boots — tier-1-safe): with ``sentinel.nan`` armed at step k,
``run_pipeline`` (i) skips the poisoned updates and quarantines repro
bundles, (ii) rolls back to the last known-good checkpoint after K
strikes, (iii) resumes and reaches the SAME final loss as an uninjected
run; the bundle re-triggers the non-finite under ``paddle_tpu replay``;
and with the sentinel disabled ``Executor.run`` keeps the donating fast
path with zero sentinel work (structural check, not wall-clock)."""

import os
import pickle

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.datapipe as dp
import paddle_tpu.layers as layers
from paddle_tpu import cli, profiler
from paddle_tpu.fault import (CheckpointManager, NumericalFault, Sentinel,
                              chaos, replay_bundle, sentinel_from_env)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    chaos.clear()
    yield
    chaos.clear()


def _counter(name):
    return profiler.runtime_metrics.counter(name)


def build_model(seed=11, lr=0.05):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[6], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1, param_attr="w", bias_attr="b")
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9) \
            .minimize(loss)
    return main, startup, loss


def make_samples(n=40, seed=7):
    rng = np.random.RandomState(seed)
    w_true = np.arange(1.0, 7.0, dtype="float32").reshape(6, 1)
    xs = rng.rand(n, 6).astype("float32")
    return [{"x": xs[i], "y": (xs[i:i + 1] @ w_true)[0].astype("float32")}
            for i in range(n)]


def make_pipe(samples):
    # shuffle (RNG + buffer state) AND a threaded prefetch stage: the
    # rollback must restore/requiesce both kinds of state correctly
    return dp.InMemorySource(samples).shuffle(8, seed=3) \
        .batch(4, drop_last=True).prefetch(depth=2)


def _feed(step):
    rng = np.random.RandomState(step)
    xs = rng.rand(8, 6).astype("float32")
    return {"x": xs, "y": xs.sum(1, keepdims=True).astype("float32")}


# ---------------------------------------------------------------------------
# detection unit tests
# ---------------------------------------------------------------------------

class TestDetection:
    def test_non_finite_state_trips_and_names_culprit(self):
        s = Sentinel(cadence=1, strikes=99, spike_factor=None)
        bad = np.array([1.0, np.nan], "float32")
        with pytest.raises(NumericalFault) as ei:
            s.after_step(["loss"], [np.float32(1.0)], {"w": bad})
        assert ei.value.reason == "non_finite"
        assert "w" in ei.value.bad

    def test_non_finite_loss_trips(self):
        s = Sentinel(cadence=1, strikes=99, spike_factor=None)
        with pytest.raises(NumericalFault):
            s.after_step(["loss"], [np.float32(np.inf)], {})

    def test_integer_state_never_trips(self):
        s = Sentinel(cadence=1, strikes=99, spike_factor=None)
        fetches, state = s.after_step(
            ["step"], [np.int64(3)], {"count": np.arange(4)})
        assert state["count"].shape == (4,)

    def test_cadence_skips_off_steps(self):
        s = Sentinel(cadence=3, strikes=99, spike_factor=None)
        bad = {"w": np.array([np.nan], "float32")}
        s.after_step([], [], bad)       # tick 1: unchecked
        s.after_step([], [], bad)       # tick 2: unchecked
        with pytest.raises(NumericalFault):
            s.after_step([], [], bad)   # tick 3: checked
        assert _counter("sentinel.checks") >= 1

    def test_ema_spike_detector(self):
        s = Sentinel(cadence=1, strikes=99, spike_factor=3.0,
                     spike_warmup=3)
        for v in (1.0, 1.1, 0.9, 1.0):
            s.after_step(["loss"], [np.float32(v)], {})
        with pytest.raises(NumericalFault) as ei:
            s.after_step(["loss"], [np.float32(50.0)], {})
        assert ei.value.reason == "loss_spike"

    def test_spike_detector_warms_up_first(self):
        s = Sentinel(cadence=1, strikes=99, spike_factor=3.0,
                     spike_warmup=5)
        # huge swings inside the warmup window must not trip
        for v in (1.0, 99.0, 0.01):
            s.after_step(["loss"], [np.float32(v)], {})

    def test_clean_check_resets_strikes(self, tmp_path):
        s = Sentinel(cadence=1, strikes=2, spike_factor=None,
                     quarantine_dir=str(tmp_path))
        f = NumericalFault("x", reason="non_finite")
        assert s.handle_fault(f, step=1) is None     # strike 1
        assert s._strikes == 1
        s.after_step([], [], {"w": np.ones(2, "float32")})  # clean
        assert s._strikes == 0

    def test_phantom_promotion_keeps_rollback_budget(self):
        """mark_good returning None (checkpoint rotated away before
        promotion) is not forward progress: the rollback budget must
        not refill."""
        class Mgr:
            dirname = "."

            def mark_good(self, step):
                return None

        s = Sentinel(manager=Mgr())
        s._rollbacks = 2
        s._promote(5)
        assert s._rollbacks == 2

    def test_sentinel_from_env_grammar(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SENTINEL", "0")
        assert sentinel_from_env() is None
        monkeypatch.setenv("PADDLE_TPU_SENTINEL", "1")
        assert isinstance(sentinel_from_env(), Sentinel)
        monkeypatch.setenv(
            "PADDLE_TPU_SENTINEL",
            "cadence=4;strikes=2,spike=off;good_after=3")
        s = sentinel_from_env()
        assert (s.cadence, s.strikes, s.spike_factor,
                s.mark_good_after) == (4, 2, None, 3)
        monkeypatch.setenv("PADDLE_TPU_SENTINEL", "bogus=1")
        with pytest.raises(ValueError):
            sentinel_from_env()


# ---------------------------------------------------------------------------
# skip-step semantics inside Executor.run
# ---------------------------------------------------------------------------

class TestSkipStep:
    def test_tripped_step_discards_update(self):
        main, startup, loss = build_model()
        exe = fluid.Executor()
        exe.run(startup)
        s = Sentinel(cadence=1, strikes=99, spike_factor=None)
        exe.run(main, feed=_feed(1), fetch_list=[loss], sentinel=s)
        w_before = np.asarray(fluid.executor.fetch_var("w")).copy()
        chaos.inject("sentinel.nan", times=1)
        with pytest.raises(NumericalFault) as ei:
            exe.run(main, feed=_feed(2), fetch_list=[loss], sentinel=s)
        assert ei.value.injected
        # the poisoned update never reached the scope
        w_after = np.asarray(fluid.executor.fetch_var("w"))
        np.testing.assert_array_equal(w_before, w_after)
        assert np.isfinite(w_after).all()
        # and the guard recovers: the next clean step trains normally
        chaos.clear("sentinel.nan")
        exe.run(main, feed=_feed(3), fetch_list=[loss], sentinel=s)
        assert not np.array_equal(
            w_after, np.asarray(fluid.executor.fetch_var("w")))

    def test_injection_defers_to_the_next_checked_step(self):
        """With cadence>1 the failpoint must poison a CHECKED step —
        an off-cadence poison would be committed unseen and the later
        check would quarantine an innocent batch."""
        s = Sentinel(cadence=2, strikes=99, spike_factor=None)
        chaos.inject("sentinel.nan", times=1)
        state = {"w": np.ones(3, "float32")}
        # tick 1 is off-cadence: unpoisoned, unchecked, returned as-is
        _, out = s.after_step(["loss"], [np.float32(1.0)], state)
        assert np.isfinite(np.asarray(out["w"])).all()
        with pytest.raises(NumericalFault) as ei:
            s.after_step(["loss"], [np.float32(1.0)], state)  # tick 2
        assert ei.value.injected and ei.value.step == 2

    def test_direct_run_without_pipeline_propagates_fault(self):
        main, startup, loss = build_model()
        exe = fluid.Executor()
        exe.run(startup)
        s = Sentinel(cadence=1, strikes=1, spike_factor=None)
        chaos.inject("sentinel.nan", times=1)
        with pytest.raises(NumericalFault):
            exe.run(main, feed=_feed(1), fetch_list=[loss], sentinel=s)

    def test_disabled_sentinel_is_structurally_free(self, monkeypatch):
        """With sentinel=None the executor must never touch the sentinel
        (no check, no device sync) and must keep donating state buffers
        — the structural form of the 'no per-step sync' guarantee (the
        2-vCPU bench host makes wall-clock checks meaningless)."""
        main, startup, loss = build_model()
        exe = fluid.Executor()
        exe.run(startup)
        seen = []
        orig = Sentinel.after_step

        def spy(self, *a, **k):
            seen.append(1)
            return orig(self, *a, **k)

        monkeypatch.setattr(Sentinel, "after_step", spy)
        exe.run(main, feed=_feed(1), fetch_list=[loss])
        assert not seen, "sentinel code ran on an unguarded step"
        compiled = [c for c in exe._cache.values()
                    if hasattr(c, "donated")]
        assert compiled and all(c.donated for c in compiled), \
            "unguarded steps must keep the donating executable"
        # the guarded variant is a SEPARATE, non-donating executable
        s = Sentinel(cadence=1, strikes=99, spike_factor=None)
        exe.run(main, feed=_feed(2), fetch_list=[loss], sentinel=s)
        assert seen, "sentinel guard did not run on a guarded step"
        assert [c for c in exe._cache.values()
                if hasattr(c, "donated") and not c.donated]


# ---------------------------------------------------------------------------
# the end-to-end chaos drill (acceptance criterion)
# ---------------------------------------------------------------------------

class TestEscalationLadderEndToEnd:
    @pytest.fixture(scope="class")
    def drill(self, tmp_path_factory):
        """One reference run + one chaos-injected run, shared by every
        assertion in this class (the drill is the expensive part: ~20
        checkpointed steps; the assertions are cheap reads)."""
        root = tmp_path_factory.mktemp("ladder")
        before = {n: _counter(n) for n in
                  ("sentinel.skipped_steps", "sentinel.quarantined",
                   "sentinel.rollbacks")}
        ref_outs, _, _ = self._run_training(root, "ref", inject=False)
        got_outs, mgr, sentinel = self._run_training(root, "chaos",
                                                     inject=True)
        delta = {n: _counter(n) - before[n] for n in before}
        return {"ref_outs": ref_outs, "got_outs": got_outs, "mgr": mgr,
                "sentinel": sentinel, "delta": delta}

    def _run_training(self, tmp_path, tag, inject=False):
        samples = make_samples()
        main, startup, loss = build_model()
        scope = fluid.Scope()
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        pipe = make_pipe(samples)
        mgr = None
        sentinel = None
        on_step = None
        if inject:
            mgr = CheckpointManager(str(tmp_path / tag), keep=4,
                                    executor=exe, main_program=main,
                                    scope=scope, datapipe=pipe)
            sentinel = Sentinel(manager=mgr, cadence=1, strikes=2,
                                mark_good_after=1)

            def on_step(step, fetches):
                mgr.save(step)
                sentinel.note_checkpoint(step)

            # poison steps 5 and 6 (after=4, times=2): two consecutive
            # strikes -> rollback
            chaos.inject("sentinel.nan", after=4, times=2)
        outs = exe.run_pipeline(main, pipe, fetch_list=[loss.name],
                                scope=scope, sentinel=sentinel,
                                on_step=on_step)
        chaos.clear("sentinel.nan")
        return outs, mgr, sentinel

    def test_skip_quarantine_rollback_resume_same_loss(self, drill):
        sentinel, mgr = drill["sentinel"], drill["mgr"]
        # (i) the two poisoned steps were skipped + quarantined
        assert drill["delta"]["sentinel.skipped_steps"] == 2
        assert drill["delta"]["sentinel.quarantined"] == 2
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        assert len(bundles) == 2
        # (ii) one rollback to the last known-good checkpoint
        assert drill["delta"]["sentinel.rollbacks"] == 1
        # rollback target: step 2 was the newest promoted known-good
        # (step 3's promotion window was voided by the strikes)
        assert mgr.last_good_step() is not None
        # (iii) resumed and converged to the SAME losses: the rollback
        # rewound params AND datapipe position, and run_pipeline dropped
        # the rewound entries, so the returned list is the reference
        # sequence — every batch applied exactly once, skipped/undone
        # steps absent
        ref_losses = [float(np.asarray(o[0]).reshape(-1)[0])
                      for o in drill["ref_outs"]]
        got_losses = [float(np.asarray(o[0]).reshape(-1)[0])
                      for o in drill["got_outs"]]
        assert len(ref_losses) == 10
        np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5)

    def test_quarantine_bundle_replays_the_fault(self, drill):
        sentinel = drill["sentinel"]
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        # the bundle is a self-contained pickle
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        assert bundle["reason"] == "non_finite" and bundle["injected"]
        assert bundle["repro"]["feed"] and bundle["repro"]["state"]
        # library replay reproduces the non-finite on CPU
        report = replay_bundle(path)
        assert report["reproduced"] and report["reason"] == "non_finite"
        # ... and so does the CLI (exit 0 = reproduced)
        assert cli.main(["replay", path]) == 0
        assert cli.main(["replay", "--json", path]) == 0

    def test_localize_names_the_poisoned_op(self, drill, capsys):
        """The localization drill: ``replay --localize`` re-executes the
        quarantined step op-by-op with probes armed and names the EXACT
        op the poison landed on — the loss-producing ``reduce_mean``
        appended by :func:`build_model` in THIS file — with its creation
        site and the input-stat trail leading into it."""
        from paddle_tpu.obs import numerics
        sentinel = drill["sentinel"]
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        report = numerics.localize_bundle(path)
        assert report["localized"] and report["injected"]
        fb = report["first_bad_op"]
        assert fb["type"] == "reduce_mean"
        # creation site attributes the op to user code — this test file
        assert fb["creation_site"][0].endswith("test_sentinel.py")
        # the op's inputs were still finite: the fault is localized to
        # this op, not inherited from upstream
        assert all(s.get("finite_frac") == 1.0
                   for s in fb["inputs"].values())
        assert any(s.get("finite_frac", 1.0) < 1.0
                   for s in fb["outputs"].values())
        assert fb["trail"][-1]["type"] == "reduce_mean"
        assert report["ops_probed"] >= fb["index"] + 1
        # CLI: exit 0 = localized; the prose names op type + site
        assert cli.main(["replay", "--localize", path]) == 0
        out = capsys.readouterr().out
        assert "reduce_mean" in out and "test_sentinel.py" in out
        assert cli.main(["replay", "--localize", "--json", path]) == 0

    def test_localize_clean_and_malformed_exit_codes(self, drill,
                                                     tmp_path):
        """Un-injected bundles replay finite op-by-op — exit 1 (nothing
        to localize); garbage bundles are malformed — exit 2, mirroring
        plain replay's triage contract."""
        sentinel = drill["sentinel"]
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        bundle["injected"] = False   # no op-level poison: replays clean
        clean = str(tmp_path / "clean.pkl")
        with open(clean, "wb") as f:
            pickle.dump(bundle, f, protocol=4)
        assert cli.main(["replay", "--localize", clean]) == 1
        garbage = tmp_path / "garbage.pkl"
        garbage.write_bytes(b"\x80\x04not a pickle")
        assert cli.main(["replay", "--localize", str(garbage)]) == 2
        assert cli.main(["replay", "--localize",
                         str(tmp_path / "missing.pkl")]) == 2

    def test_bundle_and_sentinel_carry_health_digest(self, drill):
        """Guarded steps fuse param/update norms into the finite check;
        the digest rides the sentinel (escalation context), the
        quarantine bundle (forensics), and the train.* gauges the
        ledger snapshots."""
        sentinel = drill["sentinel"]
        assert sentinel.last_health is not None
        assert set(sentinel.last_health) == \
            {"param_norm", "grad_norm", "update_ratio"}
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        assert bundle["health"] is not None
        assert "param_norm" in bundle["health"]
        for g in ("train.param_norm", "train.grad_norm",
                  "train.update_ratio"):
            assert profiler.runtime_metrics.gauge(g) is not None

    def test_replay_clean_bundle_exits_nonzero(self, drill, tmp_path):
        """A bundle whose step replays clean (fault not injected, math
        fine) reports no repro — exit 1, the 'suspect hardware' verdict."""
        sentinel = drill["sentinel"]
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        bundle["injected"] = False   # pretend the NaN came from the chip
        clean = str(tmp_path / "clean.pkl")
        with open(clean, "wb") as f:
            pickle.dump(bundle, f, protocol=4)
        assert cli.main(["replay", clean]) == 1
        assert cli.main(["replay", str(tmp_path / "missing.pkl")]) == 2
        # a truncated/garbage bundle is "malformed" (2) — never the
        # "replayed clean, suspect hardware" verdict (1)
        garbage = tmp_path / "garbage.pkl"
        garbage.write_bytes(b"\x80\x04not a pickle")
        assert cli.main(["replay", str(garbage)]) == 2

    def test_replay_preserves_live_armed_failpoint(self, drill):
        """Regression: in-process replay of an injected bundle used to
        inject+clear sentinel.nan, silently clobbering (then disarming)
        a live drill armed for a later step."""
        sentinel = drill["sentinel"]
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        chaos.inject("sentinel.nan", after=100, times=3)   # live drill
        report = replay_bundle(path)
        assert report["reproduced"]
        fp = chaos.swap("sentinel.nan", None)   # inspect AND disarm
        assert fp is not None, "replay disarmed the live drill"
        assert fp.after == 100 and fp.times == 3

    def test_unreplayable_bundle_exits_two(self, drill, tmp_path):
        """A bundle whose step cannot RE-EXECUTE (version skew, shape
        drift) must exit 2 (unreplayable), never 1 — exit 1 is the
        'replayed clean, suspect hardware' verdict automated triage
        trusts."""
        sentinel = drill["sentinel"]
        bundles = sorted(os.listdir(sentinel.quarantine_dir))
        path = os.path.join(sentinel.quarantine_dir, bundles[0])
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        # drift the feature width: re-execution dies inside the jitted
        # step (a raw XLA shape error, not a bundle-load error)
        feed = dict(bundle["repro"]["feed"])
        feed["x"] = np.zeros((4, 3), "float32")
        bundle["repro"] = dict(bundle["repro"], feed=feed)
        skewed = str(tmp_path / "skewed.pkl")
        with open(skewed, "wb") as f:
            pickle.dump(bundle, f, protocol=4)
        assert cli.main(["replay", skewed]) == 2

    def test_loss_spike_bundle_replays(self, tmp_path):
        """A deterministic loss spike (bad batch, finite values) must
        reproduce under replay: the bundle carries the EMA baseline the
        loss spiked against."""
        main, startup, loss = build_model()
        exe = fluid.Executor()
        exe.run(startup)
        s = Sentinel(cadence=1, strikes=99, spike_factor=0.5,
                     spike_warmup=1, quarantine_dir=str(tmp_path))
        # seed a baseline far below any real loss: the first step spikes
        s._ema, s._ema_n = 1e-6, 5
        with pytest.raises(NumericalFault) as ei:
            exe.run(main, feed=_feed(1), fetch_list=[loss], sentinel=s)
        assert ei.value.reason == "loss_spike"
        path = s.quarantine(ei.value)
        report = replay_bundle(path)
        assert report["reproduced"] and report["reason"] == "loss_spike"
        assert cli.main(["replay", path]) == 0

    def test_rollback_exact_once_under_restart_renumbering(self, tmp_path):
        """Regression: a restarted trainer renumbering its steps from 0
        under a directory still holding a prior run's higher ckpt-N.
        run_pipeline used to detect commits by diffing latest_step()
        (the directory max, stuck at the stale N), so no rollback mark
        was ever recorded and the rollback truncated the ENTIRE returned
        list — the batches before the restore point never re-ran and
        vanished from it.  Commit detection must key off the manager's
        own in-process saves."""
        samples = make_samples()
        main, startup, loss = build_model()
        scope = fluid.Scope()
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        # the prior run's leftover: a checkpoint numbered far above
        # anything this loop will save
        stale = CheckpointManager(str(tmp_path), keep=8, executor=exe,
                                  main_program=main, scope=scope,
                                  datapipe=make_pipe(samples))
        stale.save(50)
        pipe = make_pipe(samples)
        mgr = CheckpointManager(str(tmp_path), keep=8, executor=exe,
                                main_program=main, scope=scope,
                                datapipe=pipe)
        assert mgr.latest_step() == 50      # the trap this test locks
        sentinel = Sentinel(manager=mgr, cadence=1, strikes=2,
                            mark_good_after=1)

        def on_step(step, fetches):
            mgr.save(step)                  # renumbered from 0
            sentinel.note_checkpoint(step)

        chaos.inject("sentinel.nan", after=4, times=2)
        outs = exe.run_pipeline(main, pipe, fetch_list=[loss.name],
                                scope=scope, sentinel=sentinel,
                                on_step=on_step)
        chaos.clear("sentinel.nan")
        # the ladder ran: rollback to one of THIS loop's checkpoints
        assert mgr.last_good_step() is not None
        assert mgr.last_good_step() < 50
        # exactly-once: all 10 batches present (40 samples / batch 4),
        # the rewound entries re-ran and re-appended
        assert len(outs) == 10

    def test_unrecoverable_without_manager_reraises(self, tmp_path):
        samples = make_samples(16)
        main, startup, loss = build_model()
        scope = fluid.Scope()
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        pipe = dp.InMemorySource(samples).batch(4)
        sentinel = Sentinel(manager=None, cadence=1, strikes=1,
                            spike_factor=None,
                            quarantine_dir=str(tmp_path))
        chaos.inject("sentinel.nan", times=1)
        with pytest.raises(NumericalFault):
            exe.run_pipeline(main, pipe, fetch_list=[loss.name],
                             scope=scope, sentinel=sentinel)
        # the fault was still quarantined on the way out
        assert any(n.startswith("quarantine-")
                   for n in os.listdir(str(tmp_path)))
