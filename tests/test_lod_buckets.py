"""Bucketed dynamic-LoD mode (lod.py; VERDICT r1 item 4): a streaming
ragged corpus compiles O(#buckets) executables instead of O(#batches), with
results identical to the exact static-lod path."""

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers


def _rand_lod(rng, batch, max_len):
    lengths = rng.randint(1, max_len + 1, size=batch)
    splits = np.concatenate([[0], np.cumsum(lengths)])
    return [[int(s) for s in splits]]


def _build_seq_model(kind, n_rows_hint=64, dim=8):
    x = layers.data(name="x", shape=[n_rows_hint, dim],
                    append_batch_size=False, lod_level=1)
    x.stop_gradient = False
    if kind == "pool_chain":
        h = layers.sequence_softmax(layers.fc(input=x, size=1,
                                              bias_attr=False,
                                              param_attr="w_sm"))
        # weighted sum pool over the sequence then a regression head
        weighted = layers.elementwise_mul(x, h, axis=0)
        pooled = layers.sequence_pool(weighted, "sum")
        avg = layers.sequence_pool(x, "average")
        out = layers.fc(input=layers.concat([pooled, avg], axis=1), size=1,
                        param_attr="w_out")
    elif kind == "lstm":
        proj = layers.fc(input=x, size=4 * dim, bias_attr=False,
                         param_attr="w_proj")
        hidden, _ = layers.dynamic_lstm(proj, size=4 * dim,
                                        param_attr="w_lstm",
                                        bias_attr="b_lstm",
                                        use_peepholes=False)
        out = layers.fc(input=layers.sequence_pool(hidden, "last"), size=1,
                        param_attr="w_out")
    elif kind == "gru":
        proj = layers.fc(input=x, size=3 * dim, bias_attr=False,
                         param_attr="w_proj")
        hidden = layers.dynamic_gru(proj, size=dim, param_attr="w_gru",
                                    bias_attr="b_gru")
        out = layers.fc(input=layers.sequence_pool(hidden, "max"), size=1,
                        param_attr="w_out")
    elif kind == "expand":
        # pool -> expand back over tokens -> residual mix (the
        # attention-context pattern) -> pool
        pooled = layers.sequence_pool(x, "average")
        ctx_feat = layers.fc(input=pooled, size=dim, param_attr="w_ctx")
        expanded = layers.sequence_expand(x=ctx_feat, y=x)
        mixed = layers.elementwise_add(x, expanded)
        reshaped = layers.sequence_reshape(mixed, new_dim=dim // 2)
        out = layers.fc(input=layers.sequence_pool(reshaped, "sum"),
                        size=1, param_attr="w_out")
    elif kind == "conv":
        h = layers.sequence_conv(x, num_filters=6, filter_size=3,
                                 param_attr="w_sc", bias_attr="b_sc")
        out = layers.fc(input=layers.sequence_pool(h, "sum"), size=1,
                        param_attr="w_out")
    loss = layers.reduce_mean(out)
    return x, out, loss


class TestBucketedEqualsStatic:
    @pytest.mark.parametrize("kind", ["pool_chain", "lstm", "gru", "conv", "expand"])
    def test_forward_parity(self, kind):
        rng = np.random.RandomState(0)
        batch, dim = 4, 8
        lod = _rand_lod(rng, batch, 9)
        n = lod[0][-1]
        data = rng.rand(n, dim).astype("float32")

        x, out, loss = _build_seq_model(kind, dim=dim)
        prog = fluid.default_main_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())

        prog.lod_buckets = False
        (want,) = exe.run(prog, feed={"x": (data, lod)}, fetch_list=[out])
        prog.lod_buckets = True
        (got,) = exe.run(prog, feed={"x": (data, lod)}, fetch_list=[out])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=1e-6)

    def test_training_parity(self):
        """A full train step (fwd+bwd+sgd) under buckets matches exact-lod
        execution."""
        rng = np.random.RandomState(1)
        lod = _rand_lod(rng, 4, 7)
        n = lod[0][-1]
        data = rng.rand(n, 8).astype("float32")

        results = {}
        for bucketed in (False, True):
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 11
            with fluid.program_guard(main, startup):
                x, out, loss = _build_seq_model("lstm")
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
            main.lod_buckets = bucketed
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor()
                exe.run(startup)
                for _ in range(3):
                    (lv,) = exe.run(main, feed={"x": (data, lod)},
                                    fetch_list=[loss])
                results[bucketed] = (
                    float(np.asarray(lv).reshape(-1)[0]),
                    np.asarray(scope.find_var("w_lstm")).copy())
        np.testing.assert_allclose(results[True][0], results[False][0],
                                   rtol=2e-5)
        np.testing.assert_allclose(results[True][1], results[False][1],
                                   rtol=2e-5, atol=1e-6)


class TestBoundedCompiles:
    def test_100_distinct_lods_few_compiles(self):
        """The VERDICT done-criterion: 100 distinct-lod batches trigger
        <= 8 executables."""
        rng = np.random.RandomState(2)
        x, out, loss = _build_seq_model("pool_chain")
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        prog = fluid.default_main_program()
        prog.lod_buckets = True
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())

        seen_lods = set()
        losses = []
        for step in range(100):
            lod = _rand_lod(rng, 4, 16)
            seen_lods.add(tuple(lod[0]))
            data = rng.rand(lod[0][-1], 8).astype("float32")
            (lv,) = exe.run(prog, feed={"x": (data, lod)},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert len(seen_lods) > 60          # genuinely distinct lods
        assert np.isfinite(losses).all()
        assert len(exe._cache) <= 8, len(exe._cache)


class TestBucketedNewOps:
    """Round-3 dialect completion (VERDICT r2 item 5): sequence_slice,
    lod_reset, sequence_concat, sequence_erase run TRACED under buckets
    with results matching the exact static-lod path."""

    def _drive(self, build, feeds, bucketed):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            fetch = build()
        main.lod_buckets = bucketed
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            outs = exe.run(main, feed=feeds, fetch_list=[fetch])
        return np.asarray(outs[0])

    def test_slice_concat_reset_parity(self):
        rng = np.random.RandomState(3)
        lod = [[0, 3, 5, 9]]
        n = lod[0][-1]
        data = rng.rand(n, 4).astype("float32")
        lod2 = [[0, 2, 4, 6]]
        data2 = rng.rand(6, 4).astype("float32")
        off = np.array([0, 1, 2], "int64")
        ln = np.array([2, 1, 2], "int64")

        def build():
            x = layers.data(name="x", shape=[-1, 4],
                            append_batch_size=False, lod_level=1)
            x2 = layers.data(name="x2", shape=[-1, 4],
                             append_batch_size=False, lod_level=1)
            o = layers.data(name="o", shape=[3], dtype="int64",
                            append_batch_size=False)
            l = layers.data(name="l", shape=[3], dtype="int64",
                            append_batch_size=False)
            sl = layers.sequence_slice(x, o, l)
            cc = layers.sequence_concat([sl, x2])
            pooled = layers.sequence_pool(cc, "sum")
            return layers.fc(input=pooled, size=1, bias_attr=False,
                             param_attr=fluid.ParamAttr(
                                 "w_p", initializer=fluid.initializer
                                 .Constant(1.0))).name

        feeds = {"x": (data, lod), "x2": (data2, lod2), "o": off, "l": ln}
        want = self._drive(build, feeds, bucketed=False)
        got = self._drive(build, feeds, bucketed=True)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)

    def test_lod_reset_parity(self):
        rng = np.random.RandomState(4)
        lod = [[0, 2, 6]]
        data = rng.rand(6, 4).astype("float32")

        def build():
            x = layers.data(name="x", shape=[-1, 4],
                            append_batch_size=False, lod_level=1)
            rs = layers.lod_reset(x, target_lod=[0, 3, 6])
            pooled = layers.sequence_pool(rs, "average")
            return layers.fc(input=pooled, size=1, bias_attr=False,
                             param_attr=fluid.ParamAttr(
                                 "w_q", initializer=fluid.initializer
                                 .Constant(1.0))).name

        want = self._drive(build, {"x": (data, lod)}, bucketed=False)
        got = self._drive(build, {"x": (data, lod)}, bucketed=True)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)

    def test_erase_parity(self):
        ids = np.array([[1], [0], [3], [0], [2], [5], [0], [4]], "int64")
        lod = [[0, 3, 8]]

        def build():
            x = layers.data(name="ids", shape=[-1, 1], dtype="int64",
                            append_batch_size=False, lod_level=1)
            er = layers.sequence_erase(x, tokens=[0])
            f = layers.cast(er, "float32")
            return layers.sequence_pool(f, "sum").name

        want = self._drive(build, {"ids": (ids, lod)}, bucketed=False)
        got = self._drive(build, {"ids": (ids, lod)}, bucketed=True)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        np.testing.assert_allclose(want.reshape(-1), [4.0, 11.0])

    def test_streaming_bounded_compiles_through_new_ops(self):
        """100 distinct-lod batches through slice+concat+erase+reset stay
        within a handful of executables (the dialect is complete for the
        streaming set)."""
        from paddle_tpu import executor as exec_mod
        rng = np.random.RandomState(5)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[-1, 4],
                            append_batch_size=False, lod_level=1)
            o = layers.data(name="o", shape=[4], dtype="int64",
                            append_batch_size=False)
            l = layers.data(name="l", shape=[4], dtype="int64",
                            append_batch_size=False)
            sl = layers.sequence_slice(x, o, l)
            cc = layers.sequence_concat([sl, x])
            pooled = layers.sequence_pool(cc, "sum")
            out = layers.fc(input=pooled, size=1, param_attr="w_s")
            loss = layers.reduce_mean(out)
        main.lod_buckets = True
        exe = fluid.Executor()
        exe.run(startup)
        before = len(exe._cache) if hasattr(exe, "_cache") else None
        losses = []
        for _ in range(100):
            lod = _rand_lod(rng, 4, 12)
            n = lod[0][-1]
            data = rng.rand(n, 4).astype("float32")
            lengths = np.diff(np.asarray(lod[0]))
            ln = np.maximum(lengths - 1, 1).astype("int64")
            off = np.zeros(4, "int64")
            (lv,) = exe.run(main, feed={"x": (data, lod), "o": off,
                                        "l": ln}, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert np.isfinite(losses).all()


def _build_nmt_decoder(dict_size=16, emb=8, hid=8):
    """The book NMT decoder shape: GRU encoder -> DynamicRNN decoder with
    a memory initialized from the encoder's last step (the streaming-
    decode path of VERDICT r3 item 4)."""
    src = layers.data(name="src", shape=[-1, 1], dtype="int64",
                      append_batch_size=False, lod_level=1)
    trg = layers.data(name="trg", shape=[-1, 1], dtype="int64",
                      append_batch_size=False, lod_level=1)
    label = layers.data(name="label", shape=[-1, 1], dtype="int64",
                        append_batch_size=False, lod_level=1)
    src_emb = layers.embedding(input=src, size=[dict_size, emb],
                               param_attr="nmt_semb")
    enc_proj = layers.fc(input=src_emb, size=hid * 3, param_attr="nmt_ep")
    enc = layers.dynamic_gru(input=enc_proj, size=hid,
                             param_attr="nmt_gru", bias_attr="nmt_grub")
    enc_last = layers.sequence_last_step(enc)
    trg_emb = layers.embedding(input=trg, size=[dict_size, emb],
                               param_attr="nmt_temb")

    drnn = layers.DynamicRNN()
    with drnn.block():
        cur = drnn.step_input(trg_emb)
        mem = drnn.memory(init=enc_last)
        dec_h = layers.fc(input=[cur, mem], size=hid, act="tanh",
                          param_attr="nmt_dec")
        drnn.update_memory(mem, dec_h)
        out = layers.fc(input=dec_h, size=dict_size, act="softmax",
                        param_attr="nmt_out")
        drnn.output(out)
    predictions = drnn()
    cost = layers.cross_entropy(input=predictions, label=label)
    return layers.mean(cost)


def _nmt_batch(rng, batch, src_max, trg_max, dict_size=16):
    s_lod = _rand_lod(rng, batch, src_max)
    t_lod = _rand_lod(rng, batch, trg_max)
    src = rng.randint(0, dict_size, (s_lod[0][-1], 1)).astype("int64")
    trg = rng.randint(0, dict_size, (t_lod[0][-1], 1)).astype("int64")
    lab = rng.randint(0, dict_size, (t_lod[0][-1], 1)).astype("int64")
    return {"src": (src, s_lod), "trg": (trg, t_lod),
            "label": (lab, t_lod)}


class TestStreamingDecodeUnderBuckets:
    """DynamicRNN decode under bucketed dynamic LoD (r4): the plumbing
    ops (lod_rank_table / lod_tensor_to_array / array_to_lod_tensor /
    shrink_rnn_memory / max_sequence_len) run with runtime splits."""

    def test_decoder_parity_bucketed_vs_static(self):
        rng = np.random.RandomState(7)
        feed = _nmt_batch(rng, 4, 6, 5)
        results = {}
        for bucketed in (False, True):
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 3
            with fluid.program_guard(main, startup):
                avg = _build_nmt_decoder()
                fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
            main.lod_buckets = bucketed
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe = fluid.Executor()
                exe.run(startup)
                losses = []
                for _ in range(3):
                    (lv,) = exe.run(main, feed=feed, fetch_list=[avg])
                    losses.append(float(np.asarray(lv).reshape(-1)[0]))
                results[bucketed] = (
                    losses, np.asarray(scope.find_var("nmt_dec")).copy())
        np.testing.assert_allclose(results[True][0], results[False][0],
                                   rtol=3e-5)
        np.testing.assert_allclose(results[True][1], results[False][1],
                                   rtol=1e-4, atol=1e-6)

    def test_decoder_100_distinct_lods_bounded_compiles(self):
        """The VERDICT done-criterion: the NMT decoder over a stream of
        100 distinct (src, trg) LoD pairs compiles O(buckets)."""
        rng = np.random.RandomState(8)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            avg = _build_nmt_decoder()
            fluid.optimizer.SGD(learning_rate=0.05).minimize(avg)
        main.lod_buckets = True
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            seen = set()
            losses = []
            for _ in range(100):
                feed = _nmt_batch(rng, 4, 14, 11)
                seen.add((tuple(feed["src"][1][0]),
                          tuple(feed["trg"][1][0])))
                (lv,) = exe.run(main, feed=feed, fetch_list=[avg])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
            assert len(seen) > 80, "lods not distinct enough"
            assert np.isfinite(losses).all()
            # two INDEPENDENT ragged feeds -> the executable count is
            # bounded by the product of their bucket sets (row buckets x
            # maxlen buckets each), not by the 100 distinct lods
            assert len(exe._cache) <= 24, len(exe._cache)


class TestRaggedXSequenceExpand:
    """sequence_expand with a RAGGED X under buckets (r4): each x
    sub-sequence repeats r_i times; real rows stay contiguous in
    reference order, the sequence table carries empty padding slots."""

    def _run(self, bucketed, xv, x_lod, yv, y_lod):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[-1, 2], dtype="float32",
                            append_batch_size=False, lod_level=1)
            y = layers.data(name="y", shape=[-1, 1], dtype="float32",
                            append_batch_size=False, lod_level=1)
            ex = layers.sequence_expand(x=x, y=y)
            s = layers.reduce_sum(ex)
        main.lod_buckets = bucketed
        exe = fluid.Executor()
        exe.run(startup)
        ov, sv = exe.run(main, feed={"x": (xv, x_lod), "y": (yv, y_lod)},
                         fetch_list=[ex.name, s.name])
        return np.asarray(ov), float(np.asarray(sv).reshape(()))

    def test_parity_with_static(self):
        rng = np.random.RandomState(11)
        x_lod = [[0, 2, 5]]                   # lens 2, 3
        y_lod = [[0, 3, 4]]                   # reps 3, 1
        xv = rng.rand(5, 2).astype("f")
        yv = rng.rand(4, 1).astype("f")
        static_out, static_sum = self._run(False, xv, x_lod, yv, y_lod)
        dyn_out, dyn_sum = self._run(True, xv, x_lod, yv, y_lod)
        n_real = static_out.shape[0]          # 2*3 + 3*1 = 9 rows
        assert n_real == 9
        np.testing.assert_allclose(dyn_out[:n_real], static_out,
                                   rtol=1e-6)
        assert np.abs(dyn_out[n_real:]).sum() == 0  # padding rows zero
        np.testing.assert_allclose(dyn_sum, static_sum, rtol=1e-6)


class TestBeamDecodeStream:
    """r5 (VERDICT r4 item 7): STREAMING NMT beam generation stays
    bucket-bounded — the full decode program (ragged-source encoder ->
    unrolled beam_search loop -> beam_search_decode backtrack) runs
    COMPILED over a stream of distinct source LoDs with O(#buckets)
    executables, and its hypotheses match the exact-static-LoD run
    batch for batch (reference posture: beam_search_op.cc decodes on
    CPU per batch)."""

    DICT, EMB, HID, B, K, T = 40, 12, 16, 4, 3, 5

    def _build_decode(self):
        D = self
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            src = layers.data(name="src", shape=[-1, 1], dtype="int64",
                              append_batch_size=False, lod_level=1)
            emb = layers.embedding(input=src, size=[D.DICT, D.EMB],
                                   param_attr=fluid.ParamAttr("bs_emb"))
            proj = layers.fc(input=emb, size=D.HID * 3, bias_attr=False,
                             param_attr=fluid.ParamAttr("bs_proj"))
            proj.lod_level = 1
            enc = layers.dynamic_gru(input=proj, size=D.HID,
                                     param_attr=fluid.ParamAttr("bs_gru"),
                                     bias_attr=fluid.ParamAttr("bs_grub"))
            enc_last = layers.sequence_last_step(enc)       # [B, HID]
            mem = layers.reshape(
                layers.expand(
                    layers.reshape(enc_last, shape=[D.B, 1, D.HID]),
                    expand_times=[1, D.K, 1]),
                shape=[D.B * D.K, D.HID])
            pre_ids = layers.assign(np.full((D.B, D.K), 1, "int64"))
            pre_scores = layers.assign(
                np.tile(np.array([[0.0] + [-1e9] * (D.K - 1)], "f"),
                        (D.B, 1)))
            beam_offset = layers.assign(
                (np.arange(D.B, dtype="int64")[:, None] * D.K)
                .repeat(D.K, 1))
            ids_arr = par_arr = None
            for t in range(D.T):
                cur = layers.embedding(
                    input=layers.reshape(pre_ids, shape=[D.B * D.K, 1]),
                    size=[D.DICT, D.EMB],
                    param_attr=fluid.ParamAttr("bs_temb"))
                dec_h = layers.fc(
                    input=[cur, mem], size=D.HID, act="tanh",
                    param_attr=[fluid.ParamAttr("bs_fcx"),
                                fluid.ParamAttr("bs_fch")],
                    bias_attr=fluid.ParamAttr("bs_fcb"))
                out = layers.fc(input=dec_h, size=D.DICT, act="softmax",
                                param_attr=fluid.ParamAttr("bs_out"),
                                bias_attr=fluid.ParamAttr("bs_outb"))
                probs = layers.reshape(out, shape=[D.B, D.K, D.DICT])
                topk_scores, topk_idx = layers.topk(probs, k=D.K)
                acc = layers.ops.log(topk_scores) + layers.reshape(
                    pre_scores, shape=[D.B, D.K, 1])
                sel_ids, sel_scores, parent = layers.beam_search(
                    pre_ids, pre_scores, topk_idx, acc, D.K, end_id=0)
                flat_parent = layers.reshape(parent + beam_offset,
                                             shape=[D.B * D.K])
                mem = layers.gather(dec_h, flat_parent)
                it = layers.fill_constant(shape=[1], dtype="int64",
                                          value=t)
                if ids_arr is None:
                    ids_arr = layers.array_write(sel_ids, i=it)
                    par_arr = layers.array_write(parent, i=it)
                else:
                    layers.array_write(sel_ids, i=it, array=ids_arr)
                    layers.array_write(parent, i=it, array=par_arr)
                pre_ids, pre_scores = sel_ids, sel_scores
            sent, sscores = layers.beam_search_decode(
                ids_arr, par_arr, pre_scores, max_len=D.T)
        return prog, startup, sent, sscores

    def _batches(self, n):
        rng = np.random.RandomState(5)
        out = []
        for _ in range(n):
            lod = _rand_lod(rng, self.B, 12)
            src = rng.randint(2, self.DICT,
                              (lod[0][-1], 1)).astype("int64")
            out.append({"src": (src, lod)})
        return out

    def test_streaming_decode_bucket_bounded_and_matches_static(self):
        batches = self._batches(30)
        results = {}
        for bucketed in (False, True):
            prog, startup, sent, sscores = self._build_decode()
            prog.random_seed = startup.random_seed = 3
            prog.lod_buckets = bucketed
            scope = fluid.Scope()
            outs = []
            with fluid.scope_guard(scope):
                exe = fluid.Executor()
                exe.run(startup)
                for b in batches:
                    ids_v, sc_v = exe.run(
                        prog, feed=b, fetch_list=[sent.name,
                                                  sscores.name])
                    outs.append((np.asarray(ids_v), np.asarray(sc_v)))
                n_exec = len(exe._cache)
            results[bucketed] = (outs, n_exec)
        # bounded compiles: 30 distinct LoDs -> O(#buckets) executables
        n_lods = len({tuple(b["src"][1][0]) for b in batches})
        assert n_lods >= 20, n_lods
        assert results[True][1] <= 6, results[True][1]
        for (ids_d, sc_d), (ids_s, sc_s) in zip(results[True][0],
                                                results[False][0]):
            np.testing.assert_array_equal(ids_d, ids_s)
            np.testing.assert_allclose(sc_d, sc_s, rtol=1e-5, atol=1e-6)


class TestBeamTrainingInterpretDisposition:
    """r5 (VERDICT r4 item 7, training half): the legacy beam-TRAINING
    ops (kmax_seq_score -> sub_nested_seq -> cross_entropy_over_beam)
    keep the reference's CPU posture — 2-level nested LoD with
    selection-dependent row counts runs op-by-op on host (the reference
    implements all three ONLY as CPU gserver layers /
    beam_search_op.cc).  A stream of distinct nested LoDs must run
    without any jit-cache growth (no per-LoD recompiles) and produce
    per-batch results matching a direct numpy oracle for the selection."""

    def test_stream_no_compile_growth(self):
        import paddle_tpu.trainer_config_helpers as tch
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data(name="x", shape=[-1, 4], dtype="float32",
                            append_batch_size=False, lod_level=2)
            sel = layers.data(name="sel", shape=[-1, 2], dtype="int64",
                              append_batch_size=False)
            picked = tch.sub_nested_seq_layer(x, sel)
            pooled = layers.sequence_pool(picked, "sum")
        main.expect_host_ops = True
        rng = np.random.RandomState(8)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            fluid.Executor().run(startup)     # startup jits; not under test
            exe = fluid.Executor()
            for step in range(12):
                # fresh nested lod each batch: 2 outer seqs, 2-4 subseqs
                inner = [0]
                outer = [0]
                for _ in range(2):
                    n_sub = rng.randint(2, 5)
                    for _ in range(n_sub):
                        inner.append(inner[-1] + rng.randint(1, 4))
                    outer.append(outer[-1] + n_sub)
                xv = rng.rand(inner[-1], 4).astype("f")
                sel_v = np.array([[rng.randint(0, outer[b + 1] - outer[b]),
                                   -1] for b in range(2)], "int64")
                (o,) = exe.run(main,
                               feed={"x": (xv, [outer, inner]),
                                     "sel": sel_v},
                               fetch_list=[picked.name])
                rows = []
                for b in range(2):
                    s = int(sel_v[b, 0]) + outer[b]
                    rows.extend(range(inner[s], inner[s + 1]))
                np.testing.assert_allclose(np.asarray(o), xv[rows],
                                           rtol=1e-6)
            # interpret mode: per-LoD entries are cheap eager closures,
            # never XLA executables (a jitted fn would expose .lower)
            assert all(not hasattr(cb.fn, "lower")
                       for cb in exe._cache.values()), \
                "beam-training program was jit-compiled per LoD"


class TestRunStepsRaggedWindow:
    """r5: run_steps accepts per-step ragged (value, lod) batches under
    bucketed mode — the whole window pads to ONE bucket signature and
    the training loop runs in a single device dispatch (the streaming
    counterpart of the transformer bench's stacked dense feed; motivated
    by the measured 132 ms wall / 6 ms device gap of per-batch run() on
    the tunneled bench chip)."""

    def test_window_matches_per_batch_runs(self):
        rng = np.random.RandomState(4)
        batches = []
        for _ in range(4):
            lod = _rand_lod(rng, 4, 9)
            batches.append((rng.rand(lod[0][-1], 8).astype("f"), lod))

        def build():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = startup.random_seed = 5
            with fluid.program_guard(main, startup):
                x, out, loss = _build_seq_model("lstm")
                fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
            main.lod_buckets = True
            return main, startup, loss

        # reference: sequential per-batch run()
        main, startup, loss = build()
        scope = fluid.Scope()
        want = []
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            for b in batches:
                (lv,) = exe.run(main, feed={"x": b}, fetch_list=[loss])
                want.append(float(np.asarray(lv).reshape(-1)[0]))

        # one run_steps window
        main2, startup2, loss2 = build()
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe2 = fluid.Executor()
            exe2.run(startup2)
            (stacked,) = exe2.run_steps(main2, feed={"x": batches},
                                        fetch_list=[loss2], steps=4)
        got = [float(v) for v in np.asarray(stacked).reshape(-1)]
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)
