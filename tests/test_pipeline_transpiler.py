"""IR-level pipeline partitioning (parallel/pipeline_transpiler.py):
a REAL transformer Program split into 4 balanced stages, run as a GPipe
pipeline on a 4-device 'pipe' mesh, with loss and parameter-gradient
equality against the unsplit program (VERDICT r3 item 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline_transpiler import pipeline_transpiler

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 virtual devices")

P_STAGES, M_MB, MB, SEQ = 4, 4, 4, 8


def _tiny_hp():
    hp = T.ModelHyperParams()
    hp.d_model, hp.d_inner_hid, hp.n_layer = 32, 64, 2
    hp.n_head, hp.d_key, hp.d_value = 2, 16, 16
    hp.src_vocab_size = hp.trg_vocab_size = 64
    hp.max_length = SEQ * 2
    hp.dropout = 0.0
    return hp


class TestPipelineTranspiler:
    def _build(self):
        hp = _tiny_hp()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            avg_cost, feeds = T.transformer(MB, SEQ, SEQ, hp)
        return hp, main, startup, avg_cost, list(feeds)

    def test_split_is_balanced_and_covering(self):
        _, main, _, avg_cost, feed_names = self._build()
        from paddle_tpu.parallel.pipeline_transpiler import split_program
        block, stage_ops, stage_params, boundaries = split_program(
            main, P_STAGES, feed_names, [avg_cost.name])
        n_ops = sum(len(s) for s in stage_ops)
        assert n_ops == sum(1 for op in block.ops
                            if op.type not in ("feed", "fetch"))
        assert all(len(s) > 0 for s in stage_ops), \
            [len(s) for s in stage_ops]
        # every boundary is a (possibly empty) cut through live values;
        # the first carries only feeds, the last only the fetch targets
        assert set(boundaries[0]) <= set(feed_names)
        assert boundaries[-1] == [avg_cost.name]

    def test_pipelined_loss_and_grads_match_unsplit_program(self):
        hp, main, startup, avg_cost, feed_names = self._build()
        mesh = make_mesh((P_STAGES,), ("pipe",),
                         devices=jax.devices()[:P_STAGES])
        scope = fluid.Scope()
        rng_batches = [T.fake_batch(MB, SEQ, SEQ, hp, seed=97 + i)
                       for i in range(M_MB)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)

            pt = pipeline_transpiler(main, P_STAGES, feed_names,
                                     [avg_cost.name], mesh)
            pt.build(scope, rng_batches[0])
            xs = pt.stack_microbatches(rng_batches)
            run = jax.jit(pt.run_fn())

            outs = run(pt.packed_params, xs)     # {lane: [M, L]}
            pp_losses = [float(np.asarray(v).reshape(()))
                         for v in pt.select_fetch(outs, avg_cost.name)]

            # unsplit reference: one executor run per microbatch
            want_losses = []
            for b in rng_batches:
                (lv,) = exe.run(main, feed=b, fetch_list=[avg_cost.name])
                want_losses.append(float(np.asarray(lv).reshape(())))
        np.testing.assert_allclose(pp_losses, want_losses, rtol=2e-4,
                                   atol=1e-5)

        # gradient equality: d sum_mb(loss_mb) / d params
        def total_loss(packed):
            outs = run(packed, xs)
            return jnp.sum(pt.select_fetch(outs, avg_cost.name))

        g_packed = jax.grad(total_loss)(pt.packed_params)
        got = pt.unpack_grads(g_packed)

        grad_main = main.clone()
        with fluid.program_guard(grad_main):
            cost_var = grad_main.global_block().var(avg_cost.name)
            fluid.append_backward(cost_var)
        param_names = sorted({n for names in pt.stage_param_names
                              for n in names
                              if grad_main.global_block().has_var(
                                  n + "@GRAD")})
        assert param_names, "no trainable params found"
        want = {n: 0.0 for n in param_names}
        with fluid.scope_guard(scope):
            for b in rng_batches:
                gvals = exe.run(grad_main, feed=b,
                                fetch_list=[n + "@GRAD"
                                            for n in param_names])
                for n, g in zip(param_names, gvals):
                    want[n] = want[n] + np.asarray(g, np.float64)
        checked = 0
        for n in param_names:
            np.testing.assert_allclose(
                got[n], want[n], rtol=2e-3, atol=2e-5,
                err_msg=f"grad mismatch for {n}")
            checked += 1
        assert checked >= 10  # the split must cover many params


class TestPipelineHardening:
    """r5: dtype-preserving carriers, AMP-under-pipeline, sub-block
    atomicity (VERDICT r4 item 6)."""

    def test_amp_pipelined_matches_unsplit_amp(self):
        hp = _tiny_hp()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            avg_cost, feeds = T.transformer(MB, SEQ, SEQ, hp)
        main.amp = True          # bf16 compute, f32 masters — both paths
        mesh = make_mesh((P_STAGES,), ("pipe",),
                         devices=jax.devices()[:P_STAGES])
        scope = fluid.Scope()
        batches = [T.fake_batch(MB, SEQ, SEQ, hp, seed=51 + i)
                   for i in range(M_MB)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pt = pipeline_transpiler(main, P_STAGES, list(feeds),
                                     [avg_cost.name], mesh)
            assert pt.amp
            pt.build(scope, batches[0])
            xs = pt.stack_microbatches(batches)
            outs = jax.jit(pt.run_fn())(pt.packed_params, xs)
            got = [float(np.asarray(v).reshape(()))
                   for v in pt.select_fetch(outs, avg_cost.name)]
            want = []
            for b in batches:
                (lv,) = exe.run(main, feed=b, fetch_list=[avg_cost.name])
                want.append(float(np.asarray(lv).reshape(())))
        # boundary cuts round-trip runtime-bf16 values through f32
        # (value-preserving), but downstream elementwise ops then run in
        # f32 where the unsplit program ran bf16 — bf16-level tolerance
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-3)

    def test_integer_feed_rides_i32_lane_exactly(self):
        # ids >= 2^24 are NOT representable in f32; the r4 carrier
        # silently rounded them.  The i32 lane must carry them exactly
        # across a stage boundary.
        big = (1 << 24) + 1
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4, 16],
                                  append_batch_size=False)
            ids = fluid.layers.data(name="ids", shape=[4, 1],
                                    dtype="int32", append_batch_size=False)
            h = fluid.layers.fc(x, size=16)      # stage-0 weight
            h2 = fluid.layers.fc(h, size=16)     # pushes cut after fc #1
            s = fluid.layers.reduce_sum(h2)
            idf = fluid.layers.cast(ids, dtype="float32")  # last stage
            out = s + fluid.layers.reduce_sum(idf)
        mesh = make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        batches = [{"x": rng.rand(4, 16).astype("f"),
                    "ids": np.full((4, 1), big, np.int32)}
                   for _ in range(2)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pt = pipeline_transpiler(main, 2, ["x", "ids"],
                                     [out.name, ids.name], mesh)
            pt.build(scope, batches[0])
            # ids must cross the cut on the integer lane
            assert "i32" in pt.carrier_lanes
            xs = pt.stack_microbatches(batches)
            outs = jax.jit(pt.run_fn())(pt.packed_params, xs)
            ids_back = np.asarray(pt.select_fetch(outs, ids.name))
            assert ids_back.dtype == np.int32
            np.testing.assert_array_equal(ids_back,
                                          np.full((2, 4, 1), big))
            got = [float(np.asarray(v).reshape(()))
                   for v in pt.select_fetch(outs, out.name)]
            want = []
            for b in batches:
                (lv,) = exe.run(main, feed=b, fetch_list=[out.name])
                want.append(float(np.asarray(lv).reshape(())))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_sub_block_op_is_atomic_and_runs(self):
        # a While loop (sub-block op) inside a pipelined program: the op
        # is never split across a cut and its lowering recurses through
        # executor.lower_block inside the stage branch
        L = fluid.layers
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = L.data(name="x", shape=[4, 8], append_batch_size=False)
            h = L.fc(x, size=8)                  # stage-0 weight
            h2 = L.fc(h, size=8)
            i = L.zeros(shape=[1], dtype="int32")
            i.stop_gradient = True
            n = L.fill_constant(shape=[1], dtype="int32", value=3)
            n.stop_gradient = True
            acc = L.zeros(shape=[4, 8], dtype="float32")
            arr = L.array_write(x=acc, i=i)
            cond = L.less_than(x=i, y=n)
            w = L.While(cond=cond)
            with w.block():
                prev = L.array_read(array=arr, i=i)
                nxt = prev + h2                  # consumes the carrier
                i2 = L.increment(x=i, in_place=True)
                L.array_write(nxt, i=i2, array=arr)
                L.less_than(x=i2, y=n, cond=cond)
            final = L.array_read(array=arr, i=n)
            out = L.reduce_sum(final)
        mesh = make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
        scope = fluid.Scope()
        rng = np.random.RandomState(1)
        batches = [{"x": rng.rand(4, 8).astype("f")} for _ in range(2)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pt = pipeline_transpiler(main, 2, ["x"], [out.name], mesh)
            # the while op and its sub-block live in exactly one stage
            n_sub = sum(
                1 for sops in pt.stage_ops for op in sops
                if any(a.__class__.__name__ == "Block"
                       for a in op.attrs.values()))
            assert n_sub == 1
            pt.build(scope, batches[0])
            xs = pt.stack_microbatches(batches)
            outs = jax.jit(pt.run_fn())(pt.packed_params, xs)
            got = [float(np.asarray(v).reshape(()))
                   for v in pt.select_fetch(outs, out.name)]
            want = []
            for b in batches:
                (lv,) = exe.run(main, feed=b, fetch_list=[out.name])
                want.append(float(np.asarray(lv).reshape(())))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


    def test_fetched_feed_rides_every_boundary(self):
        # a feed that is consumed in stage 0 but FETCHED must still ride
        # through every boundary to the final carrier (r5 review fix)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4, 16],
                                  append_batch_size=False)
            ids = fluid.layers.data(name="ids", shape=[4, 1],
                                    dtype="int32", append_batch_size=False)
            idf = fluid.layers.cast(ids, dtype="float32")  # stage 0
            h = fluid.layers.fc(x, size=16)
            h2 = fluid.layers.fc(h + idf, size=16)
            out = fluid.layers.reduce_sum(h2)
        mesh = make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
        scope = fluid.Scope()
        rng = np.random.RandomState(2)
        batches = [{"x": rng.rand(4, 16).astype("f"),
                    "ids": np.arange(4, dtype=np.int32).reshape(4, 1)}
                   for _ in range(2)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pt = pipeline_transpiler(main, 2, ["x", "ids"],
                                     [out.name, ids.name], mesh)
            pt.build(scope, batches[0])
            xs = pt.stack_microbatches(batches)
            outs = jax.jit(pt.run_fn())(pt.packed_params, xs)
            ids_back = np.asarray(pt.select_fetch(outs, ids.name))
        np.testing.assert_array_equal(
            ids_back, np.stack([b["ids"] for b in batches]))

    def test_tensor_array_across_cut_rejected_loudly(self):
        # TensorArray state created before heavy ops and consumed after
        # them cannot ride a flat carrier; the transpiler must reject
        # with an actionable message instead of crashing in pack
        L = fluid.layers
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = L.data(name="x", shape=[4, 8], append_batch_size=False)
            i = L.zeros(shape=[1], dtype="int32")
            i.stop_gradient = True
            arr = L.array_write(x=x, i=i)       # array BEFORE the cut
            h = L.fc(x, size=8)
            h2 = L.fc(h, size=8)                # cut lands here
            back = L.array_read(array=arr, i=i)  # array AFTER the cut
            out = L.reduce_sum(h2) + L.reduce_sum(back)
        mesh = make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="tensor_array"):
            pipeline_transpiler(main, 2, ["x"], [out.name], mesh)

    def test_dp_pp_grads_match_unsplit(self):
        """dp x pp composition is differentiable: summed per-microbatch
        param grads through run_fn(data_axis=...) on a 2x4 mesh equal
        the unsplit program's (shard_map's transpose psums the
        replicated packed params over the data axis correctly)."""
        hp = _tiny_hp()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            avg_cost, feeds = T.transformer(MB, SEQ, SEQ, hp)
        dp_rows = 2
        mesh = make_mesh((dp_rows, P_STAGES), ("data", "pipe"),
                         devices=jax.devices()[:dp_rows * P_STAGES])
        scope = fluid.Scope()
        batches = [T.fake_batch(MB, SEQ, SEQ, hp, seed=11 + i)
                   for i in range(dp_rows * P_STAGES)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            pt = pipeline_transpiler(main, P_STAGES, list(feeds),
                                     [avg_cost.name], mesh)
            pt.build(scope, batches[0])
            xs = pt.stack_microbatches(batches)
            run = jax.jit(pt.run_fn(data_axis="data"))

            def total_loss(packed):
                return jnp.sum(pt.select_fetch(run(packed, xs),
                                               avg_cost.name))

            got = pt.unpack_grads(jax.grad(total_loss)(pt.packed_params))

            grad_main = main.clone()
            with fluid.program_guard(grad_main):
                fluid.append_backward(
                    grad_main.global_block().var(avg_cost.name))
            names = sorted({n for ns in pt.stage_param_names for n in ns
                            if grad_main.global_block().has_var(
                                n + "@GRAD")})
            want = {n: 0.0 for n in names}
            for b in batches:
                gv = exe.run(grad_main, feed=b,
                             fetch_list=[n + "@GRAD" for n in names])
                for n, g in zip(names, gv):
                    want[n] = want[n] + np.asarray(g, np.float64)
        assert len(names) >= 10
        for n in names:
            np.testing.assert_allclose(got[n], want[n], rtol=2e-3,
                                       atol=2e-5,
                                       err_msg=f"grad mismatch {n}")


class TestI32LaneRangeGuard:
    """The i32 carrier lane's int64 range guard is keyed on the VALUE'S
    DTYPE, not ``isinstance(np.ndarray)`` (ADVICE r5): numpy scalars
    and x64-enabled jax arrays are int64-typed without being ndarrays
    and must not wrap silently.  The static half of the same contract
    is analysis.check_pipeline_carriers (tests/test_analysis.py)."""

    def _layout(self):
        from paddle_tpu.parallel.pipeline_transpiler import _Layout
        return _Layout(["ids"], [(1,)], [np.int64])

    def test_ndarray_out_of_range_rejected(self):
        lay = self._layout()
        with pytest.raises(ValueError, match="int32 range"):
            lay.pack({"ids": np.array([2 ** 31], np.int64)}, ["i32"])

    def test_numpy_scalar_out_of_range_rejected(self):
        # np.int64(...) is NOT an ndarray — the old isinstance guard
        # let it through to wrap silently
        lay = self._layout()
        with pytest.raises(ValueError, match="int32 range"):
            lay.pack({"ids": np.int64(2 ** 31)}, ["i32"])

    def test_python_list_of_big_ints_is_not_exempt(self):
        # no dtype attr -> conversion happens in pack_microbatch's
        # np.asarray; packing the converted array still trips the guard
        lay = self._layout()
        with pytest.raises(ValueError, match="int32 range"):
            lay.pack({"ids": np.asarray([-(2 ** 40)])}, ["i32"])

    def test_in_range_int64_packs_exactly(self):
        lay = self._layout()
        vecs = lay.pack({"ids": np.array([2 ** 31 - 1], np.int64)},
                        ["i32"])
        assert int(vecs["i32"][0]) == 2 ** 31 - 1

    def test_traced_values_are_exempt(self):
        # tracers cannot be concretized; under x64-off they are never
        # int64 anyway — the guard must not break jit'd stage packing
        lay = self._layout()

        def f(v):
            return lay.pack({"ids": v}, ["i32"])["i32"]

        out = jax.jit(f)(jnp.array([5], jnp.int32))
        assert int(out[0]) == 5
