"""IR-level pipeline partitioning (parallel/pipeline_transpiler.py):
a REAL transformer Program split into 4 balanced stages, run as a GPipe
pipeline on a 4-device 'pipe' mesh, with loss and parameter-gradient
equality against the unsplit program (VERDICT r3 item 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline_transpiler import pipeline_transpiler

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 virtual devices")

P_STAGES, M_MB, MB, SEQ = 4, 4, 4, 8


def _tiny_hp():
    hp = T.ModelHyperParams()
    hp.d_model, hp.d_inner_hid, hp.n_layer = 32, 64, 2
    hp.n_head, hp.d_key, hp.d_value = 2, 16, 16
    hp.src_vocab_size = hp.trg_vocab_size = 64
    hp.max_length = SEQ * 2
    hp.dropout = 0.0
    return hp


class TestPipelineTranspiler:
    def _build(self):
        hp = _tiny_hp()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            avg_cost, feeds = T.transformer(MB, SEQ, SEQ, hp)
        return hp, main, startup, avg_cost, list(feeds)

    def test_split_is_balanced_and_covering(self):
        _, main, _, avg_cost, feed_names = self._build()
        from paddle_tpu.parallel.pipeline_transpiler import split_program
        block, stage_ops, stage_params, boundaries = split_program(
            main, P_STAGES, feed_names, [avg_cost.name])
        n_ops = sum(len(s) for s in stage_ops)
        assert n_ops == sum(1 for op in block.ops
                            if op.type not in ("feed", "fetch"))
        assert all(len(s) > 0 for s in stage_ops), \
            [len(s) for s in stage_ops]
        # every boundary is a (possibly empty) cut through live values;
        # the first carries only feeds, the last only the fetch targets
        assert set(boundaries[0]) <= set(feed_names)
        assert boundaries[-1] == [avg_cost.name]

    def test_pipelined_loss_and_grads_match_unsplit_program(self):
        hp, main, startup, avg_cost, feed_names = self._build()
        mesh = make_mesh((P_STAGES,), ("pipe",),
                         devices=jax.devices()[:P_STAGES])
        scope = fluid.Scope()
        rng_batches = [T.fake_batch(MB, SEQ, SEQ, hp, seed=97 + i)
                       for i in range(M_MB)]
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)

            pt = pipeline_transpiler(main, P_STAGES, feed_names,
                                     [avg_cost.name], mesh)
            pt.build(scope, rng_batches[0])
            xs = jnp.stack([pt.pack_microbatch(b) for b in rng_batches])
            run = jax.jit(pt.run_fn())

            outs = run(pt.packed_params, xs)     # [M, L]
            pp_losses = [float(pt.unpack_outputs(outs[i])[avg_cost.name]
                               .reshape(()))
                         for i in range(M_MB)]

            # unsplit reference: one executor run per microbatch
            want_losses = []
            for b in rng_batches:
                (lv,) = exe.run(main, feed=b, fetch_list=[avg_cost.name])
                want_losses.append(float(np.asarray(lv).reshape(())))
        np.testing.assert_allclose(pp_losses, want_losses, rtol=2e-4,
                                   atol=1e-5)

        # gradient equality: d sum_mb(loss_mb) / d params
        slot_lay = pt._carrier_layouts[-1]
        off = slot_lay.offsets[slot_lay.names.index(avg_cost.name)]

        def total_loss(packed):
            outs = run(packed, xs)
            return jnp.sum(outs[:, off])

        g_packed = jax.grad(total_loss)(pt.packed_params)
        got = pt.unpack_grads(g_packed)

        grad_main = main.clone()
        with fluid.program_guard(grad_main):
            cost_var = grad_main.global_block().var(avg_cost.name)
            fluid.append_backward(cost_var)
        param_names = sorted({n for names in pt.stage_param_names
                              for n in names
                              if grad_main.global_block().has_var(
                                  n + "@GRAD")})
        assert param_names, "no trainable params found"
        want = {n: 0.0 for n in param_names}
        with fluid.scope_guard(scope):
            for b in rng_batches:
                gvals = exe.run(grad_main, feed=b,
                                fetch_list=[n + "@GRAD"
                                            for n in param_names])
                for n, g in zip(param_names, gvals):
                    want[n] = want[n] + np.asarray(g, np.float64)
        checked = 0
        for n in param_names:
            np.testing.assert_allclose(
                got[n], want[n], rtol=2e-3, atol=2e-5,
                err_msg=f"grad mismatch for {n}")
            checked += 1
        assert checked >= 10  # the split must cover many params
