"""Autodiff engine tests (reference ``tests/unittests/test_backward.py``
plus regression coverage for multi-consumer gradient accumulation —
the reference's ``_addup_repetitive_outputs_:117`` semantics)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import grad_var_name


def test_multi_consumer_grads_are_summed():
    """y feeds two consumers: dL/dy must be the SUM of both paths."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        y = fluid.layers.fc(input=x, size=3, bias_attr=False)
        a = fluid.layers.scale(y, scale=2.0)
        b = fluid.layers.scale(y, scale=3.0)
        loss = fluid.layers.mean(fluid.layers.elementwise_add(a, b))
        fluid.append_backward(loss)

    w = main.global_block().all_parameters()[0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = np.ones((2, 3), np.float32)
    g, = exe.run(main, feed={"x": xs},
                 fetch_list=[grad_var_name(w.name)])
    # dL/dW = x^T @ (5/(2*3)) ones — key property: factor 5 = 2+3
    expected = np.full((3, 3), 5.0 * 2 / 6.0, np.float32)
    np.testing.assert_allclose(np.asarray(g), expected, rtol=1e-5)


def test_shared_weight_grads_are_summed():
    """The same parameter used by two mul ops accumulates both grads."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        w = fluid.layers.create_parameter([4, 4], "float32", name="shared_w")
        h1 = fluid.layers.mul(x, w)
        h2 = fluid.layers.mul(h1, w)  # shared weight
        loss = fluid.layers.mean(h2)
        fluid.append_backward(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.uniform(-1, 1, (2, 4)).astype("float32")
    w_val, g = exe.run(main, feed={"x": xs},
                       fetch_list=["shared_w", grad_var_name("shared_w")])
    # numeric check
    w0 = np.asarray(w_val, np.float64)
    eps = 1e-4

    def loss_at(wm):
        return ((xs @ wm) @ wm).mean()

    num = np.zeros_like(w0)
    for i in range(4):
        for j in range(4):
            wp, wm_ = w0.copy(), w0.copy()
            wp[i, j] += eps
            wm_[i, j] -= eps
            num[i, j] = (loss_at(wp) - loss_at(wm_)) / (2 * eps)
    np.testing.assert_allclose(np.asarray(g, np.float64), num, atol=1e-3)


def test_stop_gradient_prunes_branch():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        frozen = fluid.layers.fc(input=x, size=3)
        frozen.stop_gradient = True
        live = fluid.layers.fc(input=x, size=3)
        loss = fluid.layers.mean(fluid.layers.elementwise_add(frozen, live))
        pg = fluid.append_backward(loss)
    # only the live fc's params should receive grads
    got = {p.name for p, g in pg}
    frozen_params = {op.input("Y")[0] for op in main.global_block().ops
                     if op.type == "mul" and
                     op.output("Out")[0] in
                     [frozen.op.input("X")[0] if frozen.op else ""]}
    assert len(got) >= 1
    for p, g in pg:
        assert g is not None


def test_calc_gradient_with_seed():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        x.stop_gradient = False
        y = fluid.layers.mean(fluid.layers.scale(x, scale=2.0))
        seed = fluid.layers.fill_constant([1], "float32", 4.0)
        grads = fluid.calc_gradient(y, x, target_gradients=[seed])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    g, = exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                 fetch_list=grads)
    np.testing.assert_allclose(np.asarray(g),
                               np.full((2, 3), 4.0 * 2.0 / 6.0), rtol=1e-5)


def test_clone_preserves_parameters():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        fluid.layers.fc(input=x, size=2)
    cloned = main.clone()
    assert len(cloned.global_block().all_parameters()) == \
        len(main.global_block().all_parameters()) > 0


def test_error_clip_applied():
    from paddle_tpu.clip import ErrorClipByValue
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        h = fluid.layers.fc(input=x, size=3)
        h.error_clip = ErrorClipByValue(max=0.001)
        loss = fluid.layers.mean(fluid.layers.scale(h, scale=100.0))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    clip_ops = [op for op in main.global_block().ops if op.type == "clip"]
    assert clip_ops, "error clip should append clip ops on the grad"
