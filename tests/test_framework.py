"""IR core tests (mirrors reference ``framework/ddim_test.cc``,
``scope_test.cc``, ``test_program.py``, ``test_operator_desc.py``)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import Program, Variable, Operator
from paddle_tpu.scope import Scope


class TestProgram:
    def test_block_structure(self):
        p = Program()
        assert p.num_blocks == 1
        b1 = p.create_block()
        assert b1.parent_idx == 0
        assert p.current_block() is b1
        p.rollback()
        assert p.current_block() is p.global_block()

    def test_append_op_and_vars(self):
        p = Program()
        b = p.global_block()
        x = b.create_var(name="x", shape=[2, 3], dtype="float32")
        y = b.create_var(name="y", shape=[2, 3], dtype="float32")
        op = b.append_op(type="elementwise_add",
                         inputs={"X": [x], "Y": [y]},
                         outputs={"Out": ["z"]})
        assert op.input("X") == ["x"]
        assert "z" in b.vars  # auto-declared
        assert b.var("z").shape == (2, 3)  # shape inferred

    def test_serialization_roundtrip(self):
        p = Program()
        b = p.global_block()
        b.create_var(name="x", shape=[4], dtype="float32", persistable=True)
        b.append_op(type="scale", inputs={"X": ["x"]},
                    outputs={"Out": ["y"]}, attrs={"scale": 2.0})
        d = p.to_dict()
        p2 = Program.from_dict(d)
        assert p2.global_block().var("x").persistable
        assert p2.global_block().ops[0].type == "scale"
        assert p2.global_block().ops[0].attr("scale") == 2.0

    def test_clone_for_test_flips_is_test(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="img", shape=[8], dtype="float32")
            d = fluid.layers.dropout(x, dropout_prob=0.5)
        t = main.clone(for_test=True)
        dropout_ops = [op for b in t.blocks for op in b.ops
                       if op.type == "dropout"]
        assert dropout_ops and all(op.attr("is_test") for op in dropout_ops)

    def test_prune(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            h = fluid.layers.fc(input=x, size=8)
            out1 = fluid.layers.fc(input=h, size=2)
            out2 = fluid.layers.fc(input=h, size=3)  # should be pruned away
        pruned = main.prune([out1])
        kept_outputs = {n for op in pruned.global_block().ops
                        for n in op.output_arg_names}
        assert out1.name in kept_outputs
        assert out2.name not in kept_outputs


class TestScope:
    def test_hierarchy(self):
        s = Scope()
        s.set_var("a", np.ones(3))
        kid = s.new_scope()
        assert kid.find_var("a") is not None
        kid.set_var("b", np.zeros(2))
        assert s.find_var("b") is None

    def test_var_create(self):
        s = Scope()
        assert s.var("x") is None  # created empty
        assert s.has_var("x")


class TestVariable:
    def test_dtype_normalization(self):
        p = Program()
        v = p.global_block().create_var(name="v", shape=[1], dtype="fp32")
        assert v.dtype == "float32"
        v2 = p.global_block().create_var(name="v2", shape=[1],
                                         dtype=np.float64)
        assert v2.dtype == "float64"

    def test_operator_overloading_builds_ops(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = x * 2.0 + 1.0
        types = [op.type for op in main.global_block().ops]
        assert "elementwise_mul" in types
        assert "elementwise_add" in types
