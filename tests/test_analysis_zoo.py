"""Model-zoo lint gate: the analyzer reports ZERO diagnostics across
every ``paddle_tpu/models/*`` forward+backward program (main AND
startup).  Zero false positives is part of the analyzer's contract —
a check that cries wolf on known-good programs gets turned off, and
then the next transpiler bug ships.  A new model joins the gate by
joining ``models.ZOO_MODELS`` / ``build_train_program``."""

import pytest

from paddle_tpu import analysis
from paddle_tpu.models import ZOO_MODELS, build_train_program


@pytest.mark.parametrize("name", ZOO_MODELS)
def test_zoo_model_lints_clean(name):
    main, startup, feeds, fetches = build_train_program(name)
    result = analysis.lint_program(main, feed_names=feeds,
                                   fetch_names=fetches)
    assert not result.diagnostics, (
        f"{name} forward+backward program is not lint-clean "
        f"(analyzer false positive, or a real model bug):\n"
        f"{result.format()}")
    startup_result = analysis.lint_program(startup)
    assert not startup_result.diagnostics, (
        f"{name} startup program is not lint-clean:\n"
        f"{startup_result.format()}")


@pytest.mark.parametrize("name", ZOO_MODELS)
def test_zoo_model_forward_only_lints_clean(name):
    main, _, feeds, fetches = build_train_program(name, backward=False)
    result = analysis.lint_program(main, feed_names=feeds,
                                   fetch_names=fetches)
    assert not result.diagnostics, f"{name} forward:\n{result.format()}"


def test_zoo_gate_covers_every_model_module():
    """A model module added to paddle_tpu/models without joining the
    gate would silently escape linting."""
    import os

    import paddle_tpu.models as models
    mod_dir = os.path.dirname(os.path.abspath(models.__file__))
    modules = {n[:-3] for n in os.listdir(mod_dir)
               if n.endswith(".py") and n != "__init__.py"}
    assert modules == set(ZOO_MODELS), (
        f"models modules {sorted(modules)} != lint-gated zoo "
        f"{sorted(ZOO_MODELS)} — add the new model to ZOO_MODELS / "
        f"build_train_program")


def test_zoo_cli_lint_exits_clean():
    """`paddle_tpu lint --zoo all` — the command CI and humans run —
    agrees with the API-level gate."""
    from paddle_tpu.cli import main
    assert main(["lint", "--zoo", "all"]) == 0


def test_gen_bundle_lints_clean(tmp_path, capsys):
    """A freshly exported generation bundle joins the zoo gate:
    `paddle_tpu lint <bundle>` lints prefill AND decode (plus the
    cross-program signature checks) as one unit, clean."""
    from paddle_tpu.cli import main
    from paddle_tpu.models import gen_lm
    hp = gen_lm.GenConfig()
    hp.vocab_size, hp.d_model, hp.d_ffn = 32, 16, 32
    hp.n_head = hp.n_layer = 2
    hp.d_head, hp.max_len = 8, 16
    bundle = str(tmp_path / "bundle")
    gen_lm.export_gen_model(bundle, hp, num_slots=2)
    assert main(["lint", bundle]) == 0
    out = capsys.readouterr().out
    assert "3 program(s)" in out and "0 error(s)" in out
    results = analysis.lint_gen_bundle(bundle)
    assert [label for label, _ in results] == ["prefill", "decode",
                                               "bundle"]
    for label, r in results:
        assert not r.diagnostics, f"{label}:\n{r.format()}"


# ---------------------------------------------------------------------------
# typecheck coverage ratchet: the zoo-wide warn-list may shrink, never
# grow — a new model (or a rule regression) that adds uncovered op
# types must either get rules or consciously raise the ceiling here
# ---------------------------------------------------------------------------

ZOO_UNCOVERED_CEILING = 2  # exactly {while, while_grad} — ISSUE-15
# shrank 13 -> 2 by covering the LoD/array plumbing + lstm families
# (shape inference is the prerequisite for the cost model's bytes
# accounting); the two loop carriers propagate through their BODY ops'
# rules instead

#: op families frequent enough that losing their rules would blind the
#: type checker across most of the zoo (the satellite's shrink target)
MUST_BE_COVERED = {
    "mul_grad", "matmul_grad", "elementwise_add_grad", "mean_grad",
    "softmax_grad", "cross_entropy_grad", "relu_grad", "tanh_grad",
    "conv2d_grad", "pool2d_grad", "layer_norm_grad",
    "lookup_table_grad", "reshape_grad", "transpose_grad",
    "dropout_grad", "concat_grad", "reduce_sum_grad",
    "softmax_with_cross_entropy_grad", "lstm_grad",
    "sequence_pool_grad", "increment", "less_than", "sequence_pool",
    "sequence_expand", "assign_value", "max_sequence_len",
    # ISSUE-15: the families the cost model needs (bytes costing rides
    # their shape propagation) — they may never fall off again
    "lstm", "write_to_array", "read_from_array", "array_to_lod_tensor",
    "lod_tensor_to_array", "reorder_lod_tensor_by_rank",
    "lod_rank_table", "write_to_array_grad", "array_to_lod_tensor_grad",
    "lod_tensor_to_array_grad", "reorder_lod_tensor_by_rank_grad",
    # ISSUE-18: the sparse/CTR family behind the sharded-embedding
    # workload — lookup_table_grad's SelectedRows cotangent plus the
    # row-set transform ops must stay typed so the sparse optimizer
    # path and its cost pricing never go blind
    "merge_selected_rows", "get_tensor_from_selected_rows",
    "split_ids", "split_selected_rows", "nce", "nce_grad",
}


def test_zoo_uncovered_op_ratchet():
    uncovered = set()
    for name in ZOO_MODELS:
        main, _startup, feeds, fetches = build_train_program(name)
        r = analysis.lint_program(main, feed_names=feeds,
                                  fetch_names=fetches)
        uncovered.update(r.uncovered_op_types)
    blind = sorted(uncovered & MUST_BE_COVERED)
    assert not blind, (
        f"op types the type checker must keep rules for are back on "
        f"the warn-list: {blind}")
    assert len(uncovered) <= ZOO_UNCOVERED_CEILING, (
        f"zoo-wide uncovered op types grew to {len(uncovered)} "
        f"(ceiling {ZOO_UNCOVERED_CEILING}): {sorted(uncovered)} — "
        f"add @typecheck.rule coverage for the new ops instead of "
        f"raising the ceiling")


def test_selfcheck_cli_passes():
    """`paddle_tpu selfcheck` — strict zoo lint (single- and multi-
    program) plus every scanner-enforced registry in one exit-coded
    pass; drift in any section fails tier-1 here."""
    from paddle_tpu.cli import main
    assert main(["selfcheck"]) == 0
