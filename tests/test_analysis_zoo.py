"""Model-zoo lint gate: the analyzer reports ZERO diagnostics across
every ``paddle_tpu/models/*`` forward+backward program (main AND
startup).  Zero false positives is part of the analyzer's contract —
a check that cries wolf on known-good programs gets turned off, and
then the next transpiler bug ships.  A new model joins the gate by
joining ``models.ZOO_MODELS`` / ``build_train_program``."""

import pytest

from paddle_tpu import analysis
from paddle_tpu.models import ZOO_MODELS, build_train_program


@pytest.mark.parametrize("name", ZOO_MODELS)
def test_zoo_model_lints_clean(name):
    main, startup, feeds, fetches = build_train_program(name)
    result = analysis.lint_program(main, feed_names=feeds,
                                   fetch_names=fetches)
    assert not result.diagnostics, (
        f"{name} forward+backward program is not lint-clean "
        f"(analyzer false positive, or a real model bug):\n"
        f"{result.format()}")
    startup_result = analysis.lint_program(startup)
    assert not startup_result.diagnostics, (
        f"{name} startup program is not lint-clean:\n"
        f"{startup_result.format()}")


@pytest.mark.parametrize("name", ZOO_MODELS)
def test_zoo_model_forward_only_lints_clean(name):
    main, _, feeds, fetches = build_train_program(name, backward=False)
    result = analysis.lint_program(main, feed_names=feeds,
                                   fetch_names=fetches)
    assert not result.diagnostics, f"{name} forward:\n{result.format()}"


def test_zoo_gate_covers_every_model_module():
    """A model module added to paddle_tpu/models without joining the
    gate would silently escape linting."""
    import os

    import paddle_tpu.models as models
    mod_dir = os.path.dirname(os.path.abspath(models.__file__))
    modules = {n[:-3] for n in os.listdir(mod_dir)
               if n.endswith(".py") and n != "__init__.py"}
    assert modules == set(ZOO_MODELS), (
        f"models modules {sorted(modules)} != lint-gated zoo "
        f"{sorted(ZOO_MODELS)} — add the new model to ZOO_MODELS / "
        f"build_train_program")


def test_zoo_cli_lint_exits_clean():
    """`paddle_tpu lint --zoo all` — the command CI and humans run —
    agrees with the API-level gate."""
    from paddle_tpu.cli import main
    assert main(["lint", "--zoo", "all"]) == 0
