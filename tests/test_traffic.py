"""Traffic-replay harness units (fleet.traffic): rate patterns, the
seeded heavy-tail prompt mix, open-loop dispatch with an inflight cap,
outcome classification (ok / shed / deadline / error / dropped), and
the summary arithmetic the autoscale bench gates on.  All in-process —
``send`` is a plain function, no HTTP."""

import threading
import time

import pytest

from paddle_tpu.fleet import TrafficReplay
from paddle_tpu.fleet.traffic import (diurnal, flash_crowd,
                                      heavy_tail_lengths, step)
from paddle_tpu.profiler import RuntimeMetrics


class TestPatterns:
    def test_step(self):
        r = step(2.0, 10.0, at=5.0)
        assert r(0.0) == 2.0
        assert r(4.99) == 2.0
        assert r(5.0) == 10.0
        assert r(100.0) == 10.0

    def test_step_with_duration_reverts(self):
        r = step(2.0, 10.0, at=5.0, duration=3.0)
        assert r(6.0) == 10.0
        assert r(8.0) == 2.0

    def test_diurnal_trough_and_peak(self):
        r = diurnal(1.0, 9.0, period=60.0)
        assert r(0.0) == pytest.approx(1.0)
        assert r(30.0) == pytest.approx(9.0)
        assert r(60.0) == pytest.approx(1.0)
        assert 1.0 < r(10.0) < 9.0

    def test_flash_crowd_rise_and_decay(self):
        r = flash_crowd(1.0, 21.0, at=2.0, rise=0.5, fall=1.0)
        assert r(1.0) == 1.0
        assert r(2.25) == pytest.approx(11.0)   # mid-rise
        peak = r(2.5)
        assert peak == pytest.approx(21.0)
        assert 1.0 < r(4.0) < peak              # decaying
        assert r(30.0) == pytest.approx(1.0, abs=0.01)

    def test_heavy_tail_lengths(self):
        a = heavy_tail_lengths(500, seed=3, median=32, cap=512)
        b = heavy_tail_lengths(500, seed=3, median=32, cap=512)
        assert a == b                           # seeded
        assert a != heavy_tail_lengths(500, seed=4, median=32, cap=512)
        assert all(1 <= n <= 512 for n in a)
        s = sorted(a)
        med = s[len(s) // 2]
        assert 16 <= med <= 64                  # near the target median
        assert s[-1] > 4 * med                  # the heavy tail exists


def _replay(send, pattern, duration, **kw):
    m = kw.pop("metrics", RuntimeMetrics())
    replay = TrafficReplay(send, pattern, duration, metrics=m, **kw)
    return replay.run(), m


class TestReplay:
    def test_classification_and_hint_split(self):
        # deterministic outcome script keyed by arrival index
        script = [
            {"status": 200},
            {"status": 429, "retry_after": "0.5"},
            {"status": 429, "retry_after": None},
            {"status": 503, "retry_after": "1.0"},
            {"status": 504},
            {"status": 500},
            "raise",
        ]

        def send(i):
            entry = script[i % len(script)]
            if entry == "raise":
                raise ConnectionError("boom")
            return entry

        summary, m = _replay(send, lambda t: 200.0, 0.5, seed=1)
        n = summary["attempted"]
        assert n > 20
        out = summary["outcomes"]
        assert out["ok"] == m.counter("traffic.ok") > 0
        assert out["shed"] == m.counter("traffic.shed") > 0
        assert out["deadline"] == m.counter("traffic.deadline_exceeded") > 0
        assert out["error"] == m.counter("traffic.errors") > 0
        assert summary["shed_with_hint"] + summary["shed_without_hint"] \
            == out["shed"]
        assert summary["shed_without_hint"] > 0   # the None-hint 429s
        assert summary["lost_accepted"] == out["error"] + out["deadline"]
        assert m.counter("traffic.sent") == n   # every arrival metered

    def test_same_seed_same_schedule(self):
        def send(i):
            return {"status": 200}

        s1, _ = _replay(send, step(50.0, 200.0, at=0.25), 0.5, seed=9)
        s2, _ = _replay(send, step(50.0, 200.0, at=0.25), 0.5, seed=9)
        s3, _ = _replay(send, step(50.0, 200.0, at=0.25), 0.5, seed=10)
        assert s1["attempted"] == s2["attempted"]
        assert s1["attempted"] != s3["attempted"]

    def test_inflight_cap_counts_dropped(self):
        release = threading.Event()

        def send(i):
            release.wait(timeout=10.0)
            return {"status": 200}

        m = RuntimeMetrics()
        replay = TrafficReplay(send, lambda t: 100.0, 0.3, seed=2,
                               max_inflight=2, metrics=m)
        done = {}

        def run():
            done["summary"] = replay.run()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.4)
        release.set()
        t.join(timeout=10.0)
        summary = done["summary"]
        assert summary["outcomes"]["dropped"] > 0
        assert summary["outcomes"]["dropped"] == m.counter("traffic.dropped")
        assert summary["outcomes"]["ok"] <= 2 + summary["attempted"]
        # dropped arrivals were still offered load
        assert m.counter("traffic.sent") == summary["attempted"]

    def test_zero_rate_stretch_sends_nothing(self):
        sent = []

        def send(i):
            sent.append(i)
            return {"status": 200}

        summary, m = _replay(send, lambda t: 0.0, 0.3, seed=0)
        assert summary["attempted"] == 0
        assert sent == []
        assert m.counter("traffic.sent") == 0

    def test_latency_percentiles_over_ok_only(self):
        def send(i):
            time.sleep(0.01)
            return {"status": 200}

        summary, _ = _replay(send, lambda t: 50.0, 0.4, seed=5)
        assert summary["latency_ms"]["p50"] >= 10.0
        assert summary["latency_ms"]["p99"] >= summary["latency_ms"]["p50"]
