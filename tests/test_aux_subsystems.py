"""Aux-subsystem hardening tests (SURVEY.md §5 / VERDICT item 9):
check_nan_inf executor mode, chunk_eval + evaluator.py, graphviz dump,
profiler op table, ModelAverage, 2-process jax.distributed smoke."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers


class TestCheckNanInf:
    def test_raises_with_var_name(self):
        x = layers.data(name="x", shape=[2, 2], append_batch_size=False)
        y = layers.log(x)  # log of negative -> nan
        prog = fluid.default_main_program()
        prog.check_nan_inf = True
        exe = fluid.Executor()
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            exe.run(prog, feed={"x": np.full((2, 2), -1.0, "float32")},
                    fetch_list=[y])
        # healthy values pass
        out = exe.run(prog, feed={"x": np.ones((2, 2), "float32")},
                      fetch_list=[y])
        assert np.isfinite(np.asarray(out[0])).all()


class TestChunkEval:
    def test_iob_f1(self):
        # 2 chunk types, IOB: labels 0=B-0 1=I-0 2=B-1 3=I-1 4=O
        label = np.array([[0], [1], [4], [2], [3], [4]], np.int64)
        inference = np.array([[0], [1], [4], [2], [4], [4]], np.int64)
        lod = [[0, 6]]
        inf = layers.data(name="inf", shape=[6, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        lab = layers.data(name="lab", shape=[6, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        metrics = layers.chunk_eval(input=inf, label=lab,
                                    chunk_scheme="IOB", num_chunk_types=2)
        exe = fluid.Executor()
        prec, rec, f1, ni, nl, nc = exe.run(
            fluid.default_main_program(),
            feed={"inf": (inference, lod), "lab": (label, lod)},
            fetch_list=list(metrics))
        # label chunks: [0-1]:0, [3-4]:1 ; infer chunks: [0-1]:0, [3-3]:1
        # correct: [0-1]:0 only
        assert int(ni[0]) == 2 and int(nl[0]) == 2 and int(nc[0]) == 1
        np.testing.assert_allclose(prec, [0.5])
        np.testing.assert_allclose(rec, [0.5])
        np.testing.assert_allclose(f1, [0.5])


class TestChunkEvaluator:
    def test_streaming(self):
        inf = layers.data(name="inf", shape=[6, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        lab = layers.data(name="lab", shape=[6, 1], dtype="int64",
                          append_batch_size=False, lod_level=1)
        ev = fluid.evaluator.ChunkEvaluator(input=inf, label=lab,
                                            chunk_scheme="IOB",
                                            num_chunk_types=2)
        exe = fluid.Executor()
        ev.reset(exe)
        lod = [[0, 6]]
        label = np.array([[0], [1], [4], [2], [3], [4]], np.int64)
        inference = np.array([[0], [1], [4], [2], [4], [4]], np.int64)
        for _ in range(3):  # 3 identical batches accumulate
            exe.run(fluid.default_main_program(),
                    feed={"inf": (inference, lod), "lab": (label, lod)},
                    fetch_list=ev.metrics)
        prec, rec, f1 = ev.eval(exe)
        np.testing.assert_allclose(prec, [0.5])
        np.testing.assert_allclose(f1, [0.5])
        ev.reset(exe)
        prec, rec, f1 = ev.eval(exe)
        np.testing.assert_allclose(f1, [0.0])


class TestGraphviz:
    def test_dot_dump(self, tmp_path):
        x = layers.data(name="x", shape=[4, 8], append_batch_size=False)
        h = layers.fc(input=x, size=4, act="relu")
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        from paddle_tpu import debuger
        p = str(tmp_path / "g.dot")
        dot = debuger.draw_block_graphviz(
            fluid.default_main_program().global_block(), path=p)
        assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
        assert "mul" in dot and "@GRAD" in dot and os.path.exists(p)
        code = debuger.pprint_program_codes(fluid.default_main_program())
        assert "mul(" in code and "sgd(" in code


class TestOpProfiler:
    def test_sorted_table(self):
        x = layers.data(name="x", shape=[8, 16], append_batch_size=False)
        h = layers.fc(input=x, size=16, act="relu")
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        fluid.profiler.enable_op_profiling()
        try:
            exe.run(fluid.default_main_program(),
                    feed={"x": np.ones((8, 16), "float32")},
                    fetch_list=[loss])
        finally:
            fluid.profiler.disable_op_profiling()
        table = fluid.profiler.op_profile_table(sorted_key="total")
        assert "Event" in table and "mul" in table and "sgd" in table
        # sorted by total descending
        rows = table.splitlines()[1:]
        totals = [float(r.split()[2]) for r in rows]
        assert totals == sorted(totals, reverse=True)
        fluid.profiler.reset_profiler()
        assert "mul" not in fluid.profiler.op_profile_table()


class TestModelAverage:
    def test_apply_restores(self):
        x = layers.data(name="x", shape=[4, 4], append_batch_size=False)
        h = layers.fc(input=x, size=1, param_attr="ma_w", bias_attr=False)
        loss = layers.reduce_mean(h)
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        model_average = fluid.optimizer.ModelAverage(0.15)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        scope = fluid.global_scope()
        vals = []
        for _ in range(4):
            exe.run(fluid.default_main_program(),
                    feed={"x": np.ones((4, 4), "float32")},
                    fetch_list=[loss])
            vals.append(np.asarray(scope.find_var("ma_w")).copy())
        final = np.asarray(scope.find_var("ma_w")).copy()
        with model_average.apply(exe):
            averaged = np.asarray(scope.find_var("ma_w")).copy()
        restored = np.asarray(scope.find_var("ma_w"))
        np.testing.assert_allclose(restored, final)
        np.testing.assert_allclose(averaged, np.mean(vals, axis=0),
                                   rtol=1e-5)


@pytest.mark.timeout(120)
class TestTwoProcessDistributed:
    def test_two_process_allgather(self, tmp_path):
        """2-process jax.distributed cluster on one host (reference spawns
        pserver processes on localhost, test_recv_op.py); validates
        init_parallel_env + cross-process collectives over Gloo."""
        script = textwrap.dedent("""
            import os, sys
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ.pop("XLA_FLAGS", None)
            import jax
            jax.config.update("jax_platforms", "cpu")
            pid = int(sys.argv[1])
            from paddle_tpu.parallel.distributed import (
                init_parallel_env, get_rank, get_world_size)
            init_parallel_env(coordinator_address="127.0.0.1:%d",
                              num_processes=2, process_id=pid)
            assert get_world_size() == 2 and get_rank() == pid
            import jax.numpy as jnp
            from jax.experimental import multihost_utils
            x = jnp.ones((2,)) * (pid + 1)
            g = multihost_utils.process_allgather(x)
            assert g.shape == (2, 2)
            assert g.tolist() == [[1.0, 1.0], [2.0, 2.0]], g.tolist()
            print("WORKER_OK", pid)
        """) % (39911,)
        f = tmp_path / "worker.py"
        f.write_text(script)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        procs = [subprocess.Popen([sys.executable, str(f), str(i)],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, env=env)
                 for i in range(2)]
        outs = [p.communicate(timeout=110)[0].decode() for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"WORKER_OK {i}" in out


class TestCompiledOpAttribution:
    """Round-3 compiled-path profiling (reference platform/profiler.h:110):
    op lowerings run under jax.named_scope, so the COMPILED executable's
    HLO metadata — and any XProf trace of it — attributes device time back
    to IR op names (no interpret-mode proxy)."""

    def _small_train_prog(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[16], dtype="float32")
            y = layers.data("y", shape=[1], dtype="int64")
            h = layers.fc(x, 32, act="relu")
            out = layers.fc(h, 4, act="softmax")
            loss = layers.reduce_mean(layers.cross_entropy(out, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    def test_scopes_reach_hlo_metadata(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.executor import lower_block
        from paddle_tpu import profiler

        main, startup, loss = self._small_train_prog()
        exe = fluid.Executor()
        exe.run(startup)
        scope = fluid.global_scope()
        state = {n: jnp.asarray(v) for n, v in scope.items()
                 if v is not None}

        block = main.global_block()

        def step(state, feed):
            env = dict(state)
            env.update(feed)
            aux = {"rng_counter": 0, "lower_block": lower_block}
            lower_block(block, env, jax.random.PRNGKey(0), True, aux)
            # return updated params too, else XLA DCEs the whole
            # backward+sgd chain out of the lowered module
            return env[loss.name], {n: env[n] for n in state}

        rng = np.random.RandomState(0)
        feed = {"x": jnp.asarray(rng.rand(4, 16).astype("f")),
                "y": jnp.asarray(rng.randint(0, 4, (4, 1))
                                 .astype("int64"))}
        hlo = jax.jit(step).lower(state, feed).as_text(debug_info=True)
        # every op type in the program should appear as a ptop_ scope in
        # the lowered module's location metadata
        for op_type in ("mul", "relu", "softmax", "cross_entropy", "sgd"):
            assert f"ptop_{op_type}__" in hlo, \
                f"scope for {op_type} missing from lowered HLO"
        parsed = profiler.parse_op_scope(
            "jit(step)/ptop_mul__fc_0_tmp_0/dot_general")
        assert parsed == ("mul", "fc_0_tmp_0")

    def test_compiled_trace_table(self, tmp_path):
        import jax
        from paddle_tpu import profiler

        main, startup, loss = self._small_train_prog()
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(8, 16).astype("f"),
                "y": rng.randint(0, 4, (8, 1)).astype("int64")}
        exe.run(main, feed=feed, fetch_list=[loss.name])  # compile
        d = str(tmp_path / "trace")
        jax.profiler.start_trace(d)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss.name])
        jax.profiler.stop_trace()
        table, rows = profiler.compiled_op_table(d)
        # CPU traces attribute coarsely (XLA:CPU fuses aggressively); the
        # contract here is: parses without error, table renders, and any
        # attributed rows carry IR op types.  The TPU plane attributes
        # fully (see COVERAGE.md for a bench-step table).
        assert table.startswith("Event")
        for op_type, calls, total in rows:
            assert calls > 0 and total >= 0.0
