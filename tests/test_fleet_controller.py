"""Closed-loop fleet survival units: autoscaler policy schema, the
FleetController tick loop (degradation ladder, scale-up/-down,
standby pool, chaos drills on the scale path), admission-control
backpressure at the router (429 + Retry-After clamped to the caller's
deadline budget), Retry-After-hinted client retries, and the
SLO-watchdog episode re-arm contract under the controller loop.
The end-to-end autoscale drill (real replicas under a traffic replay)
lives in tests/test_bench_autoscale.py."""

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from paddle_tpu import profiler
from paddle_tpu.fault import RetryError, RetryPolicy, chaos
from paddle_tpu.fault.retry import parse_retry_after
from paddle_tpu.fleet import FleetController, FleetRouter
from paddle_tpu.fleet import controller as fc
from paddle_tpu.profiler import RuntimeMetrics
from paddle_tpu.serving import ServingClient


# ---------------------------------------------------------------------------
# helpers


class _StubReplica:
    """Minimal HTTP stand-in for a FleetReplica: scripted POST /predict
    responses plus a /stats body good enough for FleetScraper."""

    def __init__(self, script=None, gauges=None):
        # script(i) -> (status, json_body, extra_headers or None)
        self.script = script or (lambda i: (200, {"outputs": [[[1.0]]]},
                                            None))
        self.gauges = dict(gauges or {})
        self.hits = 0
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status, body, headers=None):
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._send(200, {"counters": {}, "gauges": dict(stub.gauges),
                                 "series": {}})

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                with stub._lock:
                    i = stub.hits
                    stub.hits += 1
                status, body, headers = stub.script(i)
                self._send(status, body, headers)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.addr = "127.0.0.1:%d" % self.server.server_address[1]

    def close(self):
        self.server.shutdown()
        self.server.server_close()


class FakeWatchdog:
    """Settable pressure source standing in for SLOWatchdog."""

    def __init__(self):
        self.values = []

    def set_pressure(self, ratio):
        self.values = [{"objective": "fake", "kind": "quantile",
                        "value": ratio, "threshold": 1.0,
                        "breached": ratio > 1.0}]

    def maybe_evaluate(self):
        return []

    def last_values(self):
        return [dict(v) for v in self.values]


class FakeReplica:
    """Lifecycle recorder standing in for FleetReplica in loop tests."""

    _seq = [0]

    def __init__(self):
        FakeReplica._seq[0] += 1
        self.replica_id = "fake-%d" % FakeReplica._seq[0]
        self.warmed = False
        self.enrolled = False
        self.drained = False
        self.killed = False

    def warm(self, timeout=None):
        self.warmed = True

    def enroll(self):
        self.enrolled = True

    def drain(self):
        self.drained = True


def _post(addr, body=None, headers=None, timeout=5.0):
    req = urllib.request.Request(
        "http://%s/predict" % addr,
        data=json.dumps(body or {"feeds": {"x": [[0.0]]}}).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture(autouse=True)
def _clear_chaos():
    chaos.clear()
    yield
    chaos.clear()


# ---------------------------------------------------------------------------
# policy schema


class TestPolicySchema:
    def test_example_policy_validates(self):
        assert fc.validate_policy(fc.EXAMPLE_POLICY) == []

    def test_bad_policies_are_named(self):
        def problems(**over):
            p = json.loads(json.dumps(fc.EXAMPLE_POLICY))
            for k, v in over.items():
                if isinstance(v, dict):
                    p[k].update(v)
                else:
                    p[k] = v
            return fc.validate_policy(p)

        assert problems(version=2)
        assert problems(min_replicas=5, max_replicas=2)
        assert problems(degrade={"ladder": [0.1, 0.5]})       # not 0-based
        assert problems(degrade={"ladder": [0.0, 0.8, 0.2]})  # decreasing
        assert problems(degrade={"ladder": [0.0, 1.5]})       # out of range
        assert problems(scale_up={"sustained_ticks": 0})
        assert problems(bogus_knob=1)
        assert any("bogus" in s for s in problems(bogus_knob=1))

    def test_load_policy_roundtrip_and_errors(self, tmp_path):
        path = tmp_path / "policy.json"
        path.write_text(json.dumps(fc.EXAMPLE_POLICY))
        pol = fc.load_policy(str(path))
        assert pol.max_replicas == fc.EXAMPLE_POLICY["max_replicas"]
        assert pol.source == str(path)
        assert "version" in pol.to_dict()

        path.write_text("not json {")
        with pytest.raises(ValueError, match="not JSON"):
            fc.load_policy(str(path))

    def test_policy_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(fc.POLICY_ENV, raising=False)
        assert fc.policy_from_env() is None

        good = tmp_path / "good.json"
        good.write_text(json.dumps(fc.EXAMPLE_POLICY))
        monkeypatch.setenv(fc.POLICY_ENV, str(good))
        assert fc.policy_from_env() is not None

        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"version": 1, "min_replicas": -3}))
        monkeypatch.setenv(fc.POLICY_ENV, str(bad))
        with pytest.warns(UserWarning, match="disarmed"):
            assert fc.policy_from_env() is None

    def test_defaults_fill(self):
        pol = fc.ControllerPolicy({"version": 1})
        assert pol.min_replicas >= 1
        assert pol.degrade["ladder"][0] == 0.0


# ---------------------------------------------------------------------------
# Retry-After plumbing (fault.retry units)


class TestRetryAfterHint:
    def test_parse_retry_after(self):
        assert parse_retry_after("1.5") == 1.5
        assert parse_retry_after("0") == 0.0
        assert parse_retry_after("-1") is None
        assert parse_retry_after("nan") is None
        assert parse_retry_after("soon") is None
        assert parse_retry_after("") is None
        assert parse_retry_after(None) is None

    def test_hinted_delay_caps_at_max(self):
        p = RetryPolicy(max_delay=0.5, jitter=None)
        assert p.hinted_delay(0.2) == 0.2
        assert p.hinted_delay(9.0) == 0.5

    def test_call_prefers_hint_over_backoff(self):
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] <= 2:
                e = RuntimeError("overloaded")
                e.retry_after = 0.01
                raise e
            return "ok"

        # base_delay 5s would blow the 1s budget — proves the hint won.
        p = RetryPolicy(max_attempts=5, base_delay=5.0, jitter=None,
                        retryable=(RuntimeError,))
        t0 = time.monotonic()
        assert p.call(fn) == "ok"
        assert time.monotonic() - t0 < 1.0

    def test_hint_clamped_to_deadline(self):
        def fn():
            e = RuntimeError("overloaded")
            e.retry_after = 10.0
            raise e

        p = RetryPolicy(max_attempts=50, base_delay=0.01, jitter=None,
                        deadline=0.2, retryable=(RuntimeError,))
        t0 = time.monotonic()
        with pytest.raises(RetryError):
            p.call(fn)
        # a 10s hint honored verbatim would sleep past the deadline
        assert time.monotonic() - t0 < 2.0


# ---------------------------------------------------------------------------
# router admission control


@pytest.fixture()
def admission_router():
    stub = _StubReplica()
    router = FleetRouter(replicas=[stub.addr], poll_interval=0.1)
    router.start_background()
    yield router, stub
    router.shutdown()
    stub.close()


class TestAdmissionControl:
    def test_shed_carries_retry_after_clamped_to_deadline(
            self, admission_router):
        router, _ = admission_router
        addr = "%s:%d" % router.addr
        router.set_admission(1, 1.0, retry_after_s=5.0, reason="test")

        status, body, headers = _post(addr, headers={"X-Deadline-Ms": "250"})
        assert status == 429
        assert body["error"]["type"] == "admission_shed"
        assert body["retryable"] is True
        hint = float(headers["Retry-After"])
        assert 0.0 <= hint <= 0.25

        # without a caller deadline the advisory hint passes through
        status, _, headers = _post(addr)
        assert status == 429
        assert float(headers["Retry-After"]) == pytest.approx(5.0)

    def test_fractional_shed_interleaves(self, admission_router):
        router, stub = admission_router
        addr = "%s:%d" % router.addr
        router.set_admission(1, 0.5, retry_after_s=0.01)
        statuses = [_post(addr)[0] for _ in range(8)]
        assert statuses == [200, 429] * 4      # Bresenham: admit first
        assert stub.hits == 4

        router.set_admission(0, 0.0)
        assert all(_post(addr)[0] == 200 for _ in range(4))

    def test_admission_state_in_stats(self, admission_router):
        router, _ = admission_router
        router.set_admission(2, 0.75, retry_after_s=0.5, reason="drill")
        with urllib.request.urlopen(
                "http://%s:%d/stats" % router.addr, timeout=5) as resp:
            snap = json.loads(resp.read())
        adm = snap["router"]["admission"]
        assert adm["level"] == 2
        assert adm["shed_fraction"] == 0.75
        assert adm["reason"] == "drill"

    def test_shed_counter_moves(self, admission_router):
        router, _ = admission_router
        addr = "%s:%d" % router.addr
        before = profiler.runtime_metrics.counter("fleet.admission_shed")
        router.set_admission(1, 1.0, retry_after_s=0.01)
        assert _post(addr)[0] == 429
        after = profiler.runtime_metrics.counter("fleet.admission_shed")
        assert after == before + 1

    def test_exhausted_shed_has_retry_after(self):
        # static router pointed at a dead port: every attempt fails,
        # the resulting 503 must still carry backpressure advice
        router = FleetRouter(
            replicas=["127.0.0.1:9"],
            retry=RetryPolicy(max_attempts=2, base_delay=0.01, jitter=None),
            poll_interval=0.1)
        router.start_background()
        try:
            addr = "%s:%d" % router.addr
            status, body, headers = _post(addr, timeout=10.0)
            assert status == 503
            assert body["retryable"] is True
            assert "Retry-After" in headers
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# clients honor Retry-After


class TestServingClientHonorsHint:
    def test_predict_waits_hint_not_backoff(self):
        def script(i):
            if i < 2:
                return (429, {"error": {"type": "admission_shed"},
                              "retryable": True},
                        {"Retry-After": "0.01"})
            return 200, {"outputs": [[[1.0]]]}, None

        stub = _StubReplica(script)
        try:
            client = ServingClient(
                stub.addr,
                retry=RetryPolicy(max_attempts=5, base_delay=5.0,
                                  jitter=None))
            t0 = time.monotonic()
            out = client.predict({"x": [[0.0]]})
            assert time.monotonic() - t0 < 2.0   # 5s backoff never slept
            assert out and stub.hits == 3
        finally:
            stub.close()

    def test_retry_error_history_annotates_hint(self):
        stub = _StubReplica(lambda i: (
            429, {"error": {"type": "admission_shed"}, "retryable": True},
            {"Retry-After": "0.01"}))
        try:
            client = ServingClient(
                stub.addr,
                retry=RetryPolicy(max_attempts=2, base_delay=0.01,
                                  jitter=None))
            with pytest.raises(RetryError) as ei:
                client.predict({"x": [[0.0]]})
            assert any("retry-after=0.01s" in h for h in ei.value.history)
        finally:
            stub.close()


# ---------------------------------------------------------------------------
# upstream 429 classification at the router


class Test429Classification:
    def test_failover_only_with_scrape_evidence_of_headroom(self):
        # A always sheds but advertises the most headroom (so the
        # shuffled tie-break deterministically tries it first);
        # B answers.  With scrape evidence the router retries B.
        shed = (429, {"error": {"type": "admission_shed"},
                      "retryable": True},
                {"Retry-After": "0.005"})
        a = _StubReplica(lambda i: shed,
                         gauges={"hbm.headroom_bytes": 1 << 30})
        b = _StubReplica()
        router = FleetRouter(replicas=[a.addr, b.addr], poll_interval=0.1)
        router.start_background()
        try:
            router._scraper.scrape()
            addr = "%s:%d" % router.addr
            for _ in range(4):
                assert _post(addr, timeout=10.0)[0] == 200
            assert a.hits >= 1          # A was tried, then failed over
            assert b.hits >= 4
        finally:
            router.shutdown()
            a.close()
            b.close()

    def test_passthrough_verbatim_without_alternative(self):
        a = _StubReplica(lambda i: (
            429, {"error": {"type": "admission_shed"}, "retryable": True},
            {"Retry-After": "0.123"}))
        router = FleetRouter(replicas=[a.addr], poll_interval=0.1)
        router.start_background()
        try:
            router._scraper.scrape()
            addr = "%s:%d" % router.addr
            status, _, headers = _post(addr)
            assert status == 429
            assert headers["Retry-After"] == "0.123"
        finally:
            router.shutdown()
            a.close()


# ---------------------------------------------------------------------------
# controller loop


def _policy(**over):
    p = {"version": 1, "min_replicas": 1, "max_replicas": 4,
         "standby_pool": 0, "ready_timeout_seconds": 5.0,
         "scale_up": {"pressure_ratio": 0.8, "sustained_ticks": 2,
                      "cooldown_seconds": 0.0},
         "scale_down": {"idle_rps_per_replica": 0.5, "sustained_ticks": 2,
                        "cooldown_seconds": 0.0},
         # engage_ratio 10 keeps the ladder quiet unless a test wants it
         "degrade": {"ladder": [0.0, 0.5, 1.0], "engage_ratio": 10.0,
                     "recover_ticks": 2, "retry_after_seconds": 0.25}}
    for k, v in over.items():
        if isinstance(v, dict):
            p[k].update(v)
        else:
            p[k] = v
    return p


@pytest.fixture()
def loop_rig():
    stub = _StubReplica()
    router = FleetRouter(replicas=[stub.addr], poll_interval=0.1)
    router.start_background()
    made = []

    def factory():
        r = FakeReplica()
        made.append(r)
        return r

    yield stub, router, made, factory
    router.shutdown()
    stub.close()


class TestControllerLoop:
    def test_ladder_climbs_and_recovers_with_hysteresis(self, loop_rig):
        _, router, _, factory = loop_rig
        m = RuntimeMetrics()
        wd = FakeWatchdog()
        ctl = FleetController(
            router, policy=_policy(degrade={"engage_ratio": 0.95},
                                   scale_up={"pressure_ratio": 99.0}),
            standby_factory=factory, watchdog=wd, metrics=m)

        wd.set_pressure(1.2)
        ctl.tick()
        assert ctl.state()["degrade_level"] == 1
        assert router.admission_state()["shed_fraction"] == 0.5
        ctl.tick()
        assert ctl.state()["degrade_level"] == 2
        assert router.admission_state()["shed_fraction"] == 1.0
        ctl.tick()
        assert ctl.state()["degrade_level"] == 2      # top rung holds

        wd.set_pressure(0.2)
        ctl.tick()
        assert ctl.state()["degrade_level"] == 2      # 1 healthy tick
        ctl.tick()
        assert ctl.state()["degrade_level"] == 1      # hysteresis step
        ctl.tick()
        ctl.tick()
        assert ctl.state()["degrade_level"] == 0
        assert router.admission_state()["shed_fraction"] == 0.0
        assert m.counter("controller.degrade_steps") >= 4
        ctl.shutdown()

    def test_scale_up_after_sustained_pressure(self, loop_rig):
        _, router, made, factory = loop_rig
        m = RuntimeMetrics()
        wd = FakeWatchdog()
        ctl = FleetController(router, policy=_policy(standby_pool=1),
                              standby_factory=factory, watchdog=wd,
                              metrics=m)
        assert ctl.prewarm() == 1
        assert made[0].warmed and not made[0].enrolled

        wd.set_pressure(0.9)
        ctl.tick()
        assert m.counter("controller.scale_ups") == 0   # 1 of 2 ticks
        ctl.tick()
        assert m.counter("controller.scale_ups") == 1
        assert made[0].enrolled                  # standby promoted, not cold
        assert ctl.state()["owned"] == [made[0].replica_id]
        ctl.shutdown()

    def test_scale_up_capped_at_max_replicas(self, loop_rig):
        _, router, _, factory = loop_rig
        m = RuntimeMetrics()
        wd = FakeWatchdog()
        ctl = FleetController(router, policy=_policy(max_replicas=1),
                              standby_factory=factory, watchdog=wd,
                              metrics=m)
        wd.set_pressure(5.0)
        for _ in range(4):
            ctl.tick()
        assert m.counter("controller.scale_ups") == 0
        ctl.shutdown()

    def test_scale_stall_failpoint_loses_one_promotion(self, loop_rig):
        _, router, made, factory = loop_rig
        m = RuntimeMetrics()
        ctl = FleetController(router, policy=_policy(),
                              standby_factory=factory,
                              watchdog=FakeWatchdog(), metrics=m)
        chaos.inject("fleet.scale.stall", error=True, times=1)
        assert ctl.scale_up(reason="drill") is None
        assert m.counter("controller.scale_stalls") == 1
        assert ctl.scale_up(reason="drill") is not None
        assert made[-1].enrolled
        ctl.shutdown()

    def test_standby_fail_failpoint(self, loop_rig):
        _, router, made, factory = loop_rig
        m = RuntimeMetrics()
        ctl = FleetController(router, policy=_policy(standby_pool=1),
                              standby_factory=factory,
                              watchdog=FakeWatchdog(), metrics=m)
        chaos.inject("fleet.standby.fail", error=True, times=1)
        assert ctl.prewarm(raise_on_failure=False) == 0
        assert m.counter("controller.standby_warm_failures") == 1
        with pytest.raises(RuntimeError):
            chaos.inject("fleet.standby.fail", error=True, times=1)
            ctl.prewarm()
        chaos.clear()
        assert ctl.prewarm() == 1
        assert made[-1].warmed
        ctl.shutdown()

    def test_scale_down_drains_idle_owned_replica(self):
        a, b = _StubReplica(), _StubReplica()
        router = FleetRouter(replicas=[a.addr, b.addr], poll_interval=0.1)
        router.start_background()
        try:
            m = RuntimeMetrics()
            wd = FakeWatchdog()
            wd.set_pressure(0.0)
            ctl = FleetController(
                router,
                policy=_policy(scale_down={"sustained_ticks": 2},
                               scale_up={"pressure_ratio": 99.0}),
                standby_factory=FakeReplica, watchdog=wd, metrics=m)
            owned = ctl.scale_up(reason="test")
            assert owned is not None
            # tick 1 seeds the rate window; later ticks see rps 0.0
            for _ in range(4):
                ctl.tick()
                time.sleep(0.02)
            assert owned.drained
            assert m.counter("controller.scale_downs") == 1
            assert ctl.state()["owned"] == []
            ctl.shutdown()
        finally:
            router.shutdown()
            a.close()
            b.close()

    def test_never_drains_while_degraded(self):
        a, b = _StubReplica(), _StubReplica()
        router = FleetRouter(replicas=[a.addr, b.addr], poll_interval=0.1)
        router.start_background()
        try:
            m = RuntimeMetrics()
            wd = FakeWatchdog()
            ctl = FleetController(
                router,
                policy=_policy(scale_down={"sustained_ticks": 1},
                               scale_up={"pressure_ratio": 99.0},
                               degrade={"engage_ratio": 0.9,
                                        "recover_ticks": 1000}),
                standby_factory=FakeReplica, watchdog=wd, metrics=m)
            owned = ctl.scale_up(reason="test")
            wd.set_pressure(1.5)
            ctl.tick()                       # engages the ladder
            assert ctl.state()["degrade_level"] >= 1
            wd.set_pressure(0.0)             # idle by rps, but degraded
            for _ in range(4):
                ctl.tick()
                time.sleep(0.02)
            assert not owned.drained
            assert m.counter("controller.scale_downs") == 0
            ctl.shutdown()
        finally:
            router.shutdown()
            a.close()
            b.close()

    def test_shutdown_drains_standbys_and_owned(self, loop_rig):
        _, router, made, factory = loop_rig
        ctl = FleetController(router, policy=_policy(standby_pool=1),
                              standby_factory=factory,
                              watchdog=FakeWatchdog(),
                              metrics=RuntimeMetrics())
        ctl.prewarm()
        ctl.scale_up(reason="test")
        ctl.shutdown(drain_owned=True)
        assert all(r.drained for r in made)

    def test_state_schema(self, loop_rig):
        _, router, _, factory = loop_rig
        ctl = FleetController(router, policy=_policy(),
                              standby_factory=factory,
                              watchdog=FakeWatchdog(),
                              metrics=RuntimeMetrics())
        ctl.tick()
        st = ctl.state()
        for key in ("policy", "degrade_level", "admission", "pressure",
                    "standbys", "owned", "live_replicas"):
            assert key in st
        ctl.shutdown()


# ---------------------------------------------------------------------------
# satellite: SLO watchdog episode re-arm under the controller loop


class TestEpisodeRearm:
    def test_one_postmortem_per_episode_no_duplicate_scaling(
            self, tmp_path, monkeypatch):
        from paddle_tpu.obs.slo import SLOWatchdog

        monkeypatch.setenv("PADDLE_TPU_POSTMORTEM", str(tmp_path))
        stub = _StubReplica()
        router = FleetRouter(replicas=[stub.addr], poll_interval=0.1)
        router.start_background()
        try:
            m = RuntimeMetrics()
            wd = SLOWatchdog(
                {"version": 1, "interval_seconds": 0.001,
                 "sustained_breaches": 2,
                 "objectives": [{"name": "latency", "kind": "quantile",
                                 "series": "s", "quantile": "p99",
                                 "max": 0.1}]},
                metrics=m)
            ctl = FleetController(
                router,
                policy=_policy(
                    scale_up={"pressure_ratio": 0.8, "sustained_ticks": 1,
                              "cooldown_seconds": 3600.0},
                    degrade={"engage_ratio": 0.95, "recover_ticks": 1}),
                standby_factory=FakeReplica, watchdog=wd, metrics=m)

            def tick(n):
                for _ in range(n):
                    time.sleep(0.01)
                    ctl.tick()

            for _ in range(50):          # p99 well above 0.1s threshold
                m.observe("s", 1.0)
            tick(2)                      # 2 consecutive breaches -> dump
            assert m.counter("slo.postmortems") == 1
            assert m.counter("controller.scale_ups") == 1
            assert ctl.state()["degrade_level"] >= 1

            for _ in range(3000):        # recovery floods the window
                m.observe("s", 0.001)
            tick(3)
            assert m.gauge("slo.breaching") == 0
            assert ctl.state()["degrade_level"] == 0

            for _ in range(3000):        # second episode
                m.observe("s", 1.0)
            tick(2)
            # re-armed: exactly one more post-mortem; cooldown means the
            # controller does NOT fire a duplicate scale action
            assert m.counter("slo.postmortems") == 2
            assert m.counter("controller.scale_ups") == 1
            assert os.path.exists(
                os.path.join(str(tmp_path),
                             "postmortem-%d.json" % os.getpid()))
            ctl.shutdown()
        finally:
            router.shutdown()
            stub.close()
